"""Benchmark: TPU-engine checking throughput vs the host BFS engine.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}``.

The north-star metric (BASELINE.json) is states/sec on the paxos
workload with property-violation parity vs ``spawn_bfs``. This harness
checks the same model on both engines, asserts identical unique-state
counts and discovery sets (the parity part — zero missed violations),
and reports the TPU engine's steady-state throughput: the slope of
(time, states) across waves excluding the first wave, which carries jit
compilation (the reference's analog metric is the ``sec=`` line of
``Checker::report``, `checker.rs:229-232`).

``vs_baseline`` is the ratio of the TPU engine's steady-state rate to
the host engine's whole-run rate on the same machine and model.

Env knobs: ``BENCH_WORKLOAD`` (paxos | 2pc), ``BENCH_CLIENTS`` (paxos
client count, default 2), ``BENCH_2PC_RMS`` (default 7).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "examples"))


def _steady_rate(tpu) -> float:
    # wave_log[0] is the run start; wave_log[1] ends the first
    # (compile-bearing) wave. Steady state is the slope over the rest.
    log = tpu.wave_log
    if len(log) >= 3:
        (t1, s1), (t2, s2) = log[1], log[-1]
        return (s2 - s1) / max(t2 - t1, 1e-9)
    return (log[-1][1] - log[0][1]) / max(log[-1][0] - log[0][0], 1e-9)


def main() -> None:
    workload = os.environ.get("BENCH_WORKLOAD", "paxos")
    if workload == "paxos":
        from paxos import PaxosModelCfg

        clients = int(os.environ.get("BENCH_CLIENTS", "2"))
        model = PaxosModelCfg(clients, 3).into_model()
        name = f"paxos check {clients}"
        batch = 512
    else:
        from two_phase_commit import TwoPhaseSys

        rm_count = int(os.environ.get("BENCH_2PC_RMS", "7"))
        model = TwoPhaseSys(rm_count)
        name = f"2pc check {rm_count}"
        batch = 2048

    # Host baseline: multithreaded BFS (the reference benches with all
    # cores, bench.sh:29-32; same per-state hot loop as its DFS).
    t0 = time.monotonic()
    host = model.checker().threads(os.cpu_count() or 1).spawn_bfs().join()
    host_sec = time.monotonic() - t0
    host_rate = host.state_count() / max(host_sec, 1e-9)

    # TPU engine on the same model. The table is pre-sized so mid-run
    # growth never recompiles the wave inside the measured window.
    tpu = (model.checker()
           .spawn_tpu_bfs(batch_size=batch, table_capacity=1 << 22).join())

    # Parity gates: zero missed violations, identical state space.
    assert tpu.unique_state_count() == host.unique_state_count(), (
        tpu.unique_state_count(), host.unique_state_count())
    assert set(tpu.discoveries()) == set(host.discoveries())

    tpu_rate = _steady_rate(tpu)
    print(json.dumps({
        "metric": f"tpu_bfs states/sec, {name} "
                  f"({tpu.state_count()} states, parity vs spawn_bfs OK)",
        "value": round(tpu_rate, 1),
        "unit": "states/sec",
        "vs_baseline": round(tpu_rate / max(host_rate, 1e-9), 3),
    }))


if __name__ == "__main__":
    main()

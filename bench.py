"""Benchmark: TPU-engine checking throughput vs the host BFS engine.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}`` —
ALWAYS, even on failure (with an ``"error"`` field), so the driver's
``BENCH_r{N}.json`` records what happened.

The north-star metric (BASELINE.json) is states/sec on ``paxos check 3``
with property-violation parity vs ``spawn_bfs``. This harness:

1. Probes JAX backend availability in a *subprocess* with a timeout and
   retries — on this image the failure mode of the tunneled TPU plugin
   ("axon") is a hang or an ``UNAVAILABLE`` RuntimeError inside
   ``jax.devices()`` (see BENCH_r01.json), so probing in-process would
   wedge the harness. On probe failure it forces the CPU backend via
   ``jax.config.update`` (the env var alone is too late — the image's
   sitecustomize imports jax at interpreter startup) and reports the
   error.
2. Runs the host baseline: multithreaded ``spawn_bfs`` (the reference
   benches with all cores, `bench.sh:29-32`) on the same model.
3. Runs the TPU engine and reports its steady-state throughput: the
   slope of (time, states) across waves excluding the first wave, which
   carries jit compilation (the reference's analog metric is the
   ``sec=`` line of ``Checker::report``, `checker.rs:229-232`).
4. Parity gates: identical unique-state counts and discovery sets
   (zero missed violations).

``vs_baseline`` is the ratio of the TPU engine's steady-state rate to
the host engine's whole-run rate on the same machine and model.

Env knobs:
  BENCH_WORKLOAD       paxos | 2pc            (default paxos)
  BENCH_CLIENTS        paxos client count     (default 3 — the north star)
  BENCH_2PC_RMS        2pc RM count           (default 7)
  BENCH_INIT_TIMEOUT   backend probe timeout  (default 240 s)
  BENCH_INIT_RETRIES   backend probe retries  (default 2)
  BENCH_PLATFORM       skip probing, force this platform (e.g. cpu)
"""

import json
import os
import subprocess
import sys
import time

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "examples"))


def _probe_backend():
    """Returns (platform, error). Probes ``jax.devices()`` in a subprocess
    so a hung TPU tunnel can be timed out and retried; see module doc."""
    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        _force_platform(forced)
        return forced, None
    timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
    retries = int(os.environ.get("BENCH_INIT_RETRIES", "2"))
    probe = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    last_err = "backend probe never ran"
    for attempt in range(1 + retries):
        if attempt:
            time.sleep(min(15.0, 5.0 * attempt))
        try:
            out = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            last_err = f"backend init timed out after {timeout:.0f}s"
            continue
        if out.returncode == 0 and "PLATFORM=" in out.stdout:
            return out.stdout.rsplit("PLATFORM=", 1)[1].strip(), None
        tail = (out.stderr or out.stdout or "").strip().splitlines()
        last_err = tail[-1][:300] if tail else f"probe rc={out.returncode}"
    return None, last_err


def _force_platform(platform: str):
    import jax

    os.environ["JAX_PLATFORMS"] = platform
    try:
        # The env var alone is too late (jax imported at startup by the
        # image's sitecustomize); the config update works until a backend
        # has been initialized.
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass  # backends already initialized; use whatever works


def _steady_rate(tpu) -> float:
    # wave_log[0] is the run start; wave_log[1] ends the first
    # (compile-bearing) wave. Steady state is the slope over the rest.
    log = tpu.wave_log
    if len(log) >= 3:
        (t1, s1), (t2, s2) = log[1], log[-1]
        return (s2 - s1) / max(t2 - t1, 1e-9)
    return (log[-1][1] - log[0][1]) / max(log[-1][0] - log[0][0], 1e-9)


def _build_model():
    workload = os.environ.get("BENCH_WORKLOAD", "paxos")
    if workload == "paxos":
        from paxos import PaxosModelCfg

        clients = int(os.environ.get("BENCH_CLIENTS", "3"))
        return (PaxosModelCfg(clients, 3).into_model(),
                f"paxos check {clients}", 1024)
    from two_phase_commit import TwoPhaseSys

    rm_count = int(os.environ.get("BENCH_2PC_RMS", "7"))
    return TwoPhaseSys(rm_count), f"2pc check {rm_count}", 2048


def main() -> None:
    platform, probe_err = _probe_backend()
    result = {"metric": "tpu_bfs states/sec", "value": 0.0,
              "unit": "states/sec", "vs_baseline": 0.0}
    if platform is None:
        _force_platform("cpu")
        platform = "cpu"
        result["error"] = f"tpu backend unavailable ({probe_err}); ran on cpu"

    try:
        model, name, batch = _build_model()

        # Host baseline: multithreaded BFS (same per-state hot loop as the
        # reference's all-cores DFS bench).
        t0 = time.monotonic()
        host = (model.checker()
                .threads(os.cpu_count() or 1).spawn_bfs().join())
        host_sec = time.monotonic() - t0
        host_rate = host.state_count() / max(host_sec, 1e-9)

        # TPU engine on the same model. The table is pre-sized so mid-run
        # growth never recompiles the wave inside the measured window.
        tpu = (model.checker()
               .spawn_tpu_bfs(batch_size=batch,
                              table_capacity=1 << 22).join())

        # Parity gates: zero missed violations, identical state space.
        assert tpu.unique_state_count() == host.unique_state_count(), (
            "unique-state mismatch: tpu=%d host=%d"
            % (tpu.unique_state_count(), host.unique_state_count()))
        assert set(tpu.discoveries()) == set(host.discoveries()), (
            "discovery mismatch: tpu=%s host=%s"
            % (sorted(tpu.discoveries()), sorted(host.discoveries())))

        tpu_rate = _steady_rate(tpu)
        result.update({
            "metric": f"tpu_bfs states/sec on {platform}, {name} "
                      f"({tpu.state_count()} states, "
                      "parity vs spawn_bfs OK)",
            "value": round(tpu_rate, 1),
            "unit": "states/sec",
            "vs_baseline": round(tpu_rate / max(host_rate, 1e-9), 3),
            "host_states_per_sec": round(host_rate, 1),
            "host_sec": round(host_sec, 2),
            "unique_states": host.unique_state_count(),
        })
    except Exception as e:  # noqa: BLE001 — always emit the JSON line
        prior = result.get("error")
        result["error"] = (f"{prior}; " if prior else "") + \
            f"{type(e).__name__}: {e}"
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Benchmark: TPU-engine checking throughput vs the host BFS engine.

Prints ONE JSON line: ``{"metric", "value", "unit", "vs_baseline"}`` —
ALWAYS, even on failure or timeout. A watchdog *thread* (armed at
``BENCH_BUDGET_S`` minus a grace margin) emits the line with whatever has
been measured so far and exits 0, so the driver's ``BENCH_r{N}.json``
records a number even if a stage hangs (r01 failed on backend init, r02 on
an external timeout — this harness is built so neither can zero it again).

The north-star metric (BASELINE.json) is states/sec on ``paxos check 3``
with property-violation parity vs ``spawn_bfs``. Stages, cheapest first,
each updating the result line as it lands:

1. No separate backend probe: the device-stage *subprocess*
   (``tools/device_session.py --bench-mode``) performs the one backend
   init AND the workload — the tunnel's field-observed wedge mode
   (2026-07-31) granted one init and hung the next, so probe-then-work
   burns the window. The child is watched live; if its ``init`` event
   doesn't arrive within BENCH_CHILD_INIT_GRACE the tunnel is wedged
   and the bench falls back to CPU in-process. On CPU the cheap parity
   gate runs before the headline; on an accelerator attempt the ORDER
   IS REVERSED (headline first — tunnel-side compiles are slow and the
   budget must buy the north-star number), with the metric string
   tracking the gate's pending/ok/failed status honestly.
2. Parity gate + first rate sample on a FULL enumeration small enough to
   always finish: ``2pc check 5`` (8,832 states) — identical unique-state
   counts and discovery sets vs multithreaded ``spawn_bfs``
   (zero missed violations), plus a steady-state device rate. The
   device child runs this workload on ITS backend (before the headline
   on cpu, after it on an accelerator) and streams the counts back, so
   the gate covers the backend that produced the headline.
3. Host baseline on the north-star workload (``paxos check 3``), bounded
   by ``target_state_count`` so it yields a *rate* without full
   enumeration (the reference's analog metric is the ``sec=`` line of
   ``Checker::report``, `checker.rs:229-232`; its bench runs each example
   with all cores, `bench.sh:29-32`).
4. Device engine on the same bounded workload; the headline value is its
   steady-state throughput: the slope of (time, states) across waves
   excluding the first (compile-bearing) wave.

``vs_baseline`` is the ratio of the device steady-state rate to the
**compiled** host baseline on the same machine and workload: the native
C++ multithreaded BFS (``native/host_bfs.cc``, the reference's
`bfs.rs:17-342` engine design — the honest analog of the reference's
multithreaded Rust checker), run to completion on the full state space.
``vs_python_host`` reports the ratio against the Python ``spawn_bfs``
for continuity with rounds 1-3; when the native extension is
unavailable, ``vs_baseline`` falls back to that Python rate and the
metric string says so. The caps differ
by design (host: ``BENCH_HOST_CAP`` states for a quick rate sample;
device: ``BENCH_TPU_CAP`` so steady-state waves dominate) — both engines
expand the same BFS prefix of the same state space, and each engine's
rate is flat across that range, but the ratio is a throughput comparison,
not a same-work wall-clock race.

Env knobs:
  BENCH_BUDGET_S       total wall budget, watchdog fires ~20s before
                       (default 450)
  BENCH_WORKLOAD       paxos | 2pc            (default paxos)
  BENCH_CLIENTS        paxos client count     (default 3 — the north star)
  BENCH_LIVENESS       1 adds the "eventually chosen" Eventually property
                       (liveness via ebits)
  BENCH_SYMMETRY       1 dedups by the client-symmetry representative
                       (with BENCH_CLIENTS=4 + BENCH_LIVENESS=1 this is
                       BASELINE.json config 5; the native baseline
                       switches to the symmetry-capable compiled DFS)
  BENCH_WAVE_KERNEL    1 runs the single-kernel wave megakernel
                       (expand->fingerprint->dedup->insert fused into
                       one pallas_call per wave; interpret mode on
                       CPU), 0 forces the XLA ladder; unset follows
                       the engine default. RESULT records the active
                       kernel_path + waves_per_round_trip either way.
  BENCH_TABLE_IMPL     visited-table impl: xla (default) | pallas
                       (the VMEM-staged probe kernel, pallas_table.py —
                       the on-TPU A/B of the round-5 plan)
  BENCH_WAVE_MATMUL    1 compiles the headline model's successor
                       generation to matmul form (tpu/matmul_wave.py;
                       irregular models gate to the step path and the
                       RESULT wave_matmul block says why), 0 forces the
                       vmapped step; unset follows the engine default
  BENCH_MATMUL_AB      1 adds the matmul-wave A/B stage: interleaved
                       knob-on/knob-off runs of a regular 2pc workload
                       GATED on counts/discoveries/checkpoint-bytes
                       identity, with per-arm expand wall clock and
                       kernel_path attribution under RESULT["matmul_ab"]
  BENCH_PROF           1 arms the continuous wave profiler
                       (STpu_PROF=1) for every engine the bench spawns
                       — XLA cost-model capture per compiled program
                       plus sampled roofline timings. The headline
                       engine's final per-program gauges are hoisted
                       under RESULT["prof"] (prof.* keys, which
                       bench_compare diffs key by key and tolerates
                       one-sided). BENCH_PROF_SAMPLE overrides the
                       sampling cadence (default 32)
  BENCH_RESULT_OUT     path: also write the RESULT json to this file
                       (the driver's BENCH_r{N}.json) at emit time
  BENCH_COMPARE_BASELINE  path to the previous round's BENCH json: at
                       emit time run tools/bench_compare.py against
                       BENCH_RESULT_OUT with --max-regress
                       BENCH_MAX_REGRESS (default 20) and fold the
                       gate's status into the exit code
  BENCH_2PC_RMS        2pc RM count           (default 7)
  BENCH_HOST_CAP       host-baseline target_state_count (default 60000)
  BENCH_TPU_CAP        device-run target_state_count    (default 400000)
  BENCH_PARITY_RMS     2pc parity-gate RM count         (default 5)
  BENCH_CHILD_INIT_GRACE  seconds to wait for the device child's
                       backend-init event before declaring the tunnel
                       wedged (default 75); a pre-init wedge/crash gets
                       BENCH_CHILD_PREINIT_RETRIES (default 1) bounded
                       respawns
  BENCH_ELASTIC_WORKERS  >0 routes the device stage through the elastic
                       multi-worker runtime (resilience/elastic.py)
                       with that many workers; the headline rate then
                       measures the coordinated sharded wave end to end
  BENCH_ELASTIC_PARTITIONS  logical shard count (default 8)
  BENCH_ELASTIC_BATCH  per-worker rows per coordinated round (default
                       512)
  BENCH_ELASTIC_TRANSPORT  thread (default) | process — process spawns
                       one OS process per worker (the multi-host
                       rehearsal; slower start on CPU boxes)
  BENCH_ELASTIC_KILL_ROUND  >0 kills the last worker just before that
                       coordinated round (migration drill: the RESULT
                       elastic block records the worker_lost ->
                       migrate_done cycle and the rate shows the dip)
  BENCH_ELASTIC_JOIN_ROUND  >0 admits one extra worker at that round
                       (rebalance drill)
  BENCH_SERVICE_JOBS   >0 adds the checking-as-a-service stage: submits
                       N concurrent small jobs to an in-process
                       JobService and reports jobs/s + the shared
                       wave-program cache hit ratio + cold-vs-warm job
                       latency under RESULT["service"]
  BENCH_SERVICE_WORKERS  service worker-pool width (default 2)
  BENCH_SERVICE_MODEL  corpus model the jobs check (default twopc)
  BENCH_SOAK_JOBS      >0 adds the sustained-traffic soak stage: ONE
                       arrival schedule of N same-shape jobs replayed
                       against a wave-multiplexed service and a
                       one-engine-each service (A/B on the same box);
                       aggregate jobs/s + p50/p99 job latency and the
                       per-job counter cross-check land under
                       RESULT["soak"]
  BENCH_SOAK_ARRIVAL   soak inter-arrival gap, seconds (default 0.05)
  BENCH_SOAK_MIX       preempt (default): inject one preempt->resume
                       into each soak arm so the latency tail includes
                       a drained-and-resumed job; steady: none
  BENCH_SOAK_TRACE     gen (or a tools/traffic_gen trace path) adds the
                       open-loop overload A/B: the SAME pre-sampled
                       arrival schedule replayed against an overload-
                       controller-armed service vs the disarmed
                       baseline; goodput, interactive deadline hit
                       rate/p99, sheds-by-reason and park/resume
                       counts land under RESULT["soak_trace"]
  BENCH_SOAK_TRACE_SEED / _DURATION / _RATE
                       trace generation knobs for gen (default 0/6s/
                       4Hz); _QUEUE bounds both arms' job queue
                       (default 16) so the disarmed arm's overflow
                       mode is reachable inside the bench budget;
                       _SLO overrides the STpu_SLO spec BOTH arms
                       observe under (the ON arm's burn signal;
                       default job_latency=1.0,queue_wait=0.3,
                       window=10)
  BENCH_PLATFORM       skip probing, force this platform (e.g. cpu)
  BENCH_TPU_BATCH      override the device batch size (the adaptive
                       scheduler's base bucket)
  BENCH_TPU_MAX_BATCH  top of the adaptive bucket ladder (default
                       16x the batch; the engine re-picks the dispatch
                       width per dispatch from the live frontier)
  BENCH_FORCE_ACCEL_ORDER  1 forces the accelerator stage order on CPU
                       (used to rehearse the TPU path end to end)
  BENCH_FORCE_SUBPROCESS   1 routes the device stage through the
                       tools/device_session.py subprocess even on CPU
                       (rehearses the TPU-side isolation path)
  BENCH_KEEP_SESSIONS  1 skips the startup pkill of stray measurement
                       sessions (for rehearsals run alongside the
                       background attempt loop)
  STpu_TRACE           path: stream the round's run telemetry (engine
                       wave events + bench stage spans; the device
                       child inherits the knob) as JSONL — lint with
                       tools/trace_lint.py, open in Perfetto via
                       tools/trace_export.py

On a non-CPU platform the device headline runs in a KILLABLE subprocess
(``tools/device_session.py --bench-mode``) and the main process stays on
the CPU backend: the tunnel's observed wedge mode grants one backend
init then hangs the next, and an in-process init hang is unrecoverable.
"""

import json
import os
import queue
import subprocess
import sys
import threading
import time

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "examples"))

_T0 = time.monotonic()
_BUDGET = float(os.environ.get("BENCH_BUDGET_S", "450"))
_EMITTED = threading.Event()

# The watchdog reads/replaces whole values; stages replace whole keys —
# no partial-update races worth locking over.
RESULT = {"metric": "tpu_bfs states/sec", "value": 0.0,
          "unit": "states/sec", "vs_baseline": 0.0}

#: parity-gate status; the single source for the metric's parity clause
#: and the machine-readable RESULT["parity_failed"] flag.
_PARITY = {"status": "pending"}
_HEADLINE = {}  # "recompose": closure re-rendering the headline metric


def _parity_clause() -> str:
    # When the headline ran on an accelerator, the gate ran on the CPU
    # backend (the main process never touches the tunnel) — say so.
    backend = (" (cpu backend)"
               if RESULT.get("parity_backend") == "cpu"
               and RESULT.get("platform") not in (None, "cpu") else "")
    return {"pending": "parity gate pending",
            "ok": f"parity gated on 2pc full enumeration{backend}",
            "failed": "PARITY GATE FAILED — see error"}[_PARITY["status"]]


def _remaining() -> float:
    return _BUDGET - (time.monotonic() - _T0)


#: the live device-stage child, if any — killed before ANY exit path so
#: a watchdog-triggered os._exit can never orphan a process holding the
#: TPU (the driver's next step would find the chip busy).
_CHILD = {"proc": None}


def _emit_and_exit(code: int = 0) -> None:
    proc = _CHILD["proc"]
    if proc is not None and proc.poll() is None:
        proc.kill()
    if not _EMITTED.is_set():
        _EMITTED.set()
        RESULT["bench_sec"] = round(time.monotonic() - _T0, 1)
        line = json.dumps(RESULT)
        print(line, flush=True)
        # Round-19 exit path: persist the RESULT dict and gate it
        # against the previous round's headline. Both steps are
        # best-effort — the printed line above is the contract; a
        # filesystem or comparison error must never eat it.
        out = os.environ.get("BENCH_RESULT_OUT")
        if out:
            try:
                with open(out, "w", encoding="utf-8") as f:
                    f.write(line + "\n")
            except OSError as e:
                print(f"BENCH_RESULT_OUT write failed: {e}",
                      file=sys.stderr, flush=True)
        baseline = os.environ.get("BENCH_COMPARE_BASELINE")
        if out and baseline:
            try:
                sys.path.insert(0, os.path.join(_ROOT, "tools"))
                from bench_compare import main as compare
                rc = compare([baseline, out, "--max-regress",
                              os.environ.get("BENCH_MAX_REGRESS", "20")])
                code = max(code, rc)
            except Exception as e:  # noqa: BLE001 — the gate is advisory
                print(f"bench_compare gate errored: {e}",
                      file=sys.stderr, flush=True)
    os._exit(code)


def _watchdog() -> None:
    grace = min(20.0, _BUDGET * 0.1)
    while True:
        left = _remaining() - grace
        if left <= 0:
            RESULT["error"] = (RESULT.get("error", "") +
                               "; watchdog fired at budget").lstrip("; ")
            _emit_and_exit(0)
        time.sleep(min(left, 5.0))


def _force_platform(platform: str):
    import jax

    os.environ["JAX_PLATFORMS"] = platform
    try:
        # The env var alone is too late (jax imported at startup by the
        # image's sitecustomize); the config update works until a backend
        # has been initialized.
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass  # backends already initialized; use whatever works


def _steady_rate(tpu) -> float:
    # Preferred: the engines' dispatch_log + compile_log. Compiles run
    # on the host thread between stats reads (AOT — see engine._aot),
    # so each compile's duration lies inside exactly one dispatch
    # interval; steady state is total states over total wall MINUS the
    # compile time inside the covered span (the adaptive scheduler's
    # bigger buckets compile mid-run, which the plain first-wave
    # exclusion below would mis-charge to throughput). Lazily-compiled
    # paths (no AOT) instead flag their interval via ``compiled`` and
    # are dropped whole.
    log = list(tpu.wave_log)
    dlog = list(getattr(tpu, "dispatch_log", ()) or ())
    clog = list(getattr(tpu, "compile_log", ()) or ())
    if dlog and log:
        # Global span: under pipelined dispatch a launch's execution can
        # complete inside an earlier interval, so per-interval slopes
        # misattribute; total-states over total-wall-minus-compiles is
        # robust to that (everything happened inside the span).
        t0 = log[0][0]
        t_last = dlog[-1]["t"]
        span_t = t_last - t0
        span_s = 0.0
        t_prev, s_prev = log[0]
        dropped = []  # intervals removed whole (lazy compiles inside)
        for e in dlog:
            if e.get("compiled"):
                # Lazily-compiled interval (no AOT timing): drop whole.
                span_t -= e["t"] - t_prev
                dropped.append((t_prev, e["t"]))
            else:
                span_s += e["states"] - s_prev
            t_prev, s_prev = e["t"], e["states"]
        for t_end, dur in clog:
            if t0 < t_end <= t_last and not any(
                    lo < t_end <= hi for lo, hi in dropped):
                span_t -= dur
        if span_t > 0 and span_s > 0:
            return span_s / span_t
    # Fallback: wave_log[0] is the run start; wave_log[1] ends the first
    # (compile-bearing) wave. Steady state is the slope over the rest.
    if not log:
        return 0.0
    if len(log) >= 3:
        (t1, s1), (t2, s2) = log[1], log[-1]
        return (s2 - s1) / max(t2 - t1, 1e-9)
    return (log[-1][1] - log[0][1]) / max(log[-1][0] - log[0][0], 1e-9)


def _host_bfs(model, cap=None):
    b = model.checker().threads(os.cpu_count() or 1)
    if cap:
        b = b.target_state_count(cap)
    t0 = time.monotonic()
    checker = b.spawn_bfs().join()
    sec = time.monotonic() - t0
    return checker, checker.state_count() / max(sec, 1e-9), sec


def _native_bfs_rate(model):
    """The honest baseline: the compiled multithreaded host BFS
    (native/host_bfs.cc — the reference's `bfs.rs:17-342` engine design
    in C++), run to completion or to BENCH_NATIVE_CAP generated states,
    whichever comes first (the rate is flat across that range; the
    `native_host_complete` field records which it was). Returns
    states/sec or None when the extension/model form is unavailable."""
    from stateright_tpu.native.host_bfs import HOSTBFS_AVAILABLE

    if not HOSTBFS_AVAILABLE:
        return None
    dm = model.device_model()
    if dm.native_form() is None:
        return None
    cap = int(os.environ.get("BENCH_NATIVE_CAP", "3000000"))
    b = model.checker().threads(os.cpu_count() or 1).target_state_count(cap)
    if os.environ.get("BENCH_SYMMETRY") == "1":
        # Keep the baseline apples-to-apples under config 5: the native
        # DFS is the symmetry-capable compiled engine.
        checker = b.symmetry().spawn_native_dfs(dm).join()
    else:
        checker = b.spawn_native_bfs(dm).join()
    rate = checker.state_count() / max(checker.seconds(), 1e-9)
    RESULT["native_host_states"] = checker.state_count()
    RESULT["native_host_sec"] = round(checker.seconds(), 3)
    RESULT["native_host_complete"] = checker.is_done()
    return rate


def _return_model(model):
    """Module-level identity factory: picklable for the elastic
    runtime's process-transport workers (each worker rebuilds its own
    DeviceModel from the model object)."""
    return model


def _elastic_bfs(model, workers, cap=None, deadline=None,
                 symmetry=False, checkpoint_path=None, resume_from=None,
                 chaos=True):
    """The device stage through the elastic multi-worker runtime
    (BENCH_ELASTIC_WORKERS): same (checker-like, rate, finished)
    contract as ``_tpu_bfs``, with the membership lifecycle recorded
    under RESULT["elastic"]. The kill/join drill knobs apply only with
    ``chaos`` (the headline run — the parity gate's elastic run stays
    unfaulted so it gates the wave, not the recovery). A chaos drill
    needs per-shard generations to migrate from, so a missing
    ``checkpoint_path`` gets a per-run scratch path, removed after."""
    import glob
    import tempfile
    from functools import partial

    from stateright_tpu.resilience.elastic import ElasticChecker

    kill_round = int(os.environ.get("BENCH_ELASTIC_KILL_ROUND", "0")) \
        if chaos else 0
    join_round = int(os.environ.get("BENCH_ELASTIC_JOIN_ROUND", "0")) \
        if chaos else 0
    own_ckpt = checkpoint_path is None and (kill_round or join_round)
    if own_ckpt:
        fd, checkpoint_path = tempfile.mkstemp(
            prefix="stpu_bench_elastic_", suffix=".npz")
        os.close(fd)
        os.unlink(checkpoint_path)
    try:
        run = ElasticChecker(
            partial(_return_model, model),
            workers=workers,
            n_partitions=int(os.environ.get("BENCH_ELASTIC_PARTITIONS",
                                            "8")),
            batch_rows=int(os.environ.get("BENCH_ELASTIC_BATCH", "512")),
            transport=os.environ.get("BENCH_ELASTIC_TRANSPORT",
                                     "thread"),
            checkpoint_path=checkpoint_path, resume_from=resume_from,
            symmetry=symmetry, target_state_count=cap,
            kill_at=({kill_round: f"w{workers - 1}"}
                     if kill_round else None),
            join_at=({join_round: f"w{workers}"}
                     if join_round else None))
        if deadline is None:
            run.join()
            finished = True
        else:
            while not run.is_done() and time.monotonic() < deadline:
                time.sleep(0.25)
            finished = run.is_done()
            if not finished:
                # Deadline cut: stop the coordinator at its next round
                # barrier BEFORE touching the scratch files it is
                # migrating from, and so its workers stop burning the
                # cores the remaining bench stages are about to
                # measure.
                run.stop()
                waited = time.monotonic() + 30.0
                while not run.is_done() and time.monotonic() < waited:
                    time.sleep(0.1)
        if run.is_done():
            try:
                # Reap the listener/acceptor; a stop()ped run returns
                # cleanly, an aborted one surfaces its stored error
                # here instead of silently reporting a rate.
                run.join()
            except Exception as e:  # noqa: BLE001 — partial rate stands
                RESULT["elastic_stage_error"] = \
                    f"{type(e).__name__}: {e}"[:300]
                finished = False  # an aborted run is not a clean finish
    finally:
        # Only sweep the scratch generations once the run has actually
        # stopped — deleting them under a coordinator mid-migration
        # would manufacture the very data loss the drill tests. A
        # still-running run past its stop grace leaks tempfiles
        # instead (and is recorded).
        if own_ckpt and ("run" not in locals() or run.is_done()):
            for stale in glob.glob(checkpoint_path + "*"):
                try:
                    os.unlink(stale)
                except OSError:
                    pass
        elif own_ckpt:
            RESULT["elastic_stage_error"] = (
                "elastic run did not stop within grace; scratch "
                f"checkpoints left at {checkpoint_path}")
    sched = run.scheduler_stats()
    stats = sched["elastic"]
    stats["events"] = [e["type"] for e in run.events]
    # Distributed-observability aggregates (round 12): per-worker
    # straggler gauges, merge counters, postmortem dump paths.
    obs = sched.get("elastic_obs", {})
    stats["obs"] = obs
    if chaos or "elastic" not in RESULT:
        # The parity gate's unfaulted elastic run must not clobber the
        # headline's kill/join drill record (accelerator stage order
        # runs the gate AFTER the headline).
        RESULT["elastic"] = stats
        # Straggler summary hoisted to top-level keys so BENCH_r12+
        # diffs read it without digging: the worst round's barrier
        # wait share and the slowest-worker histogram.
        RESULT["elastic_max_wait_share"] = obs.get("max_wait_share")
        RESULT["elastic_slowest_worker"] = obs.get("slowest", {})
        if kill_round or join_round:
            dumps = [p for p in obs.get("postmortems", [])
                     if os.path.exists(p)]
            RESULT["elastic_postmortems"] = dumps
            if kill_round and not dumps:
                # The drill's observability gate: a kill without a
                # flight-recorder postmortem means the always-on ring
                # failed its one job.
                RESULT["elastic_stage_error"] = (
                    "kill drill produced no flight-recorder "
                    "postmortem dump")
    return run, _steady_rate(run), finished


def _tpu_bfs(model, batch, table_capacity, cap=None, deadline=None,
             symmetry=None, max_batch=None, checkpoint_path=None,
             resume_from=None, elastic_chaos=True):
    """Runs the device engine; with a ``deadline`` (monotonic), polls
    instead of joining and returns the steady rate measured so far when
    time runs out — a partially-completed run still yields a valid rate
    (the wave_log holds per-wave samples). ``finished`` reports which.

    ``checkpoint_path``/``resume_from`` thread straight through to the
    engine (resilience subsystem): the device child sets them from
    SESSION_CKPT/SESSION_RESUME so a killed child's respawn resumes
    instead of restarting. The deadline poll loop doubles as the
    ``child_death`` fault site — each tick is one hit, so an armed
    ``STpu_FAULTS=child_death@n=K`` hard-exits the process at a
    deterministic point mid-run (modeling SIGKILL/preemption).

    ``symmetry=None`` follows the BENCH_SYMMETRY knob (the headline);
    pass ``False`` to force it off — the parity gate must, because its
    host side counts raw states and the host/device symmetry partitions
    are intentionally different strengths (RewritePlan orbits vs the
    coarser device canonical form: 665 vs 314 on 2pc), so symmetric
    counts would never gate equal even with both engines correct.

    The fused engine is the fast path; if it fails on this backend
    (an engine bug would otherwise zero the whole bench), fall back to
    the classic per-wave engine once and record why."""
    if symmetry is None:
        symmetry = os.environ.get("BENCH_SYMMETRY") == "1"

    elastic_workers = int(os.environ.get("BENCH_ELASTIC_WORKERS", "0"))
    if elastic_workers:
        return _elastic_bfs(model, elastic_workers, cap=cap,
                            deadline=deadline, symmetry=symmetry,
                            checkpoint_path=checkpoint_path,
                            resume_from=resume_from,
                            chaos=elastic_chaos)

    def spawn(fused):
        b = model.checker()
        if cap:
            b = b.target_state_count(cap)
        if symmetry:
            # Driver config 5: dedup by the client-exchangeability
            # representative (register_workload.py sym section).
            b = b.symmetry()
        # Pre-size the fused engine's arena alongside the table so a
        # bounded run never recompiles mid-flight; max_batch_size arms
        # the adaptive bucket ladder (frontier-proportional widths).
        return b.spawn_tpu_bfs(
            batch_size=batch,
            max_batch_size=max_batch,
            table_capacity=table_capacity,
            arena_capacity=table_capacity // 2,
            table_impl=os.environ.get("BENCH_TABLE_IMPL", "xla"),
            checkpoint_path=checkpoint_path,
            checkpoint_every_waves=int(
                os.environ.get("BENCH_CKPT_EVERY", "64")),
            resume_from=resume_from,
            # Packed-arena A/B knob (round 9): unset = the engine's
            # backend-aware auto (packed on accelerators, unpacked on
            # the CPU fallback); 1/0 force either arm.
            pack_arena=(None if "BENCH_PACK_ARENA" not in os.environ
                        else os.environ["BENCH_PACK_ARENA"] != "0"),
            # Single-kernel wave A/B knob (round 15): unset follows the
            # engine default (STpu_WAVE_KERNEL env, else off); 1/0
            # force either arm. Bit-identical either way — the parity
            # gate holds whichever arm the headline ran.
            wave_kernel=(None if "BENCH_WAVE_KERNEL" not in os.environ
                         else os.environ["BENCH_WAVE_KERNEL"] != "0"),
            # Matmul-form expansion A/B knob (round 19): unset follows
            # the engine default (STpu_WAVE_MATMUL env, else off); 1/0
            # force either arm. Irregular models gate back to the step
            # path with identical results — the RESULT wave_matmul
            # block records which implementation actually ran.
            wave_matmul=(None if "BENCH_WAVE_MATMUL" not in os.environ
                         else os.environ["BENCH_WAVE_MATMUL"] != "0"),
            fused=fused)

    from stateright_tpu.resilience.faults import fault_plan_from_env

    plan = fault_plan_from_env()

    def run(checker):
        if deadline is None:
            checker.join()
            return checker, _steady_rate(checker), True
        while not checker.is_done() and time.monotonic() < deadline:
            time.sleep(0.25)
            if plan.active and plan.fires("child_death", mode="exit"):
                os._exit(137)
        finished = checker.is_done()
        if finished:
            checker.join()
        return checker, _steady_rate(checker), finished

    try:
        return run(spawn(fused=None))
    except Exception as e:  # noqa: BLE001 — salvage with the classic engine
        RESULT["fused_engine_error"] = f"{type(e).__name__}: {e}"[:300]
        return run(spawn(fused=False))


def _stage_parity_gate(platform):
    """Full-enumeration parity on 2pc (zero missed violations) + the
    round's first guaranteed device rate sample. When the device-stage
    child ran the parity workload on its own backend (the backend that
    produced the headline), its counts gate instead of a local CPU run —
    TPU-specific engine bugs (u64 emulation, scatter semantics) can no
    longer pass on the strength of a CPU rehearsal (ADVICE r5 medium)."""
    from two_phase_commit import TwoPhaseSys

    if _PARITY["status"] == "ok":
        return  # already gated (e.g. before a late-resolved CPU headline)
    rms = int(os.environ.get("BENCH_PARITY_RMS", "5"))
    model = TwoPhaseSys(rms)
    host, host_rate, host_sec = _host_bfs(model)
    dev = RESULT.get("device_parity")
    if dev and dev.get("rms") == rms and dev.get("finished"):
        assert dev["unique"] == host.unique_state_count(), (
            "unique-state mismatch: device=%d host=%d"
            % (dev["unique"], host.unique_state_count()))
        assert set(dev["discoveries"]) == set(host.discoveries()), (
            "discovery mismatch: device=%s host=%s"
            % (sorted(dev["discoveries"]), sorted(host.discoveries())))
        _PARITY["status"] = "ok"
        RESULT["parity_backend"] = dev.get("platform") or platform
        RESULT.update({
            "parity": f"2pc check {rms}: {host.unique_state_count()} "
                      "unique, counts+discoveries identical "
                      f"({RESULT['parity_backend']} backend)",
            "parity_host_states_per_sec": round(host_rate, 1),
            "parity_tpu_states_per_sec": dev.get("rate"),
        })
        return
    # Raw counts on both sides regardless of BENCH_SYMMETRY — see
    # _tpu_bfs's symmetry note. The gate never runs the elastic chaos
    # drills: it gates wave correctness, not recovery.
    tpu, tpu_rate, _ = _tpu_bfs(model, 1024, 1 << 16, symmetry=False,
                                elastic_chaos=False)
    assert tpu.unique_state_count() == host.unique_state_count(), (
        "unique-state mismatch: tpu=%d host=%d"
        % (tpu.unique_state_count(), host.unique_state_count()))
    assert set(tpu.discoveries()) == set(host.discoveries()), (
        "discovery mismatch: tpu=%s host=%s"
        % (sorted(tpu.discoveries()), sorted(host.discoveries())))
    _PARITY["status"] = "ok"
    RESULT.update({
        "parity": f"2pc check {rms}: {host.unique_state_count()} unique, "
                  "counts+discoveries identical",
        "parity_host_states_per_sec": round(host_rate, 1),
        "parity_tpu_states_per_sec": round(tpu_rate, 1),
    })
    if "tpu_states" not in RESULT:
        # No headline yet (CPU stage order): this rate is the fallback
        # result line until the headline stage replaces it.
        RESULT.update({
            "metric": f"tpu_bfs states/sec on {platform}, 2pc check {rms} "
                      f"(full enumeration, parity vs spawn_bfs OK)",
            "value": round(tpu_rate, 1),
            "vs_baseline": round(tpu_rate / max(host_rate, 1e-9), 3),
        })


def build_workload(platform):
    """Returns ``(model, name, batch, table, tpu_cap, max_batch)`` for
    the headline workload. Shared with ``tools/device_session.py`` (the
    TPU-side subprocess), so both sides agree on shapes and the jit
    cache hits. ``max_batch`` tops the adaptive bucket ladder: the
    engine re-picks its dispatch width per dispatch from the live
    frontier, so the bulk of a wide run batches at ``max_batch`` while
    the seed/tail waves stay at ``batch``."""
    # On the 1-core CPU fallback, small batches win (cache-resident
    # waves); a real accelerator amortizes fixed per-wave cost over much
    # wider frontiers — and the fused engine's throughput wants a cap
    # big enough for several steady-state dispatches.
    wide = platform not in (None, "cpu")
    tpu_cap = int(os.environ.get("BENCH_TPU_CAP",
                                 "1500000" if wide else "400000"))
    if os.environ.get("BENCH_WORKLOAD", "paxos") == "paxos":
        from paxos import PaxosModelCfg

        clients = int(os.environ.get("BENCH_CLIENTS", "3"))
        liveness = os.environ.get("BENCH_LIVENESS") == "1"
        model = PaxosModelCfg(clients, 3, liveness=liveness).into_model()
        name, batch, table = (
            f"paxos check {clients}"
            + (" +liveness" if liveness else "")
            + (" +sym" if os.environ.get("BENCH_SYMMETRY") == "1"
               else ""),
            4096 if wide else 1024,
            1 << 22 if wide else 1 << 20)
    else:
        from two_phase_commit import TwoPhaseSys

        rms = int(os.environ.get("BENCH_2PC_RMS", "7"))
        model = TwoPhaseSys(rms)
        name, batch, table = (f"2pc check {rms}",
                              8192 if wide else 2048,
                              1 << 22 if wide else 1 << 20)
    batch = int(os.environ.get("BENCH_TPU_BATCH", str(batch)))
    max_batch = int(os.environ.get("BENCH_TPU_MAX_BATCH",
                                   str(batch * 16)))
    return model, name, batch, table, tpu_cap, max_batch


def _device_stage_subprocess(deadline):
    """Runs the device headline via ``tools/device_session.py
    --bench-mode``: the process that initializes the TPU is the one that
    runs the workload, and its backend init IS the probe. Field-observed
    wedge mode (2026-07-31): the tunnel granted one backend init and
    hung the next, so a separate probe that exits before the work can
    both burn the window and strand a later in-process init — a hang no
    watchdog can unwind short of ``os._exit``. The child's stdout is
    watched live: no ``init`` event within BENCH_CHILD_INIT_GRACE
    (default 75 s) means the tunnel is wedged and the child is killed
    cheaply; after a successful init it gets the room until ``deadline``
    (its internal budget makes it emit a partial result first). Returns
    the child's ``done`` event dict, or None.

    Supervised (resilience subsystem): the child checkpoints its
    headline run periodically (SESSION_CKPT), and a child that dies
    AFTER a successful init (crash, preemption, the injected
    ``child_death`` fault) is respawned up to BENCH_CHILD_RETRIES times
    (default 1) with SESSION_RESUME pointing at the newest CRC-valid
    checkpoint generation — the respawn continues the run instead of
    restarting it. A child that never initialized (wedged inside the
    init-deadline and killed, or crashed before its init event) gets
    up to BENCH_CHILD_PREINIT_RETRIES fresh spawns (default 1), each
    bounded by the same BENCH_CHILD_INIT_GRACE deadline: round-10 left
    this mode permanently unretried on the round-5 burn-the-window
    theory, but a crashed-at-import child (OOM kill, transient driver
    hiccup) is the COMMON pre-init death and one bounded retry
    recovers it — while a genuinely wedged tunnel costs one more
    killed grace window and nothing else (the deadline, not hope,
    bounds it)."""
    import tempfile

    env = dict(os.environ)
    if RESULT.get("platform") == "cpu":
        # Rehearsal (BENCH_FORCE_SUBPROCESS on a cpu box): pin the child
        # via SESSION_PLATFORM (the JAX_PLATFORMS env var alone does not
        # stop the tunneled plugin from initializing — field-tested
        # 2026-07-31; the post-import config update does) AND strip the
        # axon sitecustomize from PYTHONPATH — its register() can hang
        # any interpreter start while the relay is wedged, even
        # CPU-pinned ones (round-3 learning).
        env["SESSION_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = ""
    else:
        env.pop("JAX_PLATFORMS", None)  # the child resolves the TPU
        env.pop("SESSION_PLATFORM", None)
    # An operator-provided SESSION_CKPT is theirs to keep; the default
    # is a per-run scratch file removed when the stage concludes (a
    # stale snapshot from an earlier bench — or a recycled pid — would
    # otherwise be offered to a respawn of a DIFFERENT workload, whose
    # resume dies on the model-identity check and burns the one retry).
    own_ckpt = "SESSION_CKPT" not in env
    if own_ckpt:
        fd, ckpt_path = tempfile.mkstemp(prefix="stpu_bench_ckpt_",
                                         suffix=".npz")
        os.close(fd)
        os.unlink(ckpt_path)  # the child creates it on first write
        env["SESSION_CKPT"] = ckpt_path
    retries = int(os.environ.get("BENCH_CHILD_RETRIES", "1"))
    preinit_retries = int(os.environ.get("BENCH_CHILD_PREINIT_RETRIES",
                                         "1"))
    attempt = preinit = 0
    try:
        while True:
            done, inited, crashed = _device_stage_attempt(deadline, env)
            if done is not None:
                return done
            if not inited:
                # Pre-init wedge/crash: one bounded respawn (fresh
                # spawn, nothing to resume — the child never ran). The
                # init-deadline bounds each attempt; no deadline, no
                # retry.
                if (preinit >= preinit_retries
                        or time.monotonic() >= deadline - 5.0):
                    return None
                preinit += 1
                RESULT["device_child_preinit_retries"] = preinit
                RESULT.pop("device_stage_error", None)
                from stateright_tpu.obs import tracer_from_env

                tr = tracer_from_env("bench")
                if tr.enabled:
                    tr.event("recover", attempt=preinit, backoff_s=0.0,
                             resumed_from=None, kind="preinit_respawn",
                             _flush=True)
                    tr.close()
                continue
            if not crashed or attempt >= retries:
                return None
            attempt += 1
            from stateright_tpu.obs import tracer_from_env
            from stateright_tpu.resilience.faults import (FAULTS_ENV,
                                                          strip_point)
            from stateright_tpu.resilience.supervisor import \
                newest_valid_checkpoint

            resume = newest_valid_checkpoint(env["SESSION_CKPT"])
            if resume:
                env["SESSION_RESUME"] = resume
            else:
                # A later retry with no surviving generation must
                # restart from scratch, not inherit a SESSION_RESUME
                # pointing at a checkpoint that has since gone bad.
                env.pop("SESSION_RESUME", None)
            if env.get(FAULTS_ENV):
                # An inherited one-shot child_death spec would kill
                # the respawn at the same deterministic tick, forever,
                # by construction — the injected death happened; its
                # recovery is what the respawn exercises.
                env[FAULTS_ENV] = strip_point(env[FAULTS_ENV],
                                              "child_death")
            RESULT["device_child_respawns"] = attempt
            RESULT["device_child_resumed_from"] = resume
            RESULT.pop("device_stage_error", None)
            tr = tracer_from_env("bench")
            if tr.enabled:
                tr.event("recover", attempt=attempt, backoff_s=0.0,
                         resumed_from=resume, _flush=True)
                tr.close()
    finally:
        if own_ckpt:
            from stateright_tpu.checkpoint_format import PREV_SUFFIX

            for stale in (env["SESSION_CKPT"],
                          env["SESSION_CKPT"] + PREV_SUFFIX):
                try:
                    os.unlink(stale)
                except OSError:
                    pass


def _device_stage_attempt(deadline, env):
    """One spawn + watch of the device child. Returns ``(done_event_or_
    None, child_initialized, child_exited)`` — the respawn loop above
    retries only the initialized-then-died combination."""
    allowance = max(deadline - time.monotonic(), 10.0)
    env = dict(env)
    env["SESSION_BUDGET_S"] = str(max(allowance - 15.0, 5.0))
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(_ROOT, "tools", "device_session.py"),
         "--bench-mode"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)
    _CHILD["proc"] = proc  # the watchdog kills this before os._exit
    events_q = queue.Queue()
    stderr_tail = []
    eof = object()  # distinct sentinel: json "null" on stdout is None

    def _read_stdout():
        for line in proc.stdout:
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                events_q.put(obj)
        events_q.put(eof)

    def _read_stderr():  # drain so XLA warnings can't deadlock the pipe
        for line in proc.stderr:
            stderr_tail[:] = [line.strip()[:200]]

    threading.Thread(target=_read_stdout, daemon=True).start()
    threading.Thread(target=_read_stderr, daemon=True).start()

    init_grace = float(os.environ.get("BENCH_CHILD_INIT_GRACE", "75"))
    init_deadline = time.monotonic() + min(init_grace, allowance)
    init = done = parity = None
    exited = False
    try:
        while True:
            now = time.monotonic()
            if init is None:
                limit = min(init_deadline, deadline)
            elif done is not None:
                # Headline landed; linger only for the on-device parity
                # payload (emitted after the headline on accelerators),
                # bounded so the host-baseline stages keep their budget.
                limit = min(deadline, done_t + float(os.environ.get(
                    "BENCH_DEVICE_PARITY_GRACE", "120")))
            else:
                limit = deadline
            if now >= limit:
                break
            try:
                obj = events_q.get(timeout=min(limit - now, 5.0))
            except queue.Empty:
                continue
            if obj is eof:
                exited = True
                break  # the child exited
            if obj.get("event") == "init":
                init = obj
            elif obj.get("event") == "parity":
                parity = obj
            elif obj.get("event") == "done":
                done = obj
                done_t = time.monotonic()
                if parity is not None:
                    break  # parity already landed (CPU stage order)
    finally:
        if proc.poll() is None:
            proc.kill()
    if init:
        RESULT["device_platform"] = init.get("platform")
        RESULT["device_init_sec"] = init.get("sec")
    if parity:
        # The gate stage compares these against the host reference —
        # property-violation parity checked on the backend that produced
        # the headline (ADVICE r5 medium).
        RESULT["device_parity"] = {
            k: parity.get(k) for k in ("platform", "rms", "unique",
                                       "states", "discoveries", "rate",
                                       "finished", "sec")}
    if done and done.get("rate", 0) > 0:
        return done, init is not None, exited
    if init is None:
        # Distinguish a crashed child (instant exit, rc set) from the
        # wedged-tunnel hang (killed after the grace window) — the
        # operator response differs.
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            # The SIGKILL'd child cannot be reaped (D-state in a wedged
            # driver — exactly the scenario this path diagnoses); treat
            # it as the wedge and fall through to the honest CPU
            # fallback rather than aborting the headline stage.
            exited = False
        why = (f"device child exited rc={proc.returncode} before "
               "backend init" if exited
               else "device child wedged before backend init")
    else:
        why = "device child produced no result after init"
    RESULT["device_stage_error"] = (
        why + (f"; stderr: {stderr_tail[0]}" if stderr_tail else ""))
    return None, init is not None, exited


def _hoist_succ_telemetry(scheduler: dict) -> None:
    """Copies the successor-path (ISSUE 2) and packed-arena (ISSUE 4)
    telemetry to top-level result keys so a round's K-rung usage,
    overflow-redispatch count, collapse ratio, bytes-per-state, and
    arena/table byte high-water marks are one grep away — whether the
    headline ran in-process or streamed from the device child."""
    if not isinstance(scheduler, dict):
        return
    if scheduler.get("succ_ladder") is not None:
        RESULT["succ_ladder"] = scheduler["succ_ladder"]
    if scheduler.get("local_dedup") is not None:
        RESULT["local_dedup"] = scheduler["local_dedup"]
    packing = scheduler.get("packing")
    if isinstance(packing, dict):
        RESULT["packing"] = packing
        RESULT["bytes_per_state"] = packing.get("bytes_per_state")
        RESULT["arena_bytes_high_water"] = \
            packing.get("arena_bytes_high_water")
        RESULT["table_bytes_high_water"] = \
            packing.get("table_bytes_high_water")
    store = scheduler.get("store")
    if isinstance(store, dict) and store.get("enabled"):
        # Tiered-store telemetry (ISSUE 8): the graceful-degradation
        # record, one grep away.
        RESULT["tier_store"] = store
        RESULT["tier_spill_bytes"] = store.get("spill_bytes")
        RESULT["tier_resident_ratio"] = store.get("resident_ratio")
    wk = scheduler.get("wave_kernel")
    if isinstance(wk, dict):
        # Single-kernel wave (ISSUE 10): the active successor-path
        # implementation and the device-loop cadence, hoisted so every
        # A/B run is attributable to the path it actually executed
        # (megakernel / pallas_probe / xla / interpret).
        RESULT["wave_kernel"] = wk
        RESULT["kernel_path"] = wk.get("path")
        RESULT["waves_per_round_trip"] = wk.get("waves_per_round_trip")
    wm = scheduler.get("wave_matmul")
    if isinstance(wm, dict):
        # Matmul-form expansion (ISSUE 15): which expand implementation
        # the wave programs embedded (matmul vs vmapped step), the gate
        # reason, and the compiled plan's static MAC count — hoisted so
        # every A/B run is attributable without digging.
        RESULT["wave_matmul"] = wm
        RESULT["expand_impl"] = wm.get("expand_impl")
    pr = scheduler.get("prof")
    if isinstance(pr, dict):
        # Continuous profiler (ISSUE 18, BENCH_PROF=1): the headline
        # engine's per-program roofline gauges, numeric fields only so
        # they flatten to comparable prof.* keys in bench_compare.
        hoisted = {"dispatches": pr.get("dispatches"),
                   "sampled": pr.get("sampled")}
        for key, snap in (pr.get("programs") or {}).items():
            hoisted[key] = {
                f: snap[f] for f in ("flops", "bytes", "flops_per_s",
                                     "bytes_per_s", "intensity",
                                     "cost_ratio", "measured_s")
                if isinstance(snap.get(f), (int, float))}
        RESULT["prof"] = hoisted


def _stage_tier_drill(platform):
    """The memory-pressure arm of the kill-drill family
    (``BENCH_TIER_DRILL=1``): run a small 2pc enumeration with a device
    arena/table capped far below the state-space size (forcing visited
    spills through warm to cold) and GATE on the run finishing with
    totals and discoveries bit-identical to an uncapped run. Fills
    ``RESULT["tier_drill"]``; a mismatch sets ``parity_failed``."""
    import tempfile

    from two_phase_commit import TwoPhaseSys

    rms = int(os.environ.get("BENCH_TIER_DRILL_RMS", "4"))
    model = TwoPhaseSys(rms)

    def run(**tier):
        c = model.checker().spawn_tpu_bfs(
            batch_size=32, table_capacity=1024, fused=False, **tier)
        c.join()
        return c

    # The clean reference must be GENUINELY uncapped: main() maps
    # BENCH_TIER_* onto the STpu_TIER_* env knobs before the stages
    # run, and a kwarg-less engine would arm the store off that env —
    # turning the gate into capped-vs-capped. Strip the knobs for the
    # reference run only.
    from stateright_tpu.store.tiered import (TIER_DEVICE_ENV,
                                             TIER_DIR_ENV,
                                             TIER_HOST_ENV)

    saved = {var: os.environ.pop(var, None)
             for var in (TIER_DEVICE_ENV, TIER_HOST_ENV, TIER_DIR_ENV)}
    try:
        clean = run()
    finally:
        for var, val in saved.items():
            if val is not None:
                os.environ[var] = val
    want = (clean.state_count(), clean.unique_state_count(),
            tuple(sorted(clean.discoveries())))
    seg_dir = (os.environ.get("BENCH_TIER_DIR")
               or tempfile.mkdtemp(prefix="stpu-tier-drill-"))
    capped = run(tier_device_bytes=40_000, tier_host_bytes=4096,
                 tier_dir=seg_dir)
    got = (capped.state_count(), capped.unique_state_count(),
           tuple(sorted(capped.discoveries())))
    stats = capped.store_stats()
    RESULT["tier_drill"] = {
        "rms": rms, "match": got == want,
        "states": got[0], "unique": got[1],
        "spills": stats["spills"],
        "spill_bytes": stats["spill_bytes"],
        "disk_rows": stats["disk"]["rows"],
        "probe_hits": stats["probe_hits"],
        "resident_ratio": stats["resident_ratio"],
    }
    if got != want:
        _PARITY["status"] = "failed"
        RESULT["parity_failed"] = True
        raise AssertionError(
            f"tier drill mismatch: capped {got} vs clean {want}")
    if not stats["spill_bytes"]:
        raise AssertionError(
            "tier drill never spilled — the caps no longer exercise "
            "the store; tighten BENCH_TIER knobs")


def _stage_async_io(platform):
    """The async-host-I/O A/B arm (``BENCH_ASYNC_IO=1``): interleaved
    knob-on/knob-off runs of a checkpoint-heavy 2pc config (generation
    every 4 waves — well under the checkpoint_every_waves<=8 bar) plus
    one spill-capped tiered pair, reporting the wave-loop I/O stall
    share per arm and GATING on counters/discoveries/final-generation
    BYTES being identical across arms. Fills ``RESULT["async_io"]``; a
    mismatch sets ``parity_failed``."""
    import hashlib
    import tempfile

    from two_phase_commit import TwoPhaseSys

    rms = int(os.environ.get("BENCH_ASYNC_IO_RMS", "4"))
    reps = int(os.environ.get("BENCH_ASYNC_IO_REPS", "3"))
    model = TwoPhaseSys(rms)
    work = tempfile.mkdtemp(prefix="stpu-async-io-")

    def run(arm, async_io, **tier):
        path = os.path.join(work, f"{arm}.ckpt")
        for stale in (path, path + ".prev"):
            if os.path.exists(stale):
                os.remove(stale)
        t0 = time.monotonic()
        c = model.checker().spawn_tpu_bfs(
            batch_size=32, table_capacity=2048, fused=False,
            async_io=async_io, checkpoint_path=path,
            checkpoint_every_waves=4, **tier)
        c.join()
        wall = time.monotonic() - t0
        stats = c.scheduler_stats()["async_io"]
        # The stall the wave loop actually ate: inline write seconds
        # when sync (every write blocks the loop), join-wait seconds
        # when async (only the residue the overlap failed to hide).
        stall = stats["join_wait_s"] if async_io else stats["busy_s"]
        digest = hashlib.sha256(open(path, "rb").read()).hexdigest()
        ident = (c.state_count(), c.unique_state_count(),
                 tuple(sorted(c.discoveries())), digest)
        return ident, wall, stall, stats

    def ab_pair(label, **tier):
        walls = {True: [], False: []}
        stalls = {True: [], False: []}
        idents = {}
        overlap = 0.0
        # Interleaved (on, off, on, off, ...): both arms sample the
        # same thermal/cache drift — the 2-core-box noise discipline
        # every A/B in this bench follows.
        for _ in range(max(1, reps)):
            for async_io in (True, False):
                ident, wall, stall, stats = run(
                    f"{label}-{'on' if async_io else 'off'}",
                    async_io, **tier)
                walls[async_io].append(wall)
                stalls[async_io].append(stall)
                prev = idents.setdefault(async_io, ident)
                if prev != ident:
                    raise AssertionError(
                        f"{label}: non-deterministic arm "
                        f"(async_io={async_io})")
                overlap = max(overlap, stats.get("overlap_s", 0.0))
        if idents[True] != idents[False]:
            _PARITY["status"] = "failed"
            RESULT["parity_failed"] = True
            raise AssertionError(
                f"async_io {label} mismatch: on={idents[True][:3]} "
                f"off={idents[False][:3]} ckpt_sha "
                f"on={idents[True][3][:12]} off={idents[False][3][:12]}")
        row = {}
        for async_io in (True, False):
            arm = "on" if async_io else "off"
            wall = min(walls[async_io])
            stall = min(stalls[async_io])
            row[arm] = {"wall_s": round(wall, 3),
                        "io_stall_s": round(stall, 4),
                        "stall_share": round(stall / wall, 4)
                        if wall > 0 else None}
        row["overlap_s"] = round(overlap, 4)
        row["match"] = True
        return row

    out = {"rms": rms, "reps": reps,
           "ckpt_heavy": ab_pair("ckpt")}
    seg_dir = os.path.join(work, "segments")
    out["spill_capped"] = ab_pair(
        "tier", tier_device_bytes=40_000, tier_host_bytes=4096,
        tier_dir=seg_dir)
    RESULT["async_io"] = out


def _stage_matmul_ab(platform):
    """The matmul-wave A/B arm (``BENCH_MATMUL_AB=1``): interleaved
    knob-on/knob-off full enumerations of a regular 2pc workload,
    GATING on counts/discoveries/parent-map/checkpoint BYTES identity
    across arms and reporting per-arm wall clock with kernel_path
    attribution proving which expand implementation each arm actually
    executed. Interleaved (on, off, on, off, ...) so both arms sample
    the same thermal/cache drift — the 2-core-box noise discipline
    every A/B in this bench follows. Fills ``RESULT["matmul_ab"]``; a
    mismatch sets ``parity_failed``."""
    import hashlib
    import tempfile

    from two_phase_commit import TwoPhaseSys

    rms = int(os.environ.get("BENCH_MATMUL_AB_RMS", "5"))
    reps = int(os.environ.get("BENCH_MATMUL_AB_REPS", "3"))
    batch = int(os.environ.get("BENCH_MATMUL_AB_BATCH", "512"))
    model = TwoPhaseSys(rms)
    work = tempfile.mkdtemp(prefix="stpu-matmul-ab-")

    def run(arm, on):
        path = os.path.join(work, f"{arm}.ckpt")
        for stale in (path, path + ".prev"):
            if os.path.exists(stale):
                os.remove(stale)
        t0 = time.monotonic()
        c = model.checker().spawn_tpu_bfs(
            batch_size=batch, table_capacity=1 << 16, fused=True,
            wave_matmul=on, checkpoint_path=path)
        c.join()
        wall = time.monotonic() - t0
        digest = hashlib.sha256(open(path, "rb").read()).hexdigest()
        ident = (c.state_count(), c.unique_state_count(),
                 tuple(sorted(c.discoveries())), digest)
        return ident, wall, c.scheduler_stats()["wave_matmul"], \
            c.kernel_path(), _steady_rate(c)

    walls = {True: [], False: []}
    rates = {True: [], False: []}
    idents = {}
    stats_by_arm = {}
    for _ in range(max(1, reps)):
        for on in (True, False):
            ident, wall, wm, path, rate = run(
                "on" if on else "off", on)
            walls[on].append(wall)
            rates[on].append(rate)
            stats_by_arm[on] = (wm, path)
            prev = idents.setdefault(on, ident)
            if prev != ident:
                raise AssertionError(
                    f"matmul_ab: non-deterministic arm "
                    f"(wave_matmul={on})")
    # Attribution: recorded == executed. The on-arm must have actually
    # run the compiled plan (2pc IS regular) and say so everywhere.
    wm_on, path_on = stats_by_arm[True]
    wm_off, path_off = stats_by_arm[False]
    assert wm_on["active"] and wm_on["expand_impl"] == "matmul", wm_on
    assert path_on.endswith("+matmul"), path_on
    assert not wm_off["enabled"] and not path_off.endswith("+matmul")
    out = {"workload": f"2pc check {rms}", "reps": reps,
           "batch": batch}
    # Checkpoint digests embed the table (identical), not timestamps;
    # dropping it from the reported tuple keeps the json lean.
    if idents[True] != idents[False]:
        _PARITY["status"] = "failed"
        RESULT["parity_failed"] = True
        RESULT["matmul_ab"] = dict(out, match=False)
        raise AssertionError(
            f"matmul_ab mismatch: on={idents[True][:3]} "
            f"off={idents[False][:3]} ckpt_sha "
            f"on={idents[True][3][:12]} off={idents[False][3][:12]}")
    for on in (True, False):
        arm = "matmul" if on else "step"
        out[arm] = {
            "wall_s": round(min(walls[on]), 3),
            "states_per_sec": round(max(rates[on]), 1),
            "kernel_path": stats_by_arm[on][1],
        }
    out.update({
        "match": True,
        "states": idents[True][0],
        "unique": idents[True][1],
        "matmul_ops_per_row": wm_on["matmul_ops"],
        "reason": wm_on["reason"],
        "speedup": round(out["matmul"]["states_per_sec"]
                         / max(out["step"]["states_per_sec"], 1e-9), 3),
    })
    RESULT["matmul_ab"] = out


def _stage_headline(platform):
    """The north-star workload, bounded to a rate sample."""
    host_cap = int(os.environ.get("BENCH_HOST_CAP", "60000"))
    model, name, batch, table, tpu_cap, max_batch = build_workload(platform)

    host, host_rate, host_sec = _host_bfs(model, cap=host_cap)
    RESULT.update({
        "host_states_per_sec": round(host_rate, 1),
        "host_sec": round(host_sec, 2),
        "headline_pending": f"{name} device run did not finish",
    })
    # Leave the watchdog a margin to emit; a partial run still reports.
    deadline = _T0 + _BUDGET - min(30.0, _BUDGET * 0.12)
    use_sub = (platform != "cpu"
               or os.environ.get("BENCH_FORCE_SUBPROCESS") == "1")
    sub = _device_stage_subprocess(deadline) if use_sub else None
    if use_sub and sub is None and platform != "cpu":
        # Wedged tunnel or dead child: relabel honestly and fall back
        # to the CPU path with CPU-appropriate shapes (the specific
        # reason is in device_stage_error).
        RESULT["error"] = (RESULT.get("error", "") +
                           "; tpu device stage unavailable; ran on "
                           "cpu").lstrip("; ")
        platform = RESULT["platform"] = "cpu"
        _force_platform("cpu")
        model, name, batch, table, tpu_cap, max_batch = \
            build_workload("cpu")
        if _PARITY["status"] == "pending":
            # CPU-only host resolved late (the accelerator stage order
            # ran the headline first): gate parity NOW, before the slow
            # in-process CPU headline, so a tight watchdog budget cannot
            # emit "parity gate pending" (ADVICE r5).
            try:
                _stage_parity_gate("cpu")
            except Exception as e:  # noqa: BLE001 — headline still runs
                _PARITY["status"] = "failed"
                RESULT["parity_failed"] = True
                RESULT["error"] = (RESULT.get("error", "") +
                                   f"; _stage_parity_gate: "
                                   f"{type(e).__name__}: {e}").lstrip("; ")
    if sub is not None:
        # The child resolved the real platform (the parent may only
        # know "tpu?" — it never touches the tunnel itself).
        platform = RESULT["platform"] = sub.get("platform", platform)
        tpu_rate, finished = sub["rate"], sub["finished"]
        tpu_states, tpu_unique = sub["states"], sub["unique"]
        batch, table, tpu_cap = sub["batch"], sub["table"], sub["cap"]
        if sub.get("fused_engine_error"):
            RESULT["fused_engine_error"] = sub["fused_engine_error"]
        if sub.get("scheduler"):
            RESULT["wave_scheduler"] = sub["scheduler"]
            _hoist_succ_telemetry(sub["scheduler"])
        RESULT["device_stage"] = "subprocess"
        RESULT["device_stage_sec"] = sub.get("sec")
    else:
        tpu, tpu_rate, finished = _tpu_bfs(model, batch, table,
                                           cap=tpu_cap, deadline=deadline,
                                           max_batch=max_batch)
        tpu_states = tpu.state_count()
        tpu_unique = tpu.unique_state_count()
        try:
            RESULT["wave_scheduler"] = tpu.scheduler_stats()
            _hoist_succ_telemetry(RESULT["wave_scheduler"])
        except Exception:  # noqa: BLE001 — telemetry is optional
            pass
    if tpu_rate <= 0:
        return  # no full wave completed; keep the parity-stage numbers
    del RESULT["headline_pending"]
    ran = ("cap %d" % tpu_cap if finished
           else "partial: deadline before cap")

    def _set_headline(baseline_rate, baseline_name):
        def compose():
            return (f"tpu_bfs states/sec on {platform}, {name} "
                    f"({tpu_states} states, {ran}; "
                    f"{_parity_clause()}; baseline = "
                    f"{baseline_name}, {os.cpu_count()} core(s))")

        _HEADLINE["recompose"] = compose
        RESULT.update({
            "metric": compose(),
            "value": round(tpu_rate, 1),
            "unit": "states/sec",
            "vs_baseline": round(tpu_rate / max(baseline_rate, 1e-9), 3),
            "vs_python_host": round(tpu_rate / max(host_rate, 1e-9), 3),
            "tpu_states": tpu_states,
            "tpu_unique": tpu_unique,
        })

    # Publish with the Python baseline first, then upgrade to the honest
    # compiled baseline — run AFTER the device stage so its first-use g++
    # compile + full-space enumeration can never eat the device window,
    # and only with budget left for it (the watchdog emits whatever the
    # last completed update produced).
    _set_headline(host_rate, "Python spawn_bfs")
    if _remaining() > 40:
        try:
            native_rate = _native_bfs_rate(model)
        except Exception as e:  # noqa: BLE001 — keep the Python baseline
            RESULT["native_baseline_error"] = \
                f"{type(e).__name__}: {e}"[:300]
            native_rate = None
        if native_rate:
            RESULT["native_host_states_per_sec"] = round(native_rate, 1)
            _set_headline(native_rate, "native C++ spawn_bfs")
    if platform != "cpu" and RESULT.get("device_stage") == "subprocess":
        # The main process runs on the CPU backend when the headline came
        # from the TPU subprocess — a breakdown here would attribute the
        # wrong hardware. tools/device_session.py (full session) is the
        # on-hardware breakdown path.
        RESULT["wave_breakdown_skipped"] = "main process is on cpu"
        return
    if _remaining() > 45:
        # Per-stage wave-time attribution (staged timed dispatches on a
        # short run of the same workload) — the data that decides where
        # the next device optimization goes.
        try:
            from stateright_tpu.tpu.profiling import measure_wave_breakdown

            RESULT["wave_breakdown"] = measure_wave_breakdown(
                model, batch_size=batch, max_waves=8,
                max_batch_size=max_batch,
                deadline_s=max(10.0, _remaining() - 35))
        except Exception as e:  # noqa: BLE001 — attribution is optional
            RESULT["wave_breakdown_error"] = \
                f"{type(e).__name__}: {e}"[:300]


def _enable_jit_cache(platform) -> None:
    from stateright_tpu.jit_cache import enable_persistent_jit_cache

    # Pass the resolved platform explicitly: enabling the cache must
    # never initialize a backend (a wedged TPU tunnel hangs unboundedly).
    enable_persistent_jit_cache(platform=platform)


def _stage_service(platform) -> None:
    """Checking-as-a-service satellite (BENCH_SERVICE_JOBS=N): submits
    N concurrent small jobs to an in-process ``JobService`` and reports
    aggregate throughput plus the shared wave-program cache's hit
    ratio under ``RESULT["service"]`` — the many-small-checks axis
    (ROADMAP item 5), where the win is amortization: job 1 pays the
    XLA compiles, jobs 2..N reuse the executables. ``cold_sec`` vs
    ``warm_sec_median`` is the measured gap (same-model jobs,
    wall-clock per job)."""
    import tempfile

    from stateright_tpu.service import JobService

    n_jobs = int(os.environ.get("BENCH_SERVICE_JOBS", "0"))
    if n_jobs <= 0:
        return
    workers = int(os.environ.get("BENCH_SERVICE_WORKERS", "2"))
    model = os.environ.get("BENCH_SERVICE_MODEL", "twopc")
    svc = JobService(workers=workers,
                     data_dir=tempfile.mkdtemp(prefix="stpu-bench-svc-"))
    deadline = time.monotonic() + max(10.0, _remaining() - 10.0)
    t0 = time.monotonic()
    ids = [svc.submit({"model": model,
                       "knobs": {"batch_size": 64}})["id"]
           for _ in range(n_jobs)]
    stats = {"jobs": n_jobs, "model": model, "workers": workers}
    try:
        done = []
        while len(done) < n_jobs and time.monotonic() < deadline:
            statuses = [svc.status(j) for j in ids]
            done = [s for s in statuses
                    if s["state"] not in ("queued", "running")]
            time.sleep(0.1)
        wall = time.monotonic() - t0
        finished = [s for s in done if s["state"] == "done"]
        runtimes = sorted(s["runtime_s"] for s in finished
                          if s.get("runtime_s") is not None)
        cache = svc.program_cache.stats()
        stats.update({
            "finished": len(finished),
            "wall_sec": round(wall, 3),
            "jobs_per_sec": round(len(finished) / max(wall, 1e-9), 3),
            "cache_hits": cache["hits"],
            "cache_misses": cache["misses"],
            "cache_hit_ratio": cache["hit_ratio"],
            # Cold vs warm job latency: the slowest job carried the
            # compiles (jobs race, so max ~ cold), the median of the
            # rest ran warm.
            "cold_sec": runtimes[-1] if runtimes else None,
            "warm_sec_median": (runtimes[len(runtimes) // 2]
                                if len(runtimes) > 1 else None),
        })
        if len(finished) < n_jobs:
            stats["error"] = (f"{n_jobs - len(finished)} job(s) not "
                              "finished at the stage deadline")
    finally:
        svc.close()
        RESULT["service"] = stats


def _stage_soak(platform) -> None:
    """Sustained-traffic soak (BENCH_SOAK_JOBS=N): replays ONE arrival
    schedule of N same-shape jobs against two service configurations
    on the same box — cross-job wave multiplexing on (round 16: jobs
    share device waves as tenants of one engine) vs off (one engine
    per job, the round-14 baseline) — and reports aggregate jobs/s and
    p50/p99 per-job latency (submit to observed completion, queue wait
    included) under ``RESULT["soak"]``. With BENCH_SOAK_MIX=preempt
    (the default) one mid-schedule job is preempted and resumed in
    EACH arm, so the latency tail is measured with a drain + resume in
    flight, not on an undisturbed queue. The arms' per-job counters
    must agree pairwise (the differential suite pins solo identity;
    the A/B pins arm identity on live traffic) — a mismatch sets
    ``parity_failed``."""
    import tempfile

    from stateright_tpu.service import JobService

    n_jobs = int(os.environ.get("BENCH_SOAK_JOBS", "0"))
    if n_jobs <= 0:
        return
    arrival = float(os.environ.get("BENCH_SOAK_ARRIVAL", "0.05"))
    mix = os.environ.get("BENCH_SOAK_MIX", "preempt")
    inject = mix == "preempt"
    # BENCH_SOAK_MIX=crash (round 17): arm a torn-checkpoint fault in
    # EACH arm instead of a preempt — the mux arm's group crash now
    # routes through the Supervisor like the solo arm's, and the
    # pairwise counters_identical gate below IS the drill: per-tenant
    # counters must survive a mid-run crash of the shared engine.
    crash = mix == "crash"
    model = os.environ.get("BENCH_SERVICE_MODEL", "twopc")
    workers = int(os.environ.get("BENCH_SERVICE_WORKERS",
                                 str(min(8, n_jobs))))
    spec = {"model": model, "knobs": {"batch_size": 64}}
    if crash:
        # A small cadence so every job reaches checkpoint rest points.
        spec["knobs"]["checkpoint_every_waves"] = 2

    def _arm(mux: bool, deadline: float) -> dict:
        svc = JobService(
            workers=workers, mux=mux,
            data_dir=tempfile.mkdtemp(prefix="stpu-bench-soak-"))
        if crash:
            from stateright_tpu.resilience import (FAULTS_ENV,
                                                   reset_fault_plans)

            os.environ[FAULTS_ENV] = "torn_ckpt@n=2"
            reset_fault_plans()
        try:
            t0 = time.monotonic()
            submit_t, done_t, finals = {}, {}, {}
            ids = []
            victim = None
            for i in range(n_jobs):
                jid = svc.submit(dict(spec))["id"]
                ids.append(jid)
                submit_t[jid] = time.monotonic()
                if inject and i == n_jobs // 2:
                    # Preempt the FIRST job mid-schedule: by now it is
                    # running (or already done on a very fast box —
                    # then there is nothing to drain and the arm runs
                    # undisturbed; "preempts" reports what landed).
                    victim = ids[0]
                    try:
                        svc.preempt(victim)
                    except Exception:  # noqa: BLE001 — already done
                        victim = None
                if arrival > 0:
                    time.sleep(arrival)
            resumed_from = {}
            preempts = 0
            while time.monotonic() < deadline:
                open_ids = [j for j in ids if j not in done_t]
                if not open_ids:
                    break
                for jid in open_ids:
                    s = svc.status(jid)
                    if s["state"] in ("queued", "running"):
                        continue
                    if s["state"] == "preempted" \
                            and jid not in resumed_from.values():
                        # Resume continues the SAME logical job: its
                        # latency clock keeps running from the original
                        # submission.
                        rid = svc.submit({"resume": jid})["id"]
                        ids[ids.index(jid)] = rid
                        submit_t[rid] = submit_t.pop(jid)
                        resumed_from[rid] = jid
                        preempts += 1
                        continue
                    done_t[jid] = time.monotonic()
                    finals[jid] = (s["state"], s.get("states"),
                                   s.get("unique"))
                time.sleep(0.05)
            wall = time.monotonic() - t0
            lats = sorted(done_t[j] - submit_t[j] for j in done_t)
            finished = [j for j in done_t if finals[j][0] == "done"]
            stats = {
                "finished": len(finished),
                "preempts": preempts,
                "wall_sec": round(wall, 3),
                "jobs_per_sec": round(len(finished) / max(wall, 1e-9),
                                      3),
                "p50_sec": (round(lats[len(lats) // 2], 3)
                            if lats else None),
                "p99_sec": (round(lats[min(len(lats) - 1,
                                           int(len(lats) * 0.99))], 3)
                            if lats else None),
                "counters": sorted(finals[j][1:] for j in finished),
            }
            if len(finished) < n_jobs:
                stats["error"] = (f"{n_jobs - len(finished)} job(s) "
                                  "not finished at the arm deadline")
            return stats
        finally:
            svc.close()
            if crash:
                from stateright_tpu.resilience import (FAULTS_ENV,
                                                       reset_fault_plans)

                os.environ.pop(FAULTS_ENV, None)
                reset_fault_plans()

    stats = {"jobs": n_jobs, "model": model, "workers": workers,
             "arrival_sec": arrival, "mix": mix}
    # Half the remaining budget per arm, multiplexed first.
    for key, mux in (("mux", True), ("solo", False)):
        budget = max(15.0, (_remaining() - 10.0) / 2.0)
        stats[key] = _arm(mux, time.monotonic() + budget)
    mux_c = stats["mux"].pop("counters", [])
    solo_c = stats["solo"].pop("counters", [])
    stats["counters_identical"] = bool(mux_c) and mux_c == solo_c
    stats["speedup"] = round(
        stats["mux"]["jobs_per_sec"]
        / max(stats["solo"]["jobs_per_sec"], 1e-9), 3)
    if not stats["counters_identical"]:
        RESULT["parity_failed"] = True
        stats["error"] = (stats.get("error", "") +
                          " per-job counters differ between the "
                          "mux and solo arms").strip()
    RESULT["soak"] = stats


def _stage_soak_trace(platform) -> None:
    """Replayable open-loop overload A/B (BENCH_SOAK_TRACE=gen|PATH,
    round 21): loads a tools/traffic_gen arrival trace — or, with
    ``gen``, generates one under a bench tempdir from
    BENCH_SOAK_TRACE_SEED — and replays the SAME schedule (arrival
    times, priorities, tenants, deadlines all pre-sampled at
    generation time) against two live services on this box: overload
    controller ON (explicit :class:`OverloadController`) vs OFF (the
    shared disarmed ``NULL_CONTROL``). The replay is OPEN LOOP:
    submissions are held to the trace clock regardless of service
    state, so the ON arm's 429s are admission decisions and the OFF
    arm's failures are raw queue overflow — the contrast the
    controller exists to create. Goodput, interactive deadline
    hit-rate and p99, sheds by reason, and park/resume counts land
    under ``RESULT["soak_trace"]``. Single-host honesty: both arms
    share one box with the bench process itself (and on a 1-core
    runner with each other's leftover page cache), so compare the
    arms to each other, never to absolute SLO targets."""
    import tempfile

    trace_spec = os.environ.get("BENCH_SOAK_TRACE", "")
    if not trace_spec:
        return
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    import traffic_gen

    from stateright_tpu.service import (NULL_CONTROL, ControlPolicy,
                                        JobQueueFull, JobService,
                                        JobShed, OverloadController)

    if trace_spec == "gen":
        trace = traffic_gen.gen_trace(
            seed=int(os.environ.get("BENCH_SOAK_TRACE_SEED", "0")),
            duration_s=float(
                os.environ.get("BENCH_SOAK_TRACE_DURATION", "6")),
            rate_hz=float(os.environ.get("BENCH_SOAK_TRACE_RATE",
                                         "4")))
        trace_path = os.path.join(
            tempfile.mkdtemp(prefix="stpu-bench-trace-"),
            "traffic.jsonl")
        traffic_gen.write_trace(trace, trace_path)
    else:
        trace = traffic_gen.load_trace(trace_spec)
        trace_path = trace_spec
    arrivals = trace["arrivals"]
    model = os.environ.get("BENCH_SERVICE_MODEL", "twopc")
    workers = int(os.environ.get("BENCH_SERVICE_WORKERS", "2"))
    max_queued = int(os.environ.get("BENCH_SOAK_TRACE_QUEUE", "16"))

    # Both arms observe under the SAME armed SLO surface (the burn
    # signal the ON arm's controller consumes; the OFF arm measures
    # but never acts) — thresholds tight enough that a real overload
    # burns budget within the replay window.
    slo_spec = os.environ.get("BENCH_SOAK_TRACE_SLO",
                              "job_latency=1.0,queue_wait=0.3,"
                              "window=10")

    def _arm(armed: bool, deadline: float) -> dict:
        control = (OverloadController(ControlPolicy()) if armed
                   else NULL_CONTROL)
        prev_slo = os.environ.get("STpu_SLO")
        os.environ["STpu_SLO"] = slo_spec
        try:
            svc = JobService(
                workers=workers, mux=True, max_queued=max_queued,
                data_dir=tempfile.mkdtemp(prefix="stpu-bench-ab-"),
                control=control)
        finally:
            if prev_slo is None:
                os.environ.pop("STpu_SLO", None)
            else:
                os.environ["STpu_SLO"] = prev_slo
        try:
            t0 = time.monotonic()
            open_jobs = {}  # live job id -> (arrival idx, submit wall)
            shed = []  # (arrival idx, reason)
            final = {}  # arrival idx -> (latency_s, terminal state)
            for i, arr in enumerate(arrivals):
                wait = arr["t"] - (time.monotonic() - t0)
                if wait > 0:
                    time.sleep(wait)
                spec = {"model": model, "knobs": {"batch_size": 64},
                        "priority": arr["priority"],
                        "tenant": arr["tenant"]}
                if arr.get("deadline_s"):
                    spec["deadline_s"] = arr["deadline_s"]
                try:
                    jid = svc.submit(spec)["id"]
                except JobShed as e:
                    shed.append((i, e.reason))
                    continue
                except JobQueueFull:
                    # The disarmed arm's only refusal mode: raw
                    # overflow, blind to priority.
                    shed.append((i, "queue_full"))
                    continue
                open_jobs[jid] = (i, time.monotonic())
            while open_jobs and time.monotonic() < deadline:
                listing = {p["id"]: p for p in svc.jobs()}
                for p in listing.values():
                    # A controller park resumes as a NEW job id; the
                    # successor inherits the original's latency clock
                    # (parking must not launder queue wait).
                    prev = p.get("resume_of")
                    if prev in open_jobs and p["id"] not in open_jobs:
                        open_jobs[p["id"]] = open_jobs.pop(prev)
                for jid in list(open_jobs):
                    st = listing.get(jid)
                    if st is None or st["state"] in (
                            "queued", "running", "preempted"):
                        continue  # preempted = parked, resume pending
                    idx, sub_t = open_jobs.pop(jid)
                    final[idx] = (time.monotonic() - sub_t,
                                  st["state"])
                time.sleep(0.05)
            wall = time.monotonic() - t0
            by_reason: dict = {}
            for _, reason in shed:
                by_reason[reason] = by_reason.get(reason, 0) + 1
            inter = [i for i, a in enumerate(arrivals)
                     if a["kind"] == "interactive"]
            inter_done = [(i, final[i][0]) for i in inter
                          if final.get(i, (0, ""))[1] == "done"]
            inter_lats = sorted(lat for _, lat in inter_done)
            done = [i for i in final if final[i][1] == "done"]
            stats = {
                "finished": len(done),
                "shed": len(shed),
                "shed_by_reason": by_reason,
                "interactive_total": len(inter),
                "interactive_shed": sum(
                    1 for i, _ in shed
                    if arrivals[i]["kind"] == "interactive"),
                "interactive_deadline_met": sum(
                    1 for i, lat in inter_done
                    if lat <= (arrivals[i].get("deadline_s")
                               or float("inf"))),
                "interactive_p99_s": (round(
                    inter_lats[min(len(inter_lats) - 1,
                                   int(len(inter_lats) * 0.99))], 3)
                    if inter_lats else None),
                "goodput_jobs_s": round(
                    len(done) / max(wall, 1e-9), 3),
                "wall_s": round(wall, 3),
                "unfinished": len(open_jobs),
            }
            ctl = svc.control_status()
            if ctl is not None:
                stats["park_total"] = ctl["park_total"]
                stats["resume_total"] = ctl["resume_total"]
                stats["shed_total"] = ctl["shed_total"]
                stats["final_rung"] = ctl["rung"]
            return stats
        finally:
            svc.close()

    stats = {"trace": trace_path, "arrivals": len(arrivals),
             "model": model, "workers": workers,
             "queue_bound": max_queued}
    for key, armed in (("control_on", True), ("control_off", False)):
        budget = max(20.0, (_remaining() - 10.0) / 2.0)
        stats[key] = _arm(armed, time.monotonic() + budget)
    on, off = stats["control_on"], stats["control_off"]
    if on["interactive_total"]:
        stats["interactive_met_delta"] = (
            on["interactive_deadline_met"]
            - off["interactive_deadline_met"])
    RESULT["soak_trace"] = stats


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()
    # The bench owns the tunnel: kill any stray measurement-session
    # processes (e.g. the round's background attempt loop) BEFORE
    # spawning our own child — a leftover attempt holding the TPU would
    # make an open tunnel look wedged. SIGKILL, because a wedged backend
    # init ignores SIGTERM (MEASUREMENTS.md round-5). Rehearsals that
    # deliberately coexist with the attempt loop set
    # BENCH_KEEP_SESSIONS=1.
    if os.environ.get("BENCH_KEEP_SESSIONS") != "1":
        # Anchored to actual interpreter invocations AND to THIS repo's
        # absolute tool paths: a bare substring would also kill
        # unrelated shells whose command LINE merely mentions these
        # paths (field-tested: it killed the test harness that launched
        # a decoy), and an unanchored relative path would kill a
        # concurrent pytest's stub session or another operator's
        # checkout (ADVICE r5). Rehearsals that deliberately coexist
        # with the attempt loop set BENCH_KEEP_SESSIONS=1 (see
        # tests/README.md).
        import re as _re

        loop_sh = _re.escape(os.path.join(_ROOT, "tools",
                                          "session_loop.sh"))
        session_py = _re.escape(os.path.join(_ROOT, "tools",
                                             "device_session.py"))
        for pat in (rf"^[^ ]*bash {loop_sh}",
                    rf"^[^ ]*python[^ ]* {session_py}"):
            subprocess.run(["pkill", "-9", "-f", pat],
                           capture_output=True, check=False)
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        # Even when an accelerator is forced, only the killable child
        # ever initializes it; the main process stays on CPU.
        _force_platform("cpu")
        if platform != "cpu":
            RESULT["parity_backend"] = "cpu"
    else:
        # No separate probe: the field-observed wedge mode (2026-07-31)
        # granted ONE backend init and hung the next, so a probe that
        # exits before the work both burns the window and strands a
        # later init. Instead the device_session child (launched by the
        # headline stage, watched live, killable) performs the one init
        # AND the workload; its absence of an ``init`` event within the
        # grace window is the wedge signal, and the headline stage then
        # relabels to cpu and falls back. The MAIN process pins itself
        # to the CPU backend up front — an in-process init hang is
        # unrecoverable short of os._exit.
        _force_platform("cpu")
        platform = "tpu?"
        RESULT["parity_backend"] = "cpu"
    RESULT["platform"] = platform
    # The main process only ever compiles on CPU (where the persistent
    # cache is disabled by default); the device child enables the cache
    # for its own platform itself.
    _enable_jit_cache("cpu")

    # Run telemetry (obs subsystem): with STpu_TRACE set, every engine
    # this process spawns (and the device child, which inherits the
    # env) streams its wave events to one JSONL file, and the bench's
    # own stages land as spans in the same stream — the whole round is
    # one Perfetto-loadable capture. The scheduler/ladder/local-dedup
    # stats forwarded below are views over that same event stream
    # (engine dispatch_log == serialized wave events), not parallel
    # bookkeeping.
    from stateright_tpu.obs import tracer_from_env

    tracer = tracer_from_env("bench", meta={"budget_s": _BUDGET})
    if tracer.enabled:
        RESULT["trace"] = tracer.path

    # On a real accelerator the headline runs FIRST: tunnel-side compiles
    # are slow and the budget must buy the north-star number before the
    # parity gate; on CPU the cheap gate stays first (it also provides
    # the fallback rate sample). The metric string tracks whether the
    # gate has completed.
    # Tiered-store knobs (ISSUE 8): BENCH_TIER_* map onto the engines'
    # STpu_TIER_* env knobs BEFORE any stage spawns an engine, so the
    # in-process path and the device child (which inherits the env)
    # both run under the same tier budgets.
    for bench_key, env_key in (("BENCH_TIER_DEVICE_CAP",
                                "STpu_TIER_DEVICE_BYTES"),
                               ("BENCH_TIER_RAM_CAP",
                                "STpu_TIER_HOST_BYTES"),
                               ("BENCH_TIER_DIR", "STpu_TIER_DIR")):
        if os.environ.get(bench_key):
            os.environ[env_key] = os.environ[bench_key]
    # Continuous-profiler knob (ISSUE 18): BENCH_PROF=1 arms STpu_PROF
    # for the in-process stages AND the device child (env inherited);
    # _hoist_succ_telemetry lifts the headline engine's per-program
    # roofline gauges into RESULT["prof"]. An explicit STpu_PROF=0 in
    # the ambient env wins (setdefault).
    if os.environ.get("BENCH_PROF") == "1":
        os.environ.setdefault("STpu_PROF", "1")
        if os.environ.get("BENCH_PROF_SAMPLE"):
            os.environ["STpu_PROF_SAMPLE"] = \
                os.environ["BENCH_PROF_SAMPLE"]

    on_accel = (platform != "cpu"
                or os.environ.get("BENCH_FORCE_ACCEL_ORDER") == "1")
    stages = ((_stage_headline, _stage_parity_gate) if on_accel
              else (_stage_parity_gate, _stage_headline))
    if os.environ.get("BENCH_TIER_DRILL") == "1":
        stages = stages + (_stage_tier_drill,)
    if os.environ.get("BENCH_ASYNC_IO") == "1":
        stages = stages + (_stage_async_io,)
    if os.environ.get("BENCH_MATMUL_AB") == "1":
        stages = stages + (_stage_matmul_ab,)
    if int(os.environ.get("BENCH_SERVICE_JOBS", "0") or 0) > 0:
        stages = stages + (_stage_service,)
    if int(os.environ.get("BENCH_SOAK_JOBS", "0") or 0) > 0:
        stages = stages + (_stage_soak,)
    if os.environ.get("BENCH_SOAK_TRACE"):
        stages = stages + (_stage_soak_trace,)
    for stage in stages:
        try:
            # Read the platform at call time: a post-probe wedge inside
            # the headline stage relabels RESULT["platform"] to cpu.
            with tracer.span(stage.__name__,
                             platform=RESULT["platform"]):
                stage(RESULT["platform"])
        except Exception as e:  # noqa: BLE001 — always emit the JSON line
            prior = RESULT.get("error")
            RESULT["error"] = (f"{prior}; " if prior else "") + \
                f"{stage.__name__}: {type(e).__name__}: {e}"
            # The other stage still runs: a headline failure must not
            # zero the bench (the parity stage provides the fallback
            # rate sample); a parity failure is recorded machine-
            # readably and stamped on the metric below.
            if stage is _stage_parity_gate:
                _PARITY["status"] = "failed"
                RESULT["parity_failed"] = True
    if _HEADLINE.get("recompose"):
        # Re-render the headline metric with the FINAL parity status
        # (under accelerator order the gate runs after the headline).
        RESULT["metric"] = _HEADLINE["recompose"]()
    tracer.close()
    _emit_and_exit(0)


if __name__ == "__main__":
    main()

// Explorer client. Speaks the same JSON API as the reference's UI
// (GET /.status polled every 5 s; GET /.states/<fp>/<fp> per step, cached)
// and honors its URL scheme: #/steps/<fp>/<fp>?offset=n. Vanilla JS.
// The status line's throughput readout polls GET /.metrics (Prometheus
// text from the obs subsystem) every 2 s while checking.
'use strict';

// ---------------------------------------------------------------- model --

// A "step" is a node in the browsed path: the state reached, the action
// that led there, and lazily fetched next steps.
function makeStep(raw, prev, index) {
    return {
        action: raw.action || ('Init ' + index),
        outcome: raw.outcome,
        state: raw.state,
        svg: raw.svg,
        fingerprint: raw.fingerprint,
        ignored: raw.state === undefined,
        prev: prev,
        path: prev ? prev.path + '/' + raw.fingerprint : '',
        next: null, // filled by fetchNext
    };
}

const PRE_INIT = makeStep(
    {state: 'No state selected', fingerprint: ''}, null, 0);
PRE_INIT.action = 'Pre-init';
PRE_INIT.path = '';

const nextCache = {}; // step.path -> Promise<[step]>

function fetchNext(step) {
    if (!(step.path in nextCache)) {
        nextCache[step.path] = fetch('/.states' + step.path)
            .then((r) => {
                if (!r.ok) { throw new Error('HTTP ' + r.status); }
                return r.json();
            })
            .then((rows) => rows.map((row, i) => makeStep(row, step, i)))
            .catch((err) => {
                delete nextCache[step.path];
                throw err;
            });
    }
    return nextCache[step.path].then((steps) => {
        step.next = steps;
        return steps;
    });
}

function pathSteps(step) {
    const steps = [];
    for (let cur = step; cur; cur = cur.prev) { steps.unshift(cur); }
    return steps;
}

// ----------------------------------------------------------------- state --

let selected = PRE_INIT;  // the step whose state is displayed
let farthest = PRE_INIT;  // the tip of the browsed path

// ------------------------------------------------------------- rendering --

const $ = (id) => document.getElementById(id);

function el(tag, props, text) {
    const node = document.createElement(tag);
    Object.assign(node, props || {});
    if (text !== undefined) { node.textContent = text; }
    return node;
}

function renderStatus(s) {
    $('status-model').textContent =
        (s.model || '').replace(/[0-9A-Za-z_.]+\./g, '');
    $('status-states').textContent = Number(s.state_count).toLocaleString();
    $('status-unique').textContent =
        Number(s.unique_state_count).toLocaleString();
    const recent = s.recent_path || '';
    $('status-progress').textContent = s.done ? 'Done'
        : (recent.length < 100 ? recent : recent.slice(0, 96) + '...');
    $('status-progress').title = 'Recent path: ' + recent;

    const list = $('property-list');
    list.textContent = '';
    for (const [expectation, name, discovery] of s.properties) {
        const li = el('li');
        let summary;
        if (discovery) {
            summary = expectation === 'Sometimes'
                ? '✅ Example found: '
                : '⚠️ Counterexample found: ';
        } else if (!s.done) {
            summary = '🔎 Searching: ';
        } else {
            summary = {
                Always: '✅ Safety holds: ',
                Sometimes: '⚠️ Example not found: ',
                Eventually: '✅ Liveness holds: ',
            }[expectation];
        }
        li.appendChild(el('b', {}, summary));
        const label = expectation + ' ' + name;
        li.appendChild(discovery
            ? el('a', {className: 'font-code',
                       href: '#/steps/' + discovery}, label)
            : el('span', {className: 'font-code'}, label));
        list.appendChild(li);
    }
}

function renderPath() {
    const list = $('path-list');
    list.textContent = '';
    const steps = pathSteps(farthest);
    steps.forEach((step, i) => {
        const li = el('li');
        const a = el('a', {
            className: 'font-code',
            href: '#/steps' + farthest.path
                + '?offset=' + (steps.length - 1 - i),
        }, step.action);
        if (step === selected) { a.classList.add('is-selected-state'); }
        else if (step.state === selected.state) {
            a.classList.add('is-same-state');
        }
        li.appendChild(a);
        list.appendChild(li);
    });
}

function renderNext() {
    const list = $('next-list');
    list.textContent = '';
    for (const step of selected.next || []) {
        const li = el('li');
        const a = el('a', {className: 'font-code'}, step.action);
        if (step.ignored) {
            a.classList.add('is-ignored');
            a.title = 'Action ignored by model';
        } else {
            a.href = '#/steps' + step.path;
        }
        if (step.state === selected.state) {
            a.classList.add('is-same-state');
        }
        li.appendChild(a);
        list.appendChild(li);
    }
}

function renderState() {
    const svgPane = $('svg-pane');
    if (selected.svg) {
        svgPane.innerHTML = selected.svg;
        svgPane.hidden = false;
    } else {
        svgPane.hidden = true;
    }
    const pane = $('state-pane');
    pane.style.whiteSpace =
        $('toggle-compact').checked ? 'normal' : 'pre-wrap';
    pane.textContent = $('toggle-complete').checked
        ? selected.state
        : (selected.outcome || selected.state);
}

function renderAll() {
    renderPath();
    renderNext();
    renderState();
}

// ------------------------------------------------------------ navigation --

async function prepareView() {
    const hash = window.location.hash || '#/steps';
    const [route, query] = hash.split('?');
    const parts = route.split('/'); // ['#', 'steps', fp, fp, ...]
    if (parts[1] !== 'steps') { return; }

    let step = PRE_INIT;
    for (const fp of parts.slice(2).filter(Boolean)) {
        const next = await fetchNext(step);
        const found = next.find((s) => s.fingerprint === fp);
        if (!found) { break; }
        step = found;
    }
    await fetchNext(step); // so "Next Action Choices" is populated
    farthest = step;
    selected = step;

    for (const pair of (query || '').split('&')) {
        const [key, value] = pair.split('=');
        if (key === 'offset') {
            for (let n = parseInt(value, 10); n > 0 && selected.prev; --n) {
                selected = selected.prev;
            }
            await fetchNext(selected);
        }
    }
    renderAll();
}

document.addEventListener('keydown', (ev) => {
    const steps = pathSteps(farthest);
    const index = steps.indexOf(selected);
    if (ev.key === 'ArrowUp' || ev.key === 'k') {
        const offset = Math.min(
            steps.length - 1 - index + 1, steps.length - 1);
        window.location = '#/steps' + farthest.path + '?offset=' + offset;
    } else if (ev.key === 'ArrowDown' || ev.key === 'j') {
        const offset = Math.max(steps.length - 1 - index - 1, 0);
        window.location = '#/steps' + farthest.path + '?offset=' + offset;
    }
});

$('toggle-complete').addEventListener('change', renderState);
$('toggle-compact').addEventListener('change', renderState);

async function refreshStatus() {
    try {
        const response = await fetch('/.status');
        const status = await response.json();
        renderStatus(status);
        if (!status.done) { setTimeout(refreshStatus, 5000); }
    } catch (err) {
        setTimeout(refreshStatus, 5000);
    }
}

// ------------------------------------------------------------- metrics --

function parseMetrics(text) {
    // Prometheus exposition text -> {name: value}; comment lines skipped.
    const m = {};
    for (const line of text.split('\n')) {
        if (!line || line.startsWith('#')) { continue; }
        const space = line.lastIndexOf(' ');
        if (space <= 0) { continue; }
        m[line.slice(0, space)] = parseFloat(line.slice(space + 1));
    }
    return m;
}

function renderMetrics(m) {
    const bits = [Math.round(m.stpu_states_per_sec || 0).toLocaleString()
                  + ' states/s'];
    if (m.stpu_table_load_factor !== undefined) {
        bits.push('load ' + m.stpu_table_load_factor.toFixed(3));
    }
    if (m.stpu_wave_seconds !== undefined) {
        bits.push((m.stpu_wave_seconds * 1000).toFixed(0) + ' ms/wave');
    }
    $('status-rate').textContent = bits.join(' · ');
}

async function refreshMetrics() {
    try {
        const response = await fetch('/.metrics');
        if (response.ok) {
            const m = parseMetrics(await response.text());
            renderMetrics(m);
            if (m.stpu_done) { return; }
        }
    } catch (err) { /* server gone or endpoint missing: retry */ }
    setTimeout(refreshMetrics, 2000);
}

// ------------------------------------------------------------------ ops --

// Service Ops panel: polls GET /.ops (round 18) every 5 s. Hidden
// until the server answers with at least one armed obs participant
// or an armed continuous profiler (round 20) — a fully disarmed run
// (no STpu_HIST/SLO/ANOMALY/PROF) never shows the panel.
function renderOps(ops) {
    const participants = ops.participants || {};
    const names = Object.keys(participants).sort();
    const prof = (ops.prof && ops.prof.programs
        && Object.keys(ops.prof.programs).length) ? ops.prof : null;
    const control = ops.control || null;
    if (!names.length && !prof && !control) { return false; }
    $('ops-heading').hidden = false;
    $('ops-pane').hidden = false;

    const health = $('ops-health');
    health.textContent = ops.healthy ? 'healthy' : 'SLO breach';
    health.className = ops.healthy ? 'badge-ok' : 'badge-bad';

    const rows = $('ops-rows');
    rows.textContent = '';
    const anomalies = $('ops-anomalies');
    anomalies.textContent = '';
    for (const name of names) {
        const p = participants[name];
        const hist = p.hist || {};
        for (const key of Object.keys(hist).sort()) {
            const h = hist[key];
            const tr = el('tr');
            tr.appendChild(el('td', {}, name));
            // wave_latency_seconds{engine="classic",...} -> the labels.
            const brace = key.indexOf('{');
            tr.appendChild(el('td', {title: key},
                brace >= 0 ? key.slice(brace) : key));
            tr.appendChild(el('td', {}, String(h.count)));
            tr.appendChild(el('td', {}, h.p50 === null ? '-'
                : (h.p50 * 1000).toFixed(1)));
            tr.appendChild(el('td', {}, h.p99 === null ? '-'
                : (h.p99 * 1000).toFixed(1)));
            rows.appendChild(tr);
        }
        for (const a of p.anomalies || []) {
            anomalies.appendChild(el('li', {className: 'is-anomaly'},
                '⚠ ' + name + ': slow wave (' + a.cause + ') '
                + (a.dur_s * 1000).toFixed(0) + ' ms vs baseline '
                + (a.baseline_s * 1000).toFixed(0) + ' ms'));
        }
    }
    // Continuous-profiler tile (round 20, STpu_PROF=1): one row per
    // compiled program — last sampled roofline rates and the
    // baseline-relative cost ratio, flagged when it drifts >=1.5x.
    if (prof) {
        $('prof-table').hidden = false;
        const profRows = $('prof-rows');
        profRows.textContent = '';
        const gig = (v) => (v === null || v === undefined)
            ? '-' : (v / 1e9).toFixed(2);
        for (const key of Object.keys(prof.programs).sort()) {
            const s = prof.programs[key];
            const tr = el('tr');
            tr.appendChild(el('td', {title: key},
                key.length > 28 ? key.slice(0, 28) + '…' : key));
            tr.appendChild(el('td', {}, String(s.snap || 0)));
            tr.appendChild(el('td', {}, gig(s.flops_per_s)));
            tr.appendChild(el('td', {}, gig(s.bytes_per_s)));
            const ratio = (s.cost_ratio === null
                || s.cost_ratio === undefined)
                ? '-' : s.cost_ratio.toFixed(2);
            tr.appendChild(el('td', {
                className: s.cost_ratio >= 1.5 ? 'is-anomaly' : ''},
                ratio));
            profRows.appendChild(tr);
        }
    }
    // Overload-controller tile (round 21, STpu_CONTROL=1): engaged/
    // normal badge, the current brownout rung with its action name,
    // and the shed/park/resume counters. Absent (null) when the
    // service runs disarmed — the tile stays hidden.
    if (control) {
        $('control-tile').hidden = false;
        const badge = $('control-badge');
        badge.textContent = control.engaged
            ? 'overload: engaged' : 'overload: normal';
        badge.className = control.engaged ? 'badge-bad' : 'badge-ok';
        $('control-rung').textContent = control.rung > 0
            ? ('rung ' + control.rung + ' (' + control.rung_action + ')')
            : '';
        $('control-counters').textContent =
            'shed ' + control.shed_total
            + ' · parked ' + control.park_total
            + ' · resumed ' + control.resume_total
            + ' · queue ' + control.queue_depth
            + (control.faults_survived
                ? ' · faults survived ' + control.faults_survived : '');
        $('control-parked').textContent =
            (control.parked && control.parked.length)
                ? ('parked now: ' + control.parked.join(', ')) : '';
    }
    return true;
}

async function refreshOps() {
    try {
        const response = await fetch('/.ops');
        if (response.ok) { renderOps(await response.json()); }
    } catch (err) { /* server gone or endpoint missing: retry */ }
    setTimeout(refreshOps, 5000);
}

window.onhashchange = prepareView;
prepareView();
refreshStatus();
refreshMetrics();
refreshOps();

#!/usr/bin/env python
"""Prints a per-worker table from a merged elastic trace or a
flight-recorder postmortem dump — plus a per-job table when the stream
carries the job-service lifecycle family (schema v7).

The one-command answer to "which worker was the problem": for every
participant in the stream — each elastic worker, the coordinator, and
any single-process engine runs sharing the file — one row with its
wave count, final cumulative states, throughput, barrier wait share
(folded from the coordinator's ``straggler`` events), and fault/loss
count::

    python tools/trace_summary.py run.trace.jsonl
    python tools/trace_summary.py stpu-postmortem-w1.jsonl

    participant        waves    states   states/s  p50_ms  p99_ms  wait%    io%  faults
    coordinator           37      1146      892.1     4.2    31.1      -      -       0
    w0                    37       601      511.0     3.9    15.6    3.1    0.8       0
    w1                    22       545      488.7     7.8    62.5   11.4      -       1

(``io%`` is the schema-v10 ``io_stall_s`` wave gauge — wave-loop
seconds spent blocked on host I/O — as a share of the participant's
wall-clock span; "-" on pre-v10 captures. ``p50_ms``/``p99_ms`` are
per-participant wave-latency quantiles: from the final v11
``hist_snapshot`` when the capture carries one — deterministic
bucket-upper-bound estimates over the fixed ``obs/hist.py`` ladder —
falling back to exact percentiles over the raw wave-event time gaps
for v10-and-older captures.)

With ``job_submit``/``job_done``/``job_abort`` events present (a job
service trace, or several jobs' traces concatenated) a second table
follows, one row per job::

    job          model     engine   outcome     states    unique   io_s    sec
    j-0001       twopc     classic  done           914       288   0.02    1.2
    j-0002       twopc     classic  preempted        -         -      -    0.4

With ``profile_snapshot`` events present (a schema-v13 capture made
with ``STpu_PROF=1``) a roofline table follows, one row per compiled
program — static cost-model flops/bytes from XLA's own
``cost_analysis()``, the achieved rates from the last sampled timing,
and the baseline-relative ``ratio`` that flags a program getting
slower over the run::

    program                              samples    flops     bytes  gflops/s    gb/s  intens  ratio
    classic|bdd11a0a|(64, 65536, 768)          8   193085   1494572      0.09    0.71    0.13   0.36

Works on anything the obs schema covers (v1..v5): rows degrade to "-"
where a stream predates the field. Dependency-free beyond
``stateright_tpu.obs.schema`` (no jax, no backend init) — safe against
a live capture. Exit status 1 when the input holds no events.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

#: ``trace_export.load_events`` twin kept inline: the summary must
#: stay importable on its own (the smoke test execs it standalone).


def load_events(path: str) -> List[dict]:
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                events.append(obj)
    return events


#: The obs/hist.py fixed bucket ladder, inlined so the tool stays
#: standalone (same 2^-20..2^6 power-of-two upper bounds).
_BUCKET_BOUNDS = tuple(2.0 ** e for e in range(-20, 7))


def _bucket_quantile(buckets: List[int], count: int, q: float):
    """``obs.hist.bucket_quantile`` twin: bucket-upper-bound estimate
    over non-cumulative counts; the +Inf bucket saturates to the last
    finite bound."""
    if count <= 0 or not buckets:
        return None
    rank = q * count
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= rank and c:
            return _BUCKET_BOUNDS[min(i, len(_BUCKET_BOUNDS) - 1)]
    return _BUCKET_BOUNDS[-1]


def _participant(evt: dict) -> str:
    worker = evt.get("worker")
    if isinstance(worker, str):
        return worker
    engine = evt.get("engine", "?")
    if engine == "elastic":
        return "coordinator"
    return f"{engine} {evt.get('run', '?')}"


def summarize(events: List[dict]) -> Dict[str, dict]:
    """Folds the stream into ``{participant: row}`` (see module
    docstring for the row fields)."""
    rows: Dict[str, dict] = {}

    def row(name: str) -> dict:
        return rows.setdefault(name, {
            "waves": 0, "states": None, "first_t": None, "last_t": None,
            "wait_s": 0.0, "compute_s": 0.0, "io_stall_s": 0.0,
            "faults": 0, "postmortem": None,
            # Wave-latency quantile sources: the final v11 snapshot's
            # wave_latency_seconds series (preferred), else raw wave
            # time gaps (v10-and-older fallback).
            "hist": {}, "gaps": []})

    for evt in events:
        etype = evt.get("type")
        if etype == "wave":
            r = row(_participant(evt))
            r["waves"] += 1
            stall = evt.get("io_stall_s")
            if isinstance(stall, (int, float)):
                r["io_stall_s"] += stall
            states = evt.get("states")
            if isinstance(states, int):
                # Runs rotate (migration rollback): keep the MAX seen,
                # not the last — totals rewind with a rollback.
                r["states"] = (states if r["states"] is None
                               else max(r["states"], states))
            t = evt.get("t")
            if isinstance(t, (int, float)):
                if r["first_t"] is None:
                    r["first_t"] = t
                elif (r["last_t"] is not None and t >= r["last_t"]):
                    # Fallback latency sample: the gap to this
                    # participant's previous wave (rotated runs share
                    # the lane, matching the export's slice semantic).
                    r["gaps"].append(t - r["last_t"])
                r["last_t"] = t
        elif etype == "hist_snapshot":
            # v11: cumulative snapshots — keep the largest-count
            # payload per series; quantiles come from the final one.
            r = row(_participant(evt))
            hists = evt.get("hists")
            if isinstance(hists, dict):
                for key, data in hists.items():
                    if not key.startswith("wave_latency_seconds") \
                            or not isinstance(data, dict):
                        continue
                    cur = r["hist"].get(key)
                    if (cur is None or data.get("count", 0)
                            >= cur.get("count", 0)):
                        r["hist"][key] = data
        elif etype == "straggler":
            for w, seg in (evt.get("workers") or {}).items():
                r = row(w)
                r["wait_s"] += float(seg.get("wait_s") or 0.0)
                r["compute_s"] += float(seg.get("compute_s") or 0.0)
        elif etype == "fault":
            worker = evt.get("worker")
            r = row(worker if isinstance(worker, str)
                    else _participant(evt))
            r["faults"] += 1
        elif etype == "worker_lost":
            worker = evt.get("worker")
            if isinstance(worker, str):
                row(worker)["faults"] += 1
                if evt.get("dump"):
                    row(worker)["postmortem"] = evt["dump"]
        elif etype == "postmortem":
            row(evt.get("name", "?"))["postmortem"] = "(this file)"
    return rows


def summarize_jobs(events: List[dict]) -> Dict[str, dict]:
    """Folds the v7 job lifecycle events into ``{job_id: row}``; empty
    when the stream carries no job family (pre-service traces)."""
    jobs: Dict[str, dict] = {}
    for evt in events:
        etype = evt.get("type")
        if etype == "wave":
            # v10: per-job I/O stall, folded from attributed mux wave
            # lines (job_id) sharing the stream. Jobs only seen here
            # (no lifecycle events) don't get a row — the table is the
            # lifecycle's, the stall column rides it.
            job_id = evt.get("job_id")
            stall = evt.get("io_stall_s")
            if (isinstance(job_id, str) and job_id in jobs
                    and isinstance(stall, (int, float))):
                jobs[job_id]["io_stall_s"] += stall
            continue
        job = evt.get("job")
        if etype not in ("job_submit", "job_done", "job_abort") \
                or not isinstance(job, str):
            continue
        r = jobs.setdefault(job, {
            "model": "-", "engine": "-", "outcome": "lost",
            "states": None, "unique": None, "io_stall_s": 0.0,
            "submit_t": None, "end_t": None})
        t = evt.get("t")
        if etype == "job_submit":
            r["model"] = evt.get("model", "-")
            r["engine"] = evt.get("job_engine", "-")
            if isinstance(t, (int, float)):
                r["submit_t"] = t
        elif etype == "job_done":
            r["outcome"] = "done"
            r["states"] = evt.get("states")
            r["unique"] = evt.get("unique")
            if isinstance(t, (int, float)):
                r["end_t"] = t
        else:  # job_abort
            r["outcome"] = str(evt.get("reason", "abort"))
            if isinstance(t, (int, float)):
                r["end_t"] = t
    return jobs


def summarize_control(events: List[dict]) -> Optional[dict]:
    """Folds the v14 overload-control family into one summary row:
    shed counts by reason, admit-under-pressure count, park/resume
    pairing, and the brownout rung walk (every edge-triggered
    transition, in stream order). ``None`` when the stream carries no
    control events (disarmed or pre-v14 captures)."""
    out = {"sheds": {}, "admitted_under_pressure": 0, "parks": 0,
           "resumes": 0, "rung_walk": []}
    seen = False
    for evt in events:
        etype = evt.get("type")
        if etype == "shed":
            seen = True
            reason = str(evt.get("reason", "?"))
            out["sheds"][reason] = out["sheds"].get(reason, 0) + 1
        elif etype == "admit":
            seen = True
            out["admitted_under_pressure"] += 1
        elif etype == "park":
            seen = True
            out["parks"] += 1
        elif etype == "resume":
            seen = True
            out["resumes"] += 1
        elif etype == "controller":
            seen = True
            out["rung_walk"].append(
                (evt.get("rung"), str(evt.get("action", "?"))))
    return out if seen else None


def format_control(ctl: dict) -> str:
    sheds = ", ".join(f"{reason}={n}"
                      for reason, n in sorted(ctl["sheds"].items())) \
        or "none"
    walk = " -> ".join(f"{rung}:{action}"
                       for rung, action in ctl["rung_walk"]) or "flat"
    return (f"overload control: sheds [{sheds}] "
            f"admitted-under-pressure={ctl['admitted_under_pressure']} "
            f"parks={ctl['parks']} resumes={ctl['resumes']}\n"
            f"  brownout walk: {walk}")


def summarize_prof(events: List[dict]) -> Dict[str, dict]:
    """Folds the v13 ``profile_snapshot`` family into ``{program key:
    row}`` — the LAST snapshot per key wins (the gauges are
    baseline-relative, so the final one is the run's verdict) with a
    running sample count. Empty on pre-v13 or disarmed captures."""
    progs: Dict[str, dict] = {}
    for evt in events:
        if evt.get("type") != "profile_snapshot":
            continue
        key = str(evt.get("key", "?"))
        r = progs.setdefault(key, {"samples": 0})
        r["samples"] += 1
        for field in ("flops", "bytes", "flops_per_s", "bytes_per_s",
                      "intensity", "cost_ratio", "measured_s"):
            val = evt.get(field)
            if isinstance(val, (int, float)):
                r[field] = val
    return progs


def format_prof_table(progs: Dict[str, dict]) -> str:
    header = (f"{'program':<36} {'samples':>7} {'flops':>10} "
              f"{'bytes':>10} {'gflops/s':>9} {'gb/s':>7} "
              f"{'intens':>7} {'ratio':>6}")
    lines = [header, "-" * len(header)]

    def num(r, field, scale=1.0, fmt="{:.2f}"):
        val = r.get(field)
        return fmt.format(val / scale) if val is not None else "-"

    for key, r in sorted(progs.items()):
        lines.append(
            f"{key:<36} {r['samples']:>7} "
            f"{num(r, 'flops', fmt='{:.0f}'):>10} "
            f"{num(r, 'bytes', fmt='{:.0f}'):>10} "
            f"{num(r, 'flops_per_s', 1e9):>9} "
            f"{num(r, 'bytes_per_s', 1e9):>7} "
            f"{num(r, 'intensity'):>7} "
            f"{num(r, 'cost_ratio'):>6}")
    return "\n".join(lines)


def format_job_table(jobs: Dict[str, dict]) -> str:
    header = (f"{'job':<14} {'model':<12} {'engine':<9} {'outcome':<11} "
              f"{'states':>9} {'unique':>9} {'io_s':>6} {'sec':>7}")
    lines = [header, "-" * len(header)]
    for job, r in sorted(jobs.items()):
        sec = ("-" if r["submit_t"] is None or r["end_t"] is None
               else f"{r['end_t'] - r['submit_t']:.1f}")
        states = r["states"] if r["states"] is not None else "-"
        unique = r["unique"] if r["unique"] is not None else "-"
        io = (f"{r['io_stall_s']:.2f}" if r["io_stall_s"] > 0 else "-")
        lines.append(f"{job:<14} {r['model']:<12} {r['engine']:<9} "
                     f"{r['outcome']:<11} {states:>9} {unique:>9} "
                     f"{io:>6} {sec:>7}")
    return "\n".join(lines)


def _latency_quantiles(r: dict):
    """(p50_s, p99_s) for one participant row — final-snapshot bucket
    estimates when the capture is v11, exact gap percentiles otherwise,
    ``(None, None)`` when the row carries neither."""
    if r["hist"]:
        # Merge the participant's series (one per kernel_path)
        # element-wise; the estimate stays deterministic.
        merged: List[int] = []
        count = 0
        for data in r["hist"].values():
            buckets = data.get("buckets") or []
            if len(buckets) > len(merged):
                merged.extend([0] * (len(buckets) - len(merged)))
            for i, c in enumerate(buckets):
                merged[i] += int(c)
            count += int(data.get("count", 0))
        return (_bucket_quantile(merged, count, 0.5),
                _bucket_quantile(merged, count, 0.99))
    if r["gaps"]:
        gaps = sorted(r["gaps"])
        def pct(q):
            idx = min(len(gaps) - 1, max(0, int(q * len(gaps) + 0.5) - 1))
            return gaps[idx]
        return pct(0.5), pct(0.99)
    return None, None


def format_table(rows: Dict[str, dict]) -> str:
    header = (f"{'participant':<24} {'waves':>6} {'states':>9} "
              f"{'states/s':>10} {'p50_ms':>7} {'p99_ms':>7} "
              f"{'wait%':>6} {'io%':>6} {'faults':>6}")
    lines = [header, "-" * len(header)]
    # Coordinator first, then workers, then whatever else shared the
    # stream.
    def order(item):
        name = item[0]
        return (0 if name == "coordinator" else
                1 if " " not in name else 2, name)

    for name, r in sorted(rows.items(), key=order):
        span = ((r["last_t"] - r["first_t"])
                if r["first_t"] is not None and r["last_t"] is not None
                else 0.0)
        rate = (f"{r['states'] / span:.1f}"
                if r["states"] and span > 0 else "-")
        busy = r["wait_s"] + r["compute_s"]
        wait = f"{100.0 * r['wait_s'] / busy:.1f}" if busy > 0 else "-"
        # I/O stall share of this participant's wall-clock span (the
        # v10 gauge; "-" on pre-v10 captures where the field is null).
        io = (f"{100.0 * r['io_stall_s'] / span:.1f}"
              if r["io_stall_s"] > 0 and span > 0 else "-")
        states = r["states"] if r["states"] is not None else "-"
        p50, p99 = _latency_quantiles(r)
        p50 = f"{p50 * 1000.0:.1f}" if p50 is not None else "-"
        p99 = f"{p99 * 1000.0:.1f}" if p99 is not None else "-"
        lines.append(f"{name:<24} {r['waves']:>6} {states:>9} "
                     f"{rate:>10} {p50:>7} {p99:>7} "
                     f"{wait:>6} {io:>6} {r['faults']:>6}")
        if r["postmortem"]:
            lines.append(f"{'':<24}   postmortem: {r['postmortem']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="print a per-worker summary table from a merged "
                    "STpu_TRACE capture or a flight-recorder "
                    "postmortem dump")
    ap.add_argument("path", help="JSONL trace or postmortem file")
    args = ap.parse_args(argv)

    events = load_events(args.path)
    if not events:
        print(f"no events in {args.path}", file=sys.stderr)
        return 1
    rows = summarize(events)
    print(format_table(rows))
    jobs = summarize_jobs(events)
    if jobs:
        print()
        print(format_job_table(jobs))
    progs = summarize_prof(events)
    if progs:
        print()
        print(format_prof_table(progs))
    ctl = summarize_control(events)
    if ctl is not None:
        print()
        print(format_control(ctl))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""CLI + helper library for the checking service's job API
(``stateright_tpu.explorer.serve_service``).

The one client tests and docs use — no hand-rolled curl::

    python tools/service_client.py corpus  --url http://127.0.0.1:3000
    python tools/service_client.py submit  --url ... --model twopc \\
        --param rm_count=5 --engine classic --knob batch_size=256 --wait
    python tools/service_client.py submit  --url ... --model twopc \\
        --priority 2 --deadline-ms 1500 --retry-budget 3
    python tools/service_client.py status  --url ... j-0001
    python tools/service_client.py list    --url ...
    python tools/service_client.py trace   --url ... j-0001 --tail 10
    python tools/service_client.py preempt --url ... j-0001
    python tools/service_client.py resume  --url ... j-0001 --wait

Round 21 (overload control): a 429 from the service is an admission
DECISION, not an error — :func:`submit` returns its structured body
(``{"shed": True, "reason": ..., "retry_after_s": ...}``) with the
server's ``Retry-After`` surfaced, instead of raising. ``--priority``
and ``--deadline-ms`` pass the scheduling fields through, and
``--retry-budget N`` makes the CLI an OBEDIENT overload citizen: on a
shed it sleeps the server's Retry-After and re-submits, at most N
times — exactly the client behavior the controller's per-tenant token
buckets assume. Budget 0 (default) reports the shed and exits 2.

Dependency-free (urllib only) so it runs anywhere the repo does; the
functions return decoded payloads and raise :class:`ServiceError` with
the server's message on any other non-2xx answer.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional

__all__ = ["ServiceError", "request", "submit", "status", "jobs",
           "trace_lines", "preempt", "resume", "corpus", "wait_for",
           "submit_with_retry"]


class ServiceError(RuntimeError):
    def __init__(self, http_status: int, message: str):
        super().__init__(f"HTTP {http_status}: {message}")
        self.http_status = http_status
        self.message = message


def request(base: str, path: str, method: str = "GET",
            body: Optional[dict] = None, timeout: float = 30.0):
    """One API round trip; returns the decoded JSON payload (or raw
    text for non-JSON responses like the trace stream). A 429 answer
    returns a dict with ``shed: True``, the structured reason the
    server gave (when it gave one), and ``retry_after_s`` from the
    ``Retry-After`` header or body — admission control is an expected
    outcome the caller handles, not an exception."""
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        base.rstrip("/") + path, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            ctype = resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:
        text = e.read().decode(errors="replace")
        if e.code == 429:
            try:
                payload = json.loads(text)
            except ValueError:
                payload = {"error": text}
            if not isinstance(payload, dict):
                payload = {"error": payload}
            payload["shed"] = True
            header = e.headers.get("Retry-After")
            if payload.get("retry_after_s") is None:
                try:
                    payload["retry_after_s"] = float(header)
                except (TypeError, ValueError):
                    pass
            return payload
        raise ServiceError(e.code, text) from e
    if ctype.startswith("application/json"):
        return json.loads(raw)
    return raw.decode(errors="replace")


def submit(base: str, spec: dict) -> dict:
    """Submits one job. Returns the status payload, or a
    ``{"shed": True, ...}`` dict when admission control rejected it —
    check for the ``shed`` key before reading job fields."""
    return request(base, "/jobs", method="POST", body=spec)


def submit_with_retry(base: str, spec: dict, retry_budget: int = 0,
                      sleep=time.sleep) -> dict:
    """Submits, honoring sheds like a well-behaved client: on a 429 it
    waits the server's ``Retry-After`` and re-submits, at most
    ``retry_budget`` times; the final payload (admitted OR still shed)
    is returned. ``sleep`` is injectable for tests."""
    payload = submit(base, spec)
    tries = 0
    while payload.get("shed") and tries < retry_budget:
        sleep(float(payload.get("retry_after_s") or 1.0))
        payload = submit(base, spec)
        tries += 1
    return payload


def status(base: str, job_id: str) -> dict:
    return request(base, f"/jobs/{job_id}")


def jobs(base: str) -> list:
    return request(base, "/jobs")


def trace_lines(base: str, job_id: str,
                tail: Optional[int] = None) -> List[str]:
    text = request(base, f"/jobs/{job_id}/trace")
    lines = [ln for ln in text.splitlines() if ln.strip()]
    return lines[-tail:] if tail else lines


def preempt(base: str, job_id: str) -> dict:
    return request(base, f"/jobs/{job_id}", method="DELETE")


def resume(base: str, job_id: str) -> dict:
    return submit(base, {"resume": job_id})


def corpus(base: str) -> list:
    return request(base, "/.corpus")


def wait_for(base: str, job_id: str, timeout: float = 600.0,
             poll_s: float = 0.5) -> dict:
    """Polls until the job leaves queued/running; returns the final
    status payload."""
    deadline = time.monotonic() + timeout
    while True:
        payload = status(base, job_id)
        if payload["state"] not in ("queued", "running"):
            return payload
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"job {job_id} still {payload['state']} after "
                f"{timeout:.0f}s")
        time.sleep(poll_s)


def _kv_pairs(pairs: List[str], what: str) -> dict:
    out = {}
    for pair in pairs or []:
        key, sep, value = pair.partition("=")
        if not sep:
            raise SystemExit(f"--{what} expects key=value, got {pair!r}")
        # JSON-decode where possible so ints/bools arrive typed.
        try:
            out[key] = json.loads(value)
        except ValueError:
            out[key] = value
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="client for the checking service job API")
    ap.add_argument("--url", default="http://127.0.0.1:3000",
                    help="service base URL")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("submit", help="submit a job")
    sp.add_argument("--model", required=True)
    sp.add_argument("--param", action="append", metavar="K=V")
    sp.add_argument("--engine", default="classic",
                    choices=("classic", "fused", "host"))
    sp.add_argument("--knob", action="append", metavar="K=V")
    sp.add_argument("--property", action="append", dest="properties",
                    help="restrict reported verdicts to these names")
    sp.add_argument("--priority", type=int, default=None,
                    help="scheduling priority (higher pops first; "
                         "under overload the controller sheds the "
                         "lowest priorities first)")
    sp.add_argument("--deadline-ms", type=int, default=None,
                    help="declare a completion deadline; the overload "
                         "controller may park a long batch job to "
                         "protect it")
    sp.add_argument("--tenant", default=None,
                    help="tenant label (running quotas + per-tenant "
                         "retry budgets key on it)")
    sp.add_argument("--retry-budget", type=int, default=0,
                    help="on a 429 shed, wait the server's "
                         "Retry-After and re-submit up to N times "
                         "(default 0: report the shed and exit 2)")
    sp.add_argument("--wait", action="store_true")

    for name, needs_id in (("status", True), ("preempt", True),
                           ("resume", True), ("trace", True),
                           ("list", False), ("corpus", False)):
        p = sub.add_parser(name)
        if needs_id:
            p.add_argument("job_id")
        if name == "trace":
            p.add_argument("--tail", type=int, default=None)
        if name == "resume":
            p.add_argument("--wait", action="store_true")

    args = ap.parse_args(argv)
    base = args.url
    try:
        if args.cmd == "submit":
            spec = {"model": args.model,
                    "params": _kv_pairs(args.param, "param"),
                    "engine": args.engine,
                    "knobs": _kv_pairs(args.knob, "knob")}
            if args.properties:
                spec["properties"] = args.properties
            if args.priority is not None:
                spec["priority"] = args.priority
            if args.deadline_ms is not None:
                spec["deadline_s"] = args.deadline_ms / 1000.0
            if args.tenant is not None:
                spec["tenant"] = args.tenant
            payload = submit_with_retry(base, spec,
                                        retry_budget=args.retry_budget)
            if payload.get("shed"):
                print(json.dumps(payload, indent=2))
                return 2
            if args.wait:
                payload = wait_for(base, payload["id"])
        elif args.cmd == "status":
            payload = status(base, args.job_id)
        elif args.cmd == "list":
            payload = jobs(base)
        elif args.cmd == "corpus":
            payload = corpus(base)
        elif args.cmd == "preempt":
            payload = preempt(base, args.job_id)
        elif args.cmd == "resume":
            payload = resume(base, args.job_id)
            if payload.get("shed"):
                print(json.dumps(payload, indent=2))
                return 2
            if args.wait:
                payload = wait_for(base, payload["id"])
        else:  # trace
            for line in trace_lines(base, args.job_id, tail=args.tail):
                print(line)
            return 0
    except ServiceError as e:
        print(e, file=sys.stderr)
        return 1
    print(json.dumps(payload, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

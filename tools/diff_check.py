#!/usr/bin/env python
"""CLI for the corpus differential fuzz gate
(``stateright_tpu/service/diff.py``): replays seeded random schedules
of a registered model against the host semantics and (optionally) runs
the end-to-end engine parity check — the admission test every corpus
addition passes before the service serves it::

    python tools/diff_check.py vsr --param n=2 --seeds 8 --steps 50
    python tools/diff_check.py twopc --no-full       # walks only
    python tools/diff_check.py --all --steps 25      # whole corpus

Exit 1 on the first mismatch, with the offending state and successor
sets in the message.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="differentially fuzz a corpus model's device form "
                    "against the host checker")
    ap.add_argument("model", nargs="?",
                    help="corpus model name (see --all / the registry)")
    ap.add_argument("--all", action="store_true",
                    help="gate every registered model")
    ap.add_argument("--param", action="append", metavar="K=V",
                    help="model parameter override")
    ap.add_argument("--seeds", type=int, default=4,
                    help="number of random schedules (default 4)")
    ap.add_argument("--steps", type=int, default=40,
                    help="steps per schedule (default 40)")
    ap.add_argument("--no-full", action="store_true",
                    help="skip the end-to-end engine parity check")
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args(argv)

    from stateright_tpu.service.diff import DiffMismatch, fuzz_gate
    from stateright_tpu.service.registry import default_registry

    registry = default_registry()
    if args.all:
        names = registry.names()
    elif args.model:
        names = [args.model]
    else:
        ap.error("name a model or pass --all")

    params = {}
    for pair in args.param or []:
        key, sep, value = pair.partition("=")
        if not sep:
            ap.error(f"--param expects key=value, got {pair!r}")
        try:
            params[key] = json.loads(value)
        except ValueError:
            params[key] = value
    if args.all and params:
        # Parameters are model-specific; a corpus-wide sweep with a
        # param would reject every model that lacks the key.
        ap.error("--param only applies to a single named model")

    failed = 0
    for name in names:
        try:
            result = fuzz_gate(
                name, registry=registry,
                params=params or None,
                seeds=tuple(range(args.seeds)), steps=args.steps,
                full=not args.no_full, batch_size=args.batch_size)
        except DiffMismatch as e:
            print(f"FAIL {name}: {e}", file=sys.stderr)
            failed += 1
            continue
        except ValueError as e:
            # A bad parameter set is a per-model failure, not a sweep
            # abort.
            print(f"FAIL {name}: {e}", file=sys.stderr)
            failed += 1
            continue
        transitions = sum(w["transitions"] for w in result["walks"])
        line = (f"OK {name} params={result['params']} "
                f"walks={len(result['walks'])} "
                f"transitions={transitions}")
        parity = result.get("engine_parity")
        if parity:
            line += (f" unique={parity['device_unique']} "
                     f"states={parity['device_states']}")
        print(line)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Validates a JSONL telemetry stream against the obs schema.

Accepts both families sharing the stream format:

- an ``STpu_TRACE`` capture (trace events: ``run_start`` / ``wave`` /
  ``span`` / ``counter`` / ``gauge`` / ``grow`` /
  ``overflow_redispatch`` / ``run_end``), and
- a ``tools/device_session.py`` stdout capture (session events:
  ``init`` / ``sweep`` / ``done`` / ... — versioned and timestamped by
  the same rules).

Used by the tier-1 suite (``tests/test_obs_trace.py``) and runnable
standalone::

    python tools/trace_lint.py trace.jsonl            # exit 1 on errors
    python tools/trace_lint.py --quiet trace.jsonl    # summary only

Beyond per-line schema validation it checks four stream-level
invariants: wave indices are contiguous per run, cumulative
``states``/``unique`` never decrease within a run (a truncated or
interleaved-corrupt file trips these even when every line parses),
every ``fault`` event (an ``STpu_FAULTS`` injection firing, or an
observed failure) is eventually followed by a ``recover``/``retry`` or
a terminal ``abort`` — an unrecovered fault at end-of-stream is
exactly the silent-death mode the resilience subsystem exists to rule
out — and the membership invariant (schema v4): every ``worker_lost``
is eventually followed by a ``migrate_done`` or a terminal ``abort``,
so a lost worker whose partitions were never rebuilt anywhere cannot
pass a lint.

Dependency-free beyond ``stateright_tpu.obs.schema`` (no jax, no
backend init) — safe to run against a capture while a measurement
session holds the accelerator.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from stateright_tpu.obs.schema import (SCHEMA_VERSION,  # noqa: E402
                                       validate_event)


def _too_new(obj) -> bool:
    """An event stamped by a NEWER schema than this validator knows.
    ``validate_event`` reports it with one clear upgrade message (no
    field-set mismatch cascade); the stream-invariant checks skip such
    events too — their field semantics may have changed."""
    ver = obj.get("schema_version") if isinstance(obj, dict) else None
    return isinstance(ver, int) and ver > SCHEMA_VERSION


def lint_lines(lines) -> Tuple[Dict[str, int], List[str]]:
    """Validates an iterable of JSONL lines; returns
    ``(counts_by_kind, errors)``. ``counts_by_kind`` tallies event
    types (trace family) and event names (session family), plus a
    ``runs`` entry."""
    counts: Dict[str, int] = {}
    errors: List[str] = []
    last_wave: Dict[str, int] = {}
    last_counts: Dict[str, Tuple[int, int]] = {}
    runs = set()
    # Resilience pairing: faults awaiting a later recover/retry/abort.
    # A recover (or a supervisor retry record, schema v4) retires the
    # oldest outstanding fault (one recovery per failure); a terminal
    # abort retires every outstanding fault (the supervisor gave up —
    # the stream ends acknowledged, not silent). Recoveries with no
    # preceding fault are fine: organic failures (no injection)
    # recover through the same path. Deliberately STREAM-GLOBAL, not
    # per run: a fault fires inside an engine run while its recovery
    # is emitted by the SUPERVISOR's (or the bench parent's) own
    # tracer — different run ids by construction, so there is no join
    # key. The cost is a known approximation: with two concurrent
    # supervised runs in one file, one run's recover can retire the
    # other's fault. The membership invariant works the same way:
    # worker_lost events await a later migrate_done (or the terminal
    # abort) — a lost worker whose partitions never landed anywhere is
    # an unrecovered loss.
    open_faults: List[Tuple[int, str]] = []
    open_losses: List[Tuple[int, str]] = []
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            errors.append(f"line {lineno}: invalid JSON: {e}")
            continue
        for err in validate_event(obj):
            errors.append(f"line {lineno}: {err}")
        if not isinstance(obj, dict):
            continue
        kind = obj.get("type") or f"session:{obj.get('event')}"
        counts[kind] = counts.get(kind, 0) + 1
        run = obj.get("run")
        if run:
            runs.add(run)
        if _too_new(obj):
            continue
        etype = obj.get("type")
        if etype == "fault":
            open_faults.append((lineno, str(obj.get("point"))))
        elif etype in ("recover", "retry"):
            if open_faults:
                open_faults.pop(0)
        elif etype == "worker_lost":
            open_losses.append((lineno, str(obj.get("worker"))))
        elif etype == "migrate_done":
            if open_losses:
                open_losses.pop(0)
        elif etype == "abort":
            open_faults.clear()
            open_losses.clear()
        if etype == "wave" and isinstance(run, str):
            idx = obj.get("wave")
            if isinstance(idx, int):
                expect = last_wave.get(run, -1) + 1
                if idx != expect:
                    errors.append(
                        f"line {lineno}: run {run}: wave index {idx}, "
                        f"expected {expect} (stream gap or reorder)")
                last_wave[run] = idx
            states, unique = obj.get("states"), obj.get("unique")
            if isinstance(states, int) and isinstance(unique, int):
                ps, pu = last_counts.get(run, (0, 0))
                if states < ps or unique < pu:
                    errors.append(
                        f"line {lineno}: run {run}: cumulative counts "
                        f"went backwards (states {ps}->{states}, "
                        f"unique {pu}->{unique})")
                last_counts[run] = (states, unique)
    for lineno, point in open_faults:
        errors.append(
            f"line {lineno}: fault {point!r} is never followed by a "
            "recover or terminal abort in the stream (unrecovered "
            "failure)")
    for lineno, worker in open_losses:
        errors.append(
            f"line {lineno}: worker_lost {worker!r} is never followed "
            "by a migrate_done or terminal abort in the stream (lost "
            "partitions were never rebuilt)")
    counts["runs"] = len(runs)
    return counts, errors


def lint_file(path: str) -> Tuple[Dict[str, int], List[str]]:
    with open(path, encoding="utf-8") as f:
        return lint_lines(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a JSONL telemetry stream (STpu_TRACE "
                    "capture or device_session stdout) against the obs "
                    "schema")
    ap.add_argument("path", help="JSONL file to validate")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress individual errors (summary only)")
    ap.add_argument("--max-errors", type=int, default=20,
                    help="errors to print before truncating (default 20)")
    args = ap.parse_args(argv)

    counts, errors = lint_file(args.path)
    total = sum(v for k, v in counts.items() if k != "runs")
    if not args.quiet:
        for err in errors[:args.max_errors]:
            print(err, file=sys.stderr)
        if len(errors) > args.max_errors:
            print(f"... and {len(errors) - args.max_errors} more",
                  file=sys.stderr)
    breakdown = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    if errors:
        print(f"FAIL: {len(errors)} error(s) in {total} event(s) "
              f"({breakdown})")
        return 1
    print(f"OK: {total} event(s) valid ({breakdown})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Validates a JSONL telemetry stream against the obs schema.

Accepts both families sharing the stream format:

- an ``STpu_TRACE`` capture (trace events: ``run_start`` / ``wave`` /
  ``span`` / ``counter`` / ``gauge`` / ``grow`` /
  ``overflow_redispatch`` / ``run_end``), and
- a ``tools/device_session.py`` stdout capture (session events:
  ``init`` / ``sweep`` / ``done`` / ... — versioned and timestamped by
  the same rules).

Used by the tier-1 suite (``tests/test_obs_trace.py``) and runnable
standalone::

    python tools/trace_lint.py trace.jsonl            # exit 1 on errors
    python tools/trace_lint.py --quiet trace.jsonl    # summary only

Beyond per-line schema validation it checks these stream-level
invariants: wave indices are contiguous per run, cumulative
``states``/``unique`` never decrease within a run (a truncated or
interleaved-corrupt file trips these even when every line parses),
every ``fault`` event (an ``STpu_FAULTS`` injection firing, or an
observed failure) is eventually followed by a ``recover``/``retry`` or
a terminal ``abort`` — an unrecovered fault at end-of-stream is
exactly the silent-death mode the resilience subsystem exists to rule
out — and the membership invariant (schema v4): every ``worker_lost``
is eventually followed by a ``migrate_done`` or a terminal ``abort``,
so a lost worker whose partitions were never rebuilt anywhere cannot
pass a lint.

Schema v5 (the merged distributed stream) adds three more: per-worker
``seq`` values are strictly increasing in file order (the collector's
merge contract — ``seq`` never resets, even across the migration
tracer-run rotation, so this check spans rotations); every
``elastic_worker`` wave event carries its ``worker``/``seq``/``round``
attribution and every ``elastic`` coordinator wave its
``epoch``/``round``; and faults that name a ``worker`` pair PER
WORKER — a worker-tagged fault is retired by the ``migrate_done``
that rebuilds that worker's partitions (matched through its
``worker_lost``), not by whichever recovery happens to come first, so
two concurrent casualties cannot retire each other's faults. Flight-
recorder postmortem dumps (``obs/flight.py``) are valid input too —
their ``postmortem`` header is schema v5.

Schema v8 (the single-kernel wave) adds only nullable wave fields
(``kernel_path``/``rows``) — no new stream invariant; the field-set
exactness check picks them up through the versioned field map.

Schema v9 (cross-job wave multiplexing) adds the per-run attribution
window: a mux TOTAL wave (``job_id`` null, ``jobs_in_wave`` = J) must
be followed by exactly J attributed waves (``job_id`` set, same
``jobs_in_wave``) whose ``successors``/``candidates``/``novel`` deltas
sum to the total's, before the next total, any solo wave, or the run's
end — per-job attribution that doesn't add up to the device dispatch
is fabricated accounting. Attributed waves with NO open window are
fine: a per-JOB trace file carries only its own tenant's attributed
lines (its deltas sum across files, not within one).

Schema v10 (asynchronous host I/O) adds the checkpoint-generation
pairing: every ``ckpt_begin`` is eventually followed by a ``ckpt_done``
(retired oldest-first within its run — the writer is FIFO), or
explained by a ``fault``/``abort`` (a background write that died
surfaces at the next safe point, so the begin it interrupted is
accounted for, not silent). A run must not END with a generation still
open — judged at end-of-stream, not at the ``run_end`` itself, because
fault and Supervisor events ride their own tracers (own run ids, own
flush buffers) and can land in the merged file on either side of the
begin they explain. Additionally each run's summed ``io_stall_s`` wave
gauge must fit
inside its ``run_end`` duration window — stall seconds are wall-clock
subsets of the run, so a sum exceeding the run length is fabricated
accounting.

Schema v11 (service-level observability) adds the histogram-snapshot
invariants: ``hist_snapshot`` events are cumulative-by-construction,
so per run the ``snap`` index strictly increases, and per
(run, series) the non-cumulative bucket counts must sum exactly to the
series ``count`` while ``count`` and ``sum`` are monotone
non-decreasing across snapshots (a shrinking histogram is a truncated
or re-ordered stream — real histograms only ever accumulate). These
checks hold in postmortem dumps too: a ring window may DROP snapshots,
but the survivors still only grow.

Schema v13 (the continuous wave profiler) adds the profile-snapshot
invariants: per run the ``snap`` ordinal strictly increases (sampling
is a per-producer counter, so a reordered or interleaved-corrupt merge
trips it — in postmortem dumps too, where a ring may DROP snapshots
but never reorders them); every snapshot's ``measured_s`` and
``cost_ratio`` are finite and positive (the ratio is defined against
the program's own first sampled baseline, which makes a non-finite or
non-positive value fabricated by construction); and where a snapshot
carries both ``flops`` and ``bytes``, its ``intensity`` gauge must be
their quotient to rounding — roofline coordinates that disagree with
their own cost model are fabricated accounting. Wave events gain the
nullable ``cost_flops``/``cost_bytes``/``cost_ratio`` fields, picked
up by the versioned field-set exactness check; v12 and older captures
still lint under their own field maps.

Schema v7 (the job service) adds the per-job pairing invariant: every
``job_submit`` is eventually followed by a ``job_done`` or
``job_abort`` carrying the SAME ``job`` id — unlike the fault pairing
this one has an exact join key, so concurrent jobs in one stream can
never retire each other's submissions. A stream that ends with a job
neither finished nor acknowledged (preempt/failure) lost work.

Schema v14 (the overload controller) adds the control-stream
invariants: every ``shed`` carries a machine-readable ``reason`` from
the declared vocabulary and a positive ``retry_after_s`` (a shed the
operator can't attribute, or a 429 with no honest retry hint, is a
policy decision the stream failed to explain); every ``park`` is
eventually followed by a ``resume`` or a terminal ``job_abort`` for
the SAME job id (exact join key — parked work is work the controller
OWES back, and a stream that ends still holding a park lost it), and a
``resume`` must name a ``resumed_as`` continuation distinct from the
parked job; ``controller`` brownout-ladder events are edge-triggered —
consecutive events in one run must CHANGE ``rung`` (a repeated rung is
level-triggered spam), with ``kept`` equal to the ``rung`` actually
reported and never exceeding ``requested`` (the round-10
requested/kept honesty rule applied to degradation steps).

Schema v6 (the tiered state store) adds three more: every FRONTIER
``spill`` is eventually followed by a ``page_in`` or the producing
run's end (a stream that stops with paged-out frontier blocks
outstanding lost work); per-run per-tier byte gauges
(``tier_*_bytes`` on wave events) are monotone non-decreasing between
``pressure`` resets; and the host-store producers (host BFS/DFS, the
elastic runtime) must carry real ``capacity``/``load_factor``/
``out_rows`` occupancy gauges — the permanent-null allowance is
withdrawn for v6+ captures. v5 and older captures still lint under
their own rules.

Dependency-free beyond ``stateright_tpu.obs.schema`` (no jax, no
backend init) — safe to run against a capture while a measurement
session holds the accelerator.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from stateright_tpu.obs.schema import (SCHEMA_VERSION,  # noqa: E402
                                       SHED_REASONS, validate_event)


def _too_new(obj) -> bool:
    """An event stamped by a NEWER schema than this validator knows.
    ``validate_event`` reports it with one clear upgrade message (no
    field-set mismatch cascade); the stream-invariant checks skip such
    events too — their field semantics may have changed."""
    ver = obj.get("schema_version") if isinstance(obj, dict) else None
    return isinstance(ver, int) and ver > SCHEMA_VERSION


def lint_lines(lines) -> Tuple[Dict[str, int], List[str]]:
    """Validates an iterable of JSONL lines; returns
    ``(counts_by_kind, errors)``. ``counts_by_kind`` tallies event
    types (trace family) and event names (session family), plus a
    ``runs`` entry."""
    counts: Dict[str, int] = {}
    errors: List[str] = []
    last_wave: Dict[str, int] = {}
    last_counts: Dict[str, Tuple[int, int]] = {}
    runs = set()
    # Resilience pairing: faults awaiting a later recover/retry/abort.
    # A recover (or a supervisor retry record, schema v4) retires the
    # oldest outstanding fault (one recovery per failure); a terminal
    # abort retires every outstanding fault (the supervisor gave up —
    # the stream ends acknowledged, not silent). Recoveries with no
    # preceding fault are fine: organic failures (no injection)
    # recover through the same path. Deliberately STREAM-GLOBAL, not
    # per run: a fault fires inside an engine run while its recovery
    # is emitted by the SUPERVISOR's (or the bench parent's) own
    # tracer — different run ids by construction, so there is no join
    # key. The cost is a known approximation: with two concurrent
    # supervised runs in one file, one run's recover can retire the
    # other's fault. The membership invariant works the same way:
    # worker_lost events await a later migrate_done (or the terminal
    # abort) — a lost worker whose partitions never landed anywhere is
    # an unrecovered loss.
    open_faults: List[Tuple[int, str]] = []
    open_losses: List[Tuple[int, str]] = []
    # v5: faults that NAME a worker pair per worker — retired by the
    # migrate_done that follows that worker's worker_lost (matched
    # below), by a recover/retry when no loss was ever observed (the
    # in-engine degradation path), or by the terminal abort.
    worker_faults: Dict[str, List[int]] = {}
    # v5: per-worker seq monotonicity, spanning run rotations.
    last_seq: Dict[str, Tuple[int, int]] = {}
    # v6 (tiered store): frontier spills awaiting a page_in (or the
    # producing run's end — a run that finishes with blocks still cold
    # simply never needed them again); per-(run, tier) byte gauges
    # must be monotone BETWEEN pressure resets (a pressure event marks
    # a legitimate shrink — page-in consumption, warm->disk pushes).
    open_spills: Dict[str, List[int]] = {}
    # v7 (job service): submits awaiting their job_done/job_abort.
    # Exact-keyed by the job id — no oldest-first approximation here.
    open_jobs: Dict[str, int] = {}
    # v14 (overload control): parks awaiting their resume (or a
    # terminal job_abort for the same id) — exact-keyed like v7; and
    # per-run last controller rung for the edge-trigger check.
    open_parks: Dict[str, int] = {}
    last_ctrl_rung: Dict[str, Tuple[int, int]] = {}
    # v9 (wave multiplexing): per-run open attribution window — the
    # mux TOTAL wave awaiting its jobs_in_wave attributed lines.
    mux_windows: Dict[str, dict] = {}
    # v10 (async host I/O): checkpoint generations begun but not yet
    # landed, per run (the writer is FIFO, so ckpt_done retires the
    # oldest). A fault/abort excuses them stream-wide — the same known
    # approximation as the fault pairing itself: the begin a dying
    # write interrupted has no join key to the fault that explains it.
    # The excuse is also flush-order-independent: fault events ride
    # their own tracer (own run id, own buffer), so in the merged file
    # a fault can land BEFORE the begin it killed — begins left open at
    # run_end are therefore deferred and judged only at end-of-stream,
    # once the whole stream has had its say.
    open_ckpts: Dict[str, List[int]] = {}
    lost_ckpts: List[Tuple[int, str, int]] = []
    ckpt_excused = False
    # v10: per-run summed io_stall_s, checked against run_end's dur.
    io_stall_sums: Dict[str, float] = {}
    # v11 (service observability): per-run last snap index, and per
    # (run, series) last (count, sum) — histograms only ever grow.
    last_snap: Dict[str, Tuple[int, int]] = {}
    last_hist: Dict[Tuple[str, str], Tuple[int, int, float]] = {}
    # v13 (continuous profiler): per-run last profile_snapshot ordinal.
    last_prof_snap: Dict[str, Tuple[int, int]] = {}
    ended_runs = set()
    last_tier_bytes: Dict[Tuple[str, str], Tuple[int, int]] = {}
    # A flight-recorder postmortem (first event: the ``postmortem``
    # header) is a bounded WINDOW onto a failure, not a complete
    # stream: wave indices may start mid-run and stop abruptly,
    # cumulative counts may straddle a rollback, and an unretired
    # fault at end-of-file is the file's entire reason to exist — so
    # dumps keep per-line schema validation and per-worker seq order,
    # but relax contiguity/backwards-counts to per-run monotonicity
    # and skip the end-of-stream pairing errors.
    dump_mode = False
    first_event = True
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            errors.append(f"line {lineno}: invalid JSON: {e}")
            continue
        for err in validate_event(obj):
            errors.append(f"line {lineno}: {err}")
        if not isinstance(obj, dict):
            continue
        if first_event:
            dump_mode = obj.get("type") == "postmortem"
            first_event = False
        kind = obj.get("type") or f"session:{obj.get('event')}"
        counts[kind] = counts.get(kind, 0) + 1
        run = obj.get("run")
        if run:
            runs.add(run)
        if _too_new(obj):
            continue
        etype = obj.get("type")
        # v5 per-worker seq monotonicity: any event carrying both a
        # worker and a seq (the relayed streams) must only ever move
        # forward — seq survives run rotation precisely so this check
        # can span migrations.
        seq, worker_id = obj.get("seq"), obj.get("worker")
        if isinstance(seq, int) and isinstance(worker_id, str):
            prev_line, prev_seq = last_seq.get(worker_id, (0, None))
            if prev_seq is not None and seq <= prev_seq:
                errors.append(
                    f"line {lineno}: worker {worker_id!r}: seq {seq} "
                    f"after seq {prev_seq} (line {prev_line}) — "
                    "per-worker order lost in the merge")
            last_seq[worker_id] = (lineno, seq)
        if etype == "fault":
            fw = obj.get("worker")
            if isinstance(fw, str):
                worker_faults.setdefault(fw, []).append(lineno)
            else:
                open_faults.append((lineno, str(obj.get("point"))))
            # v10: a fault explains begun-but-unlanded generations (the
            # background write it killed never emits its ckpt_done).
            open_ckpts.clear()
            ckpt_excused = True
        elif etype in ("recover", "retry"):
            if open_faults:
                open_faults.pop(0)
            else:
                # No anonymous fault outstanding: a recovery may
                # retire the oldest worker-tagged fault whose loss was
                # never observed (in-engine recovery paths).
                for fw in sorted(worker_faults):
                    if worker_faults[fw]:
                        worker_faults[fw].pop(0)
                        break
        elif etype == "worker_lost":
            open_losses.append((lineno, str(obj.get("worker"))))
        elif etype == "migrate_done":
            if open_losses:
                _, lost_worker = open_losses.pop(0)
                # The per-worker pairing: rebuilding the lost worker's
                # partitions is what retires ITS fault, whichever
                # epoch/rotation the events straddle.
                if worker_faults.get(lost_worker):
                    worker_faults[lost_worker].pop(0)
        elif etype == "abort":
            open_faults.clear()
            open_losses.clear()
            worker_faults.clear()
            open_spills.clear()
            open_ckpts.clear()
            ckpt_excused = True
        elif etype == "ckpt_begin":
            if isinstance(run, str):
                open_ckpts.setdefault(run, []).append(lineno)
        elif etype == "ckpt_done":
            if isinstance(run, str) and open_ckpts.get(run):
                open_ckpts[run].pop(0)
        elif etype == "spill":
            if obj.get("kind") == "frontier" and isinstance(run, str):
                # Only paged-out FRONTIER blocks owe a page_in: visited
                # spills are membership-only and never come back up.
                open_spills.setdefault(run, []).append(lineno)
        elif etype == "page_in":
            if isinstance(run, str) and open_spills.get(run):
                open_spills[run].pop(0)
        elif etype == "job_submit":
            job = obj.get("job")
            if isinstance(job, str):
                if job in open_jobs:
                    errors.append(
                        f"line {lineno}: job {job!r} submitted again at "
                        f"line {lineno} while its submit at line "
                        f"{open_jobs[job]} is still unresolved")
                open_jobs[job] = lineno
        elif etype in ("job_done", "job_abort"):
            job = obj.get("job")
            if isinstance(job, str):
                open_jobs.pop(job, None)
                if etype == "job_abort":
                    # v14: a terminal abort is a legitimate end for a
                    # parked job (shutdown before pressure cleared).
                    open_parks.pop(job, None)
        elif etype == "shed":
            reason = obj.get("reason")
            if reason not in SHED_REASONS:
                errors.append(
                    f"line {lineno}: shed with reason {reason!r} — "
                    f"every shed must carry one of {SHED_REASONS} "
                    "(an unattributable 429 is a policy decision the "
                    "stream failed to explain)")
            ra = obj.get("retry_after_s")
            if not (isinstance(ra, (int, float)) and ra > 0
                    and math.isfinite(ra)):
                errors.append(
                    f"line {lineno}: shed with retry_after_s {ra!r} — "
                    "a 429 must carry a positive, finite retry hint")
        elif etype == "park":
            job = obj.get("job")
            if isinstance(job, str):
                if job in open_parks:
                    errors.append(
                        f"line {lineno}: job {job!r} parked again "
                        f"while its park at line {open_parks[job]} is "
                        "still unresolved")
                open_parks[job] = lineno
        elif etype == "resume":
            job = obj.get("job")
            if isinstance(job, str):
                open_parks.pop(job, None)
            resumed_as = obj.get("resumed_as")
            if not isinstance(resumed_as, str) or resumed_as == job:
                errors.append(
                    f"line {lineno}: resume of {job!r} with "
                    f"resumed_as {resumed_as!r} — the continuation "
                    "must be a distinct job id")
        elif etype == "controller":
            rung, requested, kept = (obj.get("rung"),
                                     obj.get("requested"),
                                     obj.get("kept"))
            if isinstance(kept, int):
                if isinstance(requested, int) and kept > requested:
                    errors.append(
                        f"line {lineno}: controller kept {kept} > "
                        f"requested {requested} — kept can only "
                        "honestly report what was clamped DOWN")
                if isinstance(rung, int) and kept != rung:
                    errors.append(
                        f"line {lineno}: controller rung {rung} != "
                        f"kept {kept} — the reported rung IS the kept "
                        "outcome")
            if isinstance(rung, int) and isinstance(run, str):
                prev = last_ctrl_rung.get(run)
                if prev is not None and prev[1] == rung:
                    errors.append(
                        f"line {lineno}: run {run}: controller event "
                        f"repeats rung {rung} (last at line {prev[0]}) "
                        "— ladder transitions are edge-triggered")
                last_ctrl_rung[run] = (lineno, rung)
        elif etype == "hist_snapshot":
            # v11: snapshots are cumulative since the producer armed —
            # snap strictly increases per run; per (run, series) the
            # non-cumulative buckets sum exactly to count, and
            # count/sum never shrink (histograms only accumulate).
            # Dumps keep these checks: a ring may drop snapshots, but
            # the survivors still only grow.
            hists = obj.get("hists")
            snap = obj.get("snap")
            if isinstance(run, str) and isinstance(snap, int):
                prev = last_snap.get(run)
                if prev is not None and snap <= prev[1]:
                    errors.append(
                        f"line {lineno}: run {run}: hist_snapshot "
                        f"snap {snap} after snap {prev[1]} (line "
                        f"{prev[0]}) — snapshot order lost")
                last_snap[run] = (lineno, snap)
            if isinstance(run, str) and isinstance(hists, dict):
                for key in sorted(hists):
                    data = hists[key]
                    if not isinstance(data, dict):
                        errors.append(
                            f"line {lineno}: run {run}: series "
                            f"{key!r} payload is not an object")
                        continue
                    buckets = data.get("buckets")
                    count = data.get("count")
                    hsum = data.get("sum")
                    if (isinstance(buckets, list)
                            and isinstance(count, int)):
                        bsum = sum(b for b in buckets
                                   if isinstance(b, int))
                        if bsum != count:
                            errors.append(
                                f"line {lineno}: run {run}: series "
                                f"{key!r}: buckets sum to {bsum}, "
                                f"count says {count} — snapshot is "
                                "internally inconsistent")
                    prev = last_hist.get((run, key))
                    if prev is not None:
                        if isinstance(count, int) and count < prev[1]:
                            errors.append(
                                f"line {lineno}: run {run}: series "
                                f"{key!r}: count went backwards "
                                f"({prev[1]}->{count}, last at line "
                                f"{prev[0]})")
                        if (isinstance(hsum, (int, float))
                                and hsum < prev[2] - 1e-6):
                            errors.append(
                                f"line {lineno}: run {run}: series "
                                f"{key!r}: sum went backwards "
                                f"({prev[2]}->{hsum}, last at line "
                                f"{prev[0]})")
                    last_hist[(run, key)] = (
                        lineno,
                        count if isinstance(count, int) else 0,
                        float(hsum) if isinstance(hsum, (int, float))
                        else 0.0)
        elif etype == "profile_snapshot":
            # v13: the sampling ordinal is a per-producer counter —
            # strictly increasing per run, in dumps too (a ring drops
            # snapshots but never reorders them).
            snap = obj.get("snap")
            if isinstance(run, str) and isinstance(snap, int):
                prev = last_prof_snap.get(run)
                if prev is not None and snap <= prev[1]:
                    errors.append(
                        f"line {lineno}: run {run}: profile_snapshot "
                        f"snap {snap} after snap {prev[1]} (line "
                        f"{prev[0]}) — snapshot order lost")
                last_prof_snap[run] = (lineno, snap)
            # v13: measured_s and cost_ratio are positive and finite by
            # construction (the ratio is against the program's own
            # first sampled baseline) — anything else is fabricated.
            for field in ("measured_s", "cost_ratio"):
                val = obj.get(field)
                if (isinstance(val, (int, float))
                        and not isinstance(val, bool)
                        and (not math.isfinite(val) or val <= 0)):
                    errors.append(
                        f"line {lineno}: profile_snapshot {field} "
                        f"{val!r} is not finite and positive — "
                        "fabricated against the program's own "
                        "baseline")
            # v13: roofline coordinates must agree with their own cost
            # model (intensity = flops / bytes, to rounding).
            flops, byts, inten = (obj.get("flops"), obj.get("bytes"),
                                  obj.get("intensity"))
            if (isinstance(flops, (int, float))
                    and isinstance(byts, (int, float)) and byts > 0
                    and isinstance(inten, (int, float))):
                want = flops / byts
                if abs(inten - want) > max(1e-5, 1e-3 * abs(want)):
                    errors.append(
                        f"line {lineno}: profile_snapshot intensity "
                        f"{inten} disagrees with flops/bytes "
                        f"{want:.6f} — roofline coordinates are "
                        "fabricated")
        elif etype == "pressure":
            # A legitimate tier shrink: reset the monotonicity window
            # for this run's tier.
            if isinstance(run, str):
                last_tier_bytes.pop((run, str(obj.get("tier"))), None)
        elif etype == "run_end" and isinstance(run, str):
            ended_runs.add(run)
            win = mux_windows.pop(run, None)
            if win is not None and not dump_mode:
                errors.append(
                    f"line {lineno}: run {run}: run_end with the mux "
                    f"wave total at line {win['line']} still awaiting "
                    f"{win['remaining']} attributed line(s)")
            # v10: a run must not end with a checkpoint generation
            # begun but never landed (nor explained by a fault/abort).
            # Deferred rather than judged here: the fault that explains
            # this begin may flush to the file AFTER (or before) this
            # run_end, since Supervisor/fault events ride other runs'
            # buffers — end-of-stream decides.
            for begin_line in open_ckpts.pop(run, []):
                lost_ckpts.append((lineno, run, begin_line))
            # v10: summed per-wave io_stall_s must fit inside the
            # run's wall-clock window (slack covers rounding and the
            # final checkpoint landing after the last wave event).
            dur = obj.get("dur")
            stall = io_stall_sums.pop(run, 0.0)
            if (isinstance(dur, (int, float)) and not dump_mode
                    and stall > dur + max(0.1, 0.05 * dur)):
                errors.append(
                    f"line {lineno}: run {run}: summed io_stall_s "
                    f"{stall:.3f}s exceeds the run_end duration "
                    f"window {dur:.3f}s — stall accounting is "
                    "fabricated")
        if etype == "wave" and isinstance(run, str):
            idx = obj.get("wave")
            if isinstance(idx, int):
                if dump_mode:
                    # A ring window: indices may start anywhere, must
                    # still move forward per run.
                    prev = last_wave.get(run)
                    if prev is not None and idx <= prev:
                        errors.append(
                            f"line {lineno}: run {run}: wave index "
                            f"{idx} after {prev} (dump reorder)")
                else:
                    expect = last_wave.get(run, -1) + 1
                    if idx != expect:
                        errors.append(
                            f"line {lineno}: run {run}: wave index "
                            f"{idx}, expected {expect} (stream gap or "
                            "reorder)")
                last_wave[run] = idx
            stall = obj.get("io_stall_s")
            if isinstance(stall, (int, float)):
                io_stall_sums[run] = io_stall_sums.get(run, 0.0) + stall
            states, unique = obj.get("states"), obj.get("unique")
            if isinstance(states, int) and isinstance(unique, int):
                ps, pu = last_counts.get(run, (0, 0))
                if (states < ps or unique < pu) and not dump_mode:
                    errors.append(
                        f"line {lineno}: run {run}: cumulative counts "
                        f"went backwards (states {ps}->{states}, "
                        f"unique {pu}->{unique})")
                last_counts[run] = (states, unique)
            # v5 attribution requirements: relayed worker waves must
            # say WHO did the work and WHERE in the merge order they
            # belong; coordinator round summaries must be positioned
            # by (epoch, round). Older captures predate the keys.
            if (isinstance(obj.get("schema_version"), int)
                    and obj["schema_version"] >= 5):
                engine = obj.get("engine")
                if engine == "elastic_worker":
                    for field in ("worker", "seq", "round"):
                        if obj.get(field) is None:
                            errors.append(
                                f"line {lineno}: elastic_worker wave "
                                f"without {field!r} — unattributable "
                                "work in a merged stream")
                elif engine == "elastic":
                    for field in ("epoch", "round"):
                        if obj.get(field) is None:
                            errors.append(
                                f"line {lineno}: elastic coordinator "
                                f"wave without {field!r}")
            # v9 attribution window (wave multiplexing): a TOTAL mux
            # wave (job_id null, jobs_in_wave set) opens a window that
            # exactly jobs_in_wave attributed lines must close, their
            # per-job deltas summing to the total's — short, long, or
            # interrupted attribution is fabricated accounting. An
            # attributed line with NO open window is legitimate (a
            # per-job trace file sees only its own tenant's lines).
            if (isinstance(obj.get("schema_version"), int)
                    and obj["schema_version"] >= 9
                    and isinstance(run, str) and not dump_mode):
                job_id = obj.get("job_id")
                jobs_in_wave = obj.get("jobs_in_wave")
                win = mux_windows.get(run)
                if job_id is None and isinstance(jobs_in_wave, int):
                    if win is not None:
                        errors.append(
                            f"line {lineno}: run {run}: new mux wave "
                            f"total while the total at line "
                            f"{win['line']} still awaits "
                            f"{win['remaining']} attributed line(s)")
                    mux_windows[run] = {
                        "line": lineno, "jobs": jobs_in_wave,
                        "remaining": jobs_in_wave,
                        "totals": tuple(obj.get(f) for f in
                                        ("successors", "candidates",
                                         "novel")),
                        "sums": [0, 0, 0]}
                elif job_id is not None and win is not None:
                    if jobs_in_wave != win["jobs"]:
                        errors.append(
                            f"line {lineno}: run {run}: attributed "
                            f"wave says jobs_in_wave={jobs_in_wave}, "
                            f"its total at line {win['line']} said "
                            f"{win['jobs']}")
                    for i, field in enumerate(("successors",
                                               "candidates", "novel")):
                        val = obj.get(field)
                        if isinstance(val, int):
                            win["sums"][i] += val
                    win["remaining"] -= 1
                    if win["remaining"] <= 0:
                        for i, field in enumerate(("successors",
                                                   "candidates",
                                                   "novel")):
                            total = win["totals"][i]
                            if (isinstance(total, int)
                                    and win["sums"][i] != total):
                                errors.append(
                                    f"line {lineno}: run {run}: "
                                    f"per-job {field} sum to "
                                    f"{win['sums'][i]}, the wave "
                                    f"total at line {win['line']} "
                                    f"said {total}")
                        del mux_windows[run]
                elif job_id is None and jobs_in_wave is None \
                        and win is not None:
                    errors.append(
                        f"line {lineno}: run {run}: solo wave inside "
                        f"an open mux window (total at line "
                        f"{win['line']} awaits {win['remaining']} "
                        "attributed line(s))")
            # v6 invariants (tiered store). Host-store producers must
            # carry REAL occupancy gauges (capacity/load_factor/
            # out_rows were permanent nulls through v5 — the
            # null-allowance is withdrawn for v6+ captures), and the
            # per-tier byte gauges may only grow between pressure
            # resets (a shrink without a pressure marker is a
            # truncated or re-ordered stream).
            if (isinstance(obj.get("schema_version"), int)
                    and obj["schema_version"] >= 6):
                if obj.get("engine") in ("host_bfs", "host_dfs",
                                         "elastic", "elastic_worker"):
                    for field in ("capacity", "load_factor",
                                  "out_rows"):
                        if obj.get(field) is None:
                            errors.append(
                                f"line {lineno}: {obj['engine']} wave "
                                f"with null {field!r} — host store "
                                "occupancy gauges are required from "
                                "schema v6")
                if isinstance(run, str):
                    for tier in ("device", "host", "disk"):
                        val = obj.get(f"tier_{tier}_bytes")
                        if not isinstance(val, int):
                            continue
                        key = (run, tier)
                        prev = last_tier_bytes.get(key)
                        if (prev is not None and val < prev[1]
                                and not dump_mode):
                            errors.append(
                                f"line {lineno}: run {run}: "
                                f"tier_{tier}_bytes went backwards "
                                f"({prev[1]}->{val}, last at line "
                                f"{prev[0]}) without a pressure "
                                "reset")
                        last_tier_bytes[key] = (lineno, val)
    if not dump_mode:
        for lineno, point in open_faults:
            errors.append(
                f"line {lineno}: fault {point!r} is never followed by "
                "a recover or terminal abort in the stream "
                "(unrecovered failure)")
        for lineno, worker in open_losses:
            errors.append(
                f"line {lineno}: worker_lost {worker!r} is never "
                "followed by a migrate_done or terminal abort in the "
                "stream (lost partitions were never rebuilt)")
        for worker in sorted(worker_faults):
            for lineno in worker_faults[worker]:
                errors.append(
                    f"line {lineno}: fault on worker {worker!r} is "
                    "never followed by that worker's migration (or a "
                    "recover/terminal abort) in the stream "
                    "(unrecovered worker failure)")
        # v7: every submitted job must leave the stream finished or
        # acknowledged — an unpaired submit is work the service lost.
        for job, lineno in sorted(open_jobs.items(),
                                  key=lambda kv: kv[1]):
            errors.append(
                f"line {lineno}: job_submit {job!r} is never followed "
                "by a job_done or job_abort in the stream (the service "
                "lost the job)")
        # v14: parked work is work the controller OWES back — a stream
        # that ends still holding a park lost it.
        for job, lineno in sorted(open_parks.items(),
                                  key=lambda kv: kv[1]):
            errors.append(
                f"line {lineno}: park of {job!r} is never followed by "
                "a resume or terminal job_abort in the stream (the "
                "controller lost the parked job)")
        # v9: a mux wave total still awaiting attributed lines at
        # end-of-stream means the device dispatch's per-job split was
        # never accounted for.
        for run, win in sorted(mux_windows.items(),
                               key=lambda kv: kv[1]["line"]):
            errors.append(
                f"line {win['line']}: run {run}: mux wave total is "
                f"never followed by its {win['jobs']} attributed "
                f"line(s) (stream ends with {win['remaining']} "
                "outstanding)")
        # v10: a generation begun but never landed at end-of-stream is
        # a write the process lost track of — exactly the async-I/O
        # failure mode the safe-point join exists to rule out. Any
        # fault/abort anywhere in the stream excuses them (the same
        # stream-global approximation the fault branch applies, made
        # flush-order-independent).
        if not ckpt_excused:
            for end_line, run, begin_line in lost_ckpts:
                errors.append(
                    f"line {end_line}: run {run}: run_end with the "
                    f"ckpt_begin at line {begin_line} never landed "
                    "(no ckpt_done, no fault/abort explaining it)")
            for run, linenos in sorted(open_ckpts.items()):
                for begin_line in linenos:
                    errors.append(
                        f"line {begin_line}: run {run}: ckpt_begin is "
                        "never followed by a ckpt_done (or a "
                        "fault/abort explaining it) in the stream "
                        "(lost background write)")
        # v6: a paged-out frontier block must come back (page_in) or
        # the producing run must END — a stream that just stops with
        # cold frontier blocks outstanding lost work.
        for run, linenos in sorted(open_spills.items()):
            if run in ended_runs:
                continue
            for lineno in linenos:
                errors.append(
                    f"line {lineno}: run {run}: frontier spill is "
                    "never followed by a page_in or the run's end "
                    "(paged-out frontier blocks were lost)")
    counts["runs"] = len(runs)
    return counts, errors


def lint_file(path: str) -> Tuple[Dict[str, int], List[str]]:
    with open(path, encoding="utf-8") as f:
        return lint_lines(f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a JSONL telemetry stream (STpu_TRACE "
                    "capture or device_session stdout) against the obs "
                    "schema")
    ap.add_argument("path", help="JSONL file to validate")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress individual errors (summary only)")
    ap.add_argument("--max-errors", type=int, default=20,
                    help="errors to print before truncating (default 20)")
    args = ap.parse_args(argv)

    counts, errors = lint_file(args.path)
    total = sum(v for k, v in counts.items() if k != "runs")
    if not args.quiet:
        for err in errors[:args.max_errors]:
            print(err, file=sys.stderr)
        if len(errors) > args.max_errors:
            print(f"... and {len(errors) - args.max_errors} more",
                  file=sys.stderr)
    breakdown = ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    if errors:
        print(f"FAIL: {len(errors)} error(s) in {total} event(s) "
              f"({breakdown})")
        return 1
    print(f"OK: {total} event(s) valid ({breakdown})")
    return 0


if __name__ == "__main__":
    sys.exit(main())

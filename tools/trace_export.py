#!/usr/bin/env python
"""Exports an ``STpu_TRACE`` JSONL capture to analysis-ready formats.

Two exporters, one pass over the stream:

- **Chrome trace-event JSON** (``-o out.json``, the default with the
  input name + ``.chrome.json``): loadable in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing``. Each run becomes a
  process track (named ``engine run``); wave events render as complete
  ("X") slices whose duration is the gap to the previous wave of the
  same run (the host-side processing interval the dispatch landed in),
  spans render on their own thread rows by depth, and cumulative
  ``states`` / ``load_factor`` render as counter ("C") tracks so the
  throughput line and the table pressure are visible against the waves
  that caused them. Timestamps are per-run relative (monotonic clocks
  from different processes don't share a base) — EXCEPT the elastic
  family (schema v5): the coordinator gets ONE track and every elastic
  worker gets ONE track keyed by worker name (run rotations from
  migrations collapse onto the same row), all sharing one time base,
  so a kill/join drill reads as parallel worker lanes under a
  coordinator lane whose membership events (worker_lost / migrate_done
  / rebalance / straggler) are instants at the moment the lanes
  change. Same-host monotonic clocks make the shared base sound for
  the transports this runtime ships. Flight-recorder postmortem dumps
  (``obs/flight.py``) are accepted as input — the ``postmortem``
  header renders as an instant ahead of the ring's events.
- **Prometheus text dump** (``--prom out.prom``): final tallies per run
  in exposition format — states/unique/waves/overflow totals, last load
  factor, counter totals, per-span-name cumulative seconds. The same
  families the explorer's live ``GET /.metrics`` serves, so dashboards
  can consume a dead run's trace and a live checker identically.

Continuous-profiler events (schema v13): ``profile_snapshot`` renders
as Perfetto counter tracks — achieved flops/s and bytes/s plus the
``cost_ratio`` drift line, one series per compiled-program key, so a
program getting slower plots against the waves where it happened — and
the Prometheus dump carries the last snapshot per (engine, key) as the
same ``stpu_prof_*`` gauge families the live ``GET /.metrics`` serves.

Dependency-free beyond the obs schema (no jax)."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from stateright_tpu.obs.schema import SCHEMA_VERSION  # noqa: E402


def load_events(path: str) -> List[dict]:
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                events.append(obj)
    return events


_ELASTIC_ENGINES = ("elastic", "elastic_worker")


def _run_key(evt: dict) -> str:
    """One track per run — except the elastic family, where the track
    is the WORKER (or the coordinator): migration rotates run ids, and
    the useful timeline is lanes per participant, not per attempt."""
    engine = evt.get("engine", "?")
    if engine == "elastic_worker":
        return f"elastic worker {evt.get('worker', '?')}"
    if engine == "elastic":
        return "elastic coordinator"
    return f"{engine} {evt.get('run', '?')}"


def to_chrome(events: List[dict]) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable)."""
    trace: List[dict] = []
    pids: Dict[str, int] = {}
    t0: Dict[str, float] = {}      # per-run time base
    prev_wave_t: Dict[str, float] = {}
    # One shared base for the whole elastic family: same-host
    # monotonic clocks, and the worker lanes must line up against the
    # coordinator's membership instants.
    elastic_t0 = min((e["t"] for e in events
                      if e.get("engine") in _ELASTIC_ENGINES
                      and isinstance(e.get("t"), (int, float))),
                     default=None)

    def pid_for(evt: dict) -> int:
        key = _run_key(evt)
        if key not in pids:
            pids[key] = len(pids) + 1
            trace.append({"ph": "M", "pid": pids[key], "tid": 0,
                          "name": "process_name",
                          "args": {"name": key}})
        return pids[key]

    def us(evt: dict, t: float) -> float:
        if evt.get("engine") in _ELASTIC_ENGINES \
                and elastic_t0 is not None:
            return max(0.0, (t - elastic_t0) * 1e6)
        run = evt.get("run", "?")
        base = t0.setdefault(run, t)
        return max(0.0, (t - base) * 1e6)

    for evt in events:
        etype = evt.get("type")
        t = evt.get("t")
        if etype is None or not isinstance(t, (int, float)):
            continue  # session-family events have no type/track
        pid = pid_for(evt)
        run = evt.get("run", "?")
        if evt.get("engine") == "elastic_worker":
            # Waves from one worker interleave across rotated runs on
            # one lane: slice duration keys on the TRACK, not the run.
            run = _run_key(evt)
        if etype == "run_start":
            t0.setdefault(run, t)
            trace.append({"ph": "i", "pid": pid, "tid": 1,
                          "name": "run_start", "ts": us(evt, t),
                          "s": "p", "args": evt.get("meta", {})})
        elif etype == "wave":
            start = prev_wave_t.get(run, t0.get(run, t))
            prev_wave_t[run] = t
            args = {k: v for k, v in evt.items()
                    if k not in ("type", "run", "engine",
                                 "schema_version", "t")}
            trace.append({
                "ph": "X", "pid": pid, "tid": 1,
                "name": f"wave B={evt.get('bucket')}",
                "ts": us(evt, start),
                "dur": max(0.0, (t - start) * 1e6), "args": args})
            for counter, value in (("states", evt.get("states")),
                                   ("load_factor",
                                    evt.get("load_factor"))):
                if value is not None:
                    trace.append({"ph": "C", "pid": pid, "tid": 0,
                                  "name": counter, "ts": us(evt, t),
                                  "args": {counter: value}})
            # Tiered-store byte gauges (schema v6): one counter track
            # with a series per tier, so pressure reads as the device
            # line flattening while host/disk climb.
            tiers = {tier: evt.get(f"tier_{tier}_bytes")
                     for tier in ("device", "host", "disk")}
            if any(v is not None for v in tiers.values()):
                trace.append({
                    "ph": "C", "pid": pid, "tid": 0,
                    "name": "tier_bytes", "ts": us(evt, t),
                    "args": {k: v for k, v in tiers.items()
                             if v is not None}})
        elif etype == "span":
            dur = float(evt.get("dur", 0.0))
            trace.append({
                "ph": "X", "pid": pid,
                "tid": 2 + int(evt.get("depth", 0)),
                "name": str(evt.get("name", "span")),
                "ts": us(evt, t), "dur": dur * 1e6,
                "args": evt.get("attrs", {})})
        elif etype == "straggler":
            # Straggler attribution (schema v5): an instant on the
            # coordinator lane plus a wait-share counter track, so
            # barrier cost plots against the worker lanes causing it.
            trace.append({
                "ph": "i", "pid": pid, "tid": 1, "name": "straggler",
                "ts": us(evt, t), "s": "p",
                "args": {"round": evt.get("round"),
                         "slowest": evt.get("slowest"),
                         "wait_share": evt.get("wait_share"),
                         "workers": evt.get("workers", {})}})
            trace.append({"ph": "C", "pid": pid, "tid": 0,
                          "name": "wait_share", "ts": us(evt, t),
                          "args": {"wait_share":
                                   evt.get("wait_share", 0)}})
        elif etype in ("grow", "overflow_redispatch",
                       # Resilience markers (schema v3): process-scoped
                       # instants so a Perfetto timeline shows exactly
                       # where a run faulted, degraded, and recovered.
                       "fault", "recover", "degrade", "abort",
                       # Membership markers (schema v4): where a worker
                       # was lost, its partitions migrated, and a join
                       # rebalanced — the states/s dip between a
                       # worker_lost and its migrate_done is the
                       # migration cost a timeline makes visible.
                       "worker_lost", "worker_join", "migrate_done",
                       "rebalance", "retry",
                       # Flight-recorder dump header (schema v5): the
                       # postmortem file is valid exporter input.
                       "postmortem",
                       # Tiered-store markers (schema v6): where rows
                       # moved down a tier, paged back in, or a tier
                       # crossed its budget.
                       "spill", "page_in", "pressure",
                       # Job-service lifecycle (schema v7): a job trace
                       # renders submit -> done/abort as process-scoped
                       # instants bracketing the engine's run.
                       "job_submit", "job_done", "job_abort"):
            trace.append({
                "ph": "i", "pid": pid, "tid": 1, "name": etype,
                "ts": us(evt, t),
                "s": "p" if etype in ("fault", "recover", "degrade",
                                      "abort", "worker_lost",
                                      "worker_join", "migrate_done",
                                      "rebalance", "retry",
                                      "postmortem", "job_submit",
                                      "job_done", "job_abort") else "t",
                "args": {k: v for k, v in evt.items()
                         if k not in ("type", "run", "engine",
                                      "schema_version", "t")}})
        elif etype == "profile_snapshot":
            # Roofline counter tracks (schema v13): one series per
            # compiled-program key, so the achieved rates and the
            # drift ratio plot against the waves that produced them.
            key = str(evt.get("key", "?"))
            rates = {k: evt[k] for k in ("flops_per_s", "bytes_per_s")
                     if isinstance(evt.get(k), (int, float))}
            if rates:
                trace.append({"ph": "C", "pid": pid, "tid": 0,
                              "name": f"roofline {key}",
                              "ts": us(evt, t), "args": rates})
            ratio = evt.get("cost_ratio")
            if isinstance(ratio, (int, float)):
                trace.append({"ph": "C", "pid": pid, "tid": 0,
                              "name": f"cost_ratio {key}",
                              "ts": us(evt, t),
                              "args": {"cost_ratio": ratio}})
        elif etype in ("counter", "gauge"):
            trace.append({"ph": "C", "pid": pid, "tid": 0,
                          "name": str(evt.get("name", etype)),
                          "ts": us(evt, t),
                          "args": {"value": evt.get("value", 0)}})
        elif etype == "run_end":
            trace.append({"ph": "i", "pid": pid, "tid": 1,
                          "name": "run_end", "ts": us(evt, t),
                          "s": "p",
                          "args": {"dur": evt.get("dur"),
                                   "counters": evt.get("counters", {})}})
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"schema_version": SCHEMA_VERSION}}


def to_prometheus(events: List[dict]) -> str:
    """Final tallies in Prometheus exposition format, labeled per run."""
    finals: Dict[str, dict] = {}
    span_sec: Dict[tuple, float] = {}
    counter_final: Dict[tuple, float] = {}
    overflows: Dict[str, int] = {}
    grows: Dict[str, int] = {}
    # v11: the LAST hist_snapshot per (run, series) — snapshots are
    # cumulative, so the final one is the run's whole distribution.
    hist_finals: Dict[str, Dict[str, dict]] = {}
    spills: Dict[str, int] = {}
    spill_bytes: Dict[str, float] = {}
    page_ins: Dict[str, int] = {}
    # v13: the LAST profile_snapshot per (engine, program key) — the
    # baseline-relative gauges supersede earlier samples — plus the
    # per-engine sampled totals.
    prof_finals: Dict[tuple, dict] = {}
    prof_sampled: Dict[str, int] = {}
    worker_wait: Dict[str, float] = {}
    worker_compute: Dict[str, float] = {}
    max_wait_share = None
    for evt in events:
        etype = evt.get("type")
        run = evt.get("run", "?")
        engine = evt.get("engine", "?")
        if etype == "wave":
            finals[run] = dict(evt, engine=engine)
        elif etype == "straggler":
            share = evt.get("wait_share", 0)
            max_wait_share = (share if max_wait_share is None
                              else max(max_wait_share, share))
            for w, seg in (evt.get("workers") or {}).items():
                worker_wait[w] = worker_wait.get(w, 0.0) \
                    + float(seg.get("wait_s") or 0.0)
                worker_compute[w] = worker_compute.get(w, 0.0) \
                    + float(seg.get("compute_s") or 0.0)
        elif etype == "span":
            key = (engine, run, evt.get("name", "span"))
            span_sec[key] = span_sec.get(key, 0.0) + float(
                evt.get("dur", 0.0))
        elif etype == "counter":
            counter_final[(engine, run, evt.get("name", "counter"))] = \
                evt.get("value", 0)
        elif etype == "overflow_redispatch":
            overflows[run] = overflows.get(run, 0) + 1
        elif etype == "grow":
            grows[run] = grows.get(run, 0) + 1
        elif etype == "spill":
            spills[run] = spills.get(run, 0) + 1
            spill_bytes[run] = spill_bytes.get(run, 0) \
                + float(evt.get("bytes") or 0)
        elif etype == "page_in":
            page_ins[run] = page_ins.get(run, 0) + 1
        elif etype == "hist_snapshot":
            hists = evt.get("hists")
            if isinstance(hists, dict):
                hist_finals.setdefault(run, {}).update(hists)
        elif etype == "profile_snapshot":
            prof_finals[(engine, str(evt.get("key", "?")))] = evt
            prof_sampled[engine] = prof_sampled.get(engine, 0) + 1

    lines: List[str] = []

    def emit(metric: str, mtype: str, rows) -> None:
        rows = list(rows)
        if not rows:
            return
        lines.append(f"# TYPE {metric} {mtype}")
        for labels, value in rows:
            label_s = ",".join(f'{k}="{v}"' for k, v in labels.items())
            lines.append(f"{metric}{{{label_s}}} {value}")

    def final_rows(field):
        for run, evt in sorted(finals.items()):
            value = evt.get(field)
            if value is not None:
                yield {"engine": evt["engine"], "run": run}, value

    emit("stpu_states_total", "counter", final_rows("states"))
    emit("stpu_unique_states_total", "counter", final_rows("unique"))
    emit("stpu_waves_total", "counter",
         (({"engine": evt["engine"], "run": run}, evt.get("wave", 0) + 1)
          for run, evt in sorted(finals.items())))
    emit("stpu_table_load_factor", "gauge", final_rows("load_factor"))
    emit("stpu_overflow_redispatches_total", "counter",
         (({"run": run}, n) for run, n in sorted(overflows.items())))
    emit("stpu_table_grows_total", "counter",
         (({"run": run}, n) for run, n in sorted(grows.items())))
    # Tiered-store families (schema v6): final per-tier residency off
    # the last wave event, plus spill/page-in totals — the same
    # families the explorer's live /.metrics serves.
    emit("stpu_tier_bytes", "gauge",
         (({"engine": evt["engine"], "run": run, "tier": tier}, value)
          for run, evt in sorted(finals.items())
          for tier in ("device", "host", "disk")
          for value in (evt.get(f"tier_{tier}_bytes"),)
          if value is not None))
    emit("stpu_tier_spills_total", "counter",
         (({"run": run}, n) for run, n in sorted(spills.items())))
    emit("stpu_tier_spill_bytes_total", "counter",
         (({"run": run}, round(v, 1))
          for run, v in sorted(spill_bytes.items())))
    emit("stpu_tier_page_ins_total", "counter",
         (({"run": run}, n) for run, n in sorted(page_ins.items())))
    emit("stpu_span_seconds_total", "counter",
         (({"engine": e, "run": r, "name": n}, round(v, 6))
          for (e, r, n), v in sorted(span_sec.items())))
    emit("stpu_counter_total", "counter",
         (({"engine": e, "run": r, "name": n}, v)
          for (e, r, n), v in sorted(counter_final.items())))
    # Straggler attribution (schema v5): per-worker barrier-wait and
    # compute seconds plus the worst round's wait share — the same
    # families the live elastic ``GET /.metrics`` exports.
    emit("stpu_worker_wait_seconds_total", "counter",
         (({"worker": w}, round(v, 6))
          for w, v in sorted(worker_wait.items())))
    emit("stpu_worker_compute_seconds_total", "counter",
         (({"worker": w}, round(v, 6))
          for w, v in sorted(worker_compute.items())))
    if max_wait_share is not None:
        lines.append("# TYPE stpu_max_wait_share gauge")
        lines.append(f"stpu_max_wait_share {max_wait_share}")
    # Continuous-profiler families (schema v13): the same ``stpu_prof_*``
    # names ``prometheus_prof_lines`` serves live, reconstructed from
    # the stream's last snapshot per (engine, program key).
    emit("stpu_prof_sampled_total", "counter",
         (({"engine": e}, n) for e, n in sorted(prof_sampled.items())))
    for metric, field in (("stpu_prof_flops", "flops"),
                          ("stpu_prof_bytes", "bytes"),
                          ("stpu_prof_flops_per_s", "flops_per_s"),
                          ("stpu_prof_bytes_per_s", "bytes_per_s"),
                          ("stpu_prof_intensity", "intensity"),
                          ("stpu_prof_cost_ratio", "cost_ratio"),
                          ("stpu_prof_measured_seconds", "measured_s")):
        emit(metric, "gauge",
             (({"engine": e, "key": k}, v)
              for (e, k), evt in sorted(prof_finals.items())
              for v in (evt.get(field),)
              if isinstance(v, (int, float))))
    # Latency histograms (schema v11): the final snapshot per run is
    # the whole distribution — _bucket/_sum/_count via the same
    # emission helper the live ``GET /.metrics`` uses, so a dead
    # capture and a live scrape read identically. Merged across runs
    # by series identity (keys carry their engine/worker labels).
    if hist_finals:
        from stateright_tpu.obs.hist import prometheus_hist_lines

        merged: Dict[str, dict] = {}
        for run in sorted(hist_finals):
            for key, data in hist_finals[run].items():
                cur = merged.get(key)
                # A rotated producer (migration) re-emits the same
                # series under a new run id with LARGER cumulative
                # counts — keep the superset.
                if cur is None or (data.get("count", 0)
                                   >= cur.get("count", 0)):
                    merged[key] = data
        lines += prometheus_hist_lines(merged)
    return "\n".join(lines) + ("\n" if lines else "")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="export an STpu_TRACE JSONL capture to a "
                    "Perfetto-loadable Chrome trace and/or a Prometheus "
                    "text dump")
    ap.add_argument("path", help="JSONL trace file")
    ap.add_argument("-o", "--out", default=None,
                    help="Chrome trace output path (default "
                         "<path>.chrome.json)")
    ap.add_argument("--prom", default=None,
                    help="also write a Prometheus text dump here")
    ap.add_argument("--no-chrome", action="store_true",
                    help="skip the Chrome trace output")
    args = ap.parse_args(argv)

    events = load_events(args.path)
    if not events:
        print(f"no events in {args.path}", file=sys.stderr)
        return 1
    if not args.no_chrome:
        out = args.out or args.path + ".chrome.json"
        with open(out, "w", encoding="utf-8") as f:
            json.dump(to_chrome(events), f)
        print(f"wrote {out} ({len(events)} events)")
    if args.prom:
        with open(args.prom, "w", encoding="utf-8") as f:
            f.write(to_prometheus(events))
        print(f"wrote {args.prom}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Replayable open-loop load for the overload controller (round 21).

Generates SEEDED arrival traces — Poisson base arrivals, a heavy-tailed
(bounded-Pareto) service-demand mix, and explicit overload episodes
where the arrival rate multiplies — and replays them two ways:

- **Simulated** (`simulate`): a deterministic discrete-event model of
  the job service (priority queue, fixed worker pool, the REAL
  :class:`~stateright_tpu.service.control.ControlPolicy` driven with
  simulated time). Same trace + same policy ⇒ bit-identical outcome,
  including the exact shed set — the determinism half of the round-21
  acceptance gate, and the fast way to A/B policy knobs with no device
  or wall clock anywhere.
- **Live** (``bench.py`` stage ``soak_trace``, ``BENCH_SOAK_TRACE=<path>``):
  the same trace replayed against a real in-process service,
  controller-on vs controller-off, measuring goodput, interactive p99,
  sheds, and parked/resumed jobs.

Open-loop honesty: arrivals fire at their scheduled times whether or
not the system keeps up — the generator never waits for the system, so
overload actually overloads (a closed-loop client would self-throttle
and hide the very regime the controller exists for).

Every sampled quantity (arrival gaps, demand, episode placement) is
drawn at GENERATION time from one seeded RNG and stored in the trace;
replay draws nothing. ``demand_s`` is abstract service time: the
simulator consumes it directly, the live replay maps it onto real job
sizes.

Usage::

    python tools/traffic_gen.py --seed 7 --duration 60 --out trace.jsonl
    python tools/traffic_gen.py --seed 7 --duration 60 --simulate \
        --ab          # controller on vs off on the same trace
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import os
import random
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

TRACE_VERSION = 1

#: The three arrival classes and their mix weights. ``interactive``
#: carries a deadline and pops first; ``batch`` is the preemption
#: victim pool; ``soak`` (priority < 0) is what brownout rung 3 pauses.
CLASSES = (
    ("interactive", 0.35, 2, True),
    ("batch", 0.45, 0, False),
    ("soak", 0.20, -1, False),
)


def gen_trace(seed: int, duration_s: float, rate_hz: float = 4.0,
              overload_factor: float = 4.0,
              overload_frac: float = 0.35,
              demand_mean_s: float = 0.35,
              demand_alpha: float = 1.5,
              demand_cap_s: float = 8.0,
              deadline_s: float = 1.5,
              tenants: int = 3) -> dict:
    """Samples one trace: Poisson arrivals at ``rate_hz``, multiplied
    by ``overload_factor`` inside a contiguous overload episode
    covering ``overload_frac`` of the duration (placed by the same
    RNG), demand from a bounded Pareto (``alpha < 2`` — heavy-tailed,
    finite by the cap), class/tenant assignment from the same stream."""
    rng = random.Random(seed)
    ep_len = duration_s * overload_frac
    ep_start = rng.uniform(0.15 * duration_s,
                           max(0.15 * duration_s,
                               duration_s - ep_len - 0.05 * duration_s))
    xm = demand_mean_s * (demand_alpha - 1) / demand_alpha
    arrivals: List[dict] = []
    t = 0.0
    while True:
        in_episode = ep_start <= t < ep_start + ep_len
        rate = rate_hz * (overload_factor if in_episode else 1.0)
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        roll, acc = rng.random(), 0.0
        kind, priority, has_deadline = CLASSES[-1][0], CLASSES[-1][2], \
            CLASSES[-1][3]
        for name, weight, pri, dl in CLASSES:
            acc += weight
            if roll < acc:
                kind, priority, has_deadline = name, pri, dl
                break
        demand = min(demand_cap_s,
                     xm / (rng.random() ** (1.0 / demand_alpha)))
        if kind == "interactive":
            # Interactive checks are small by construction; the heavy
            # tail belongs to the batch/soak classes.
            demand = min(demand, demand_mean_s)
        arrivals.append({
            "t": round(t, 6),
            "kind": kind,
            "priority": priority,
            "tenant": f"t{rng.randrange(tenants)}",
            "demand_s": round(demand, 6),
            "deadline_s": deadline_s if has_deadline else None,
        })
    return {
        "version": TRACE_VERSION,
        "seed": seed,
        "duration_s": duration_s,
        "rate_hz": rate_hz,
        "overload": {"factor": overload_factor,
                     "start_s": round(ep_start, 6),
                     "len_s": round(ep_len, 6)},
        "arrivals": arrivals,
    }


def write_trace(trace: dict, path: str) -> None:
    """One header line, then one line per arrival — greppable and
    streamable like every other JSONL artifact in the repo."""
    with open(path, "w") as f:
        header = {k: v for k, v in trace.items() if k != "arrivals"}
        header["arrivals"] = len(trace["arrivals"])
        f.write(json.dumps(header) + "\n")
        for a in trace["arrivals"]:
            f.write(json.dumps(a) + "\n")


def load_trace(path: str) -> dict:
    with open(path) as f:
        header = json.loads(f.readline())
        if header.get("version") != TRACE_VERSION:
            raise ValueError(
                f"trace version {header.get('version')!r} != "
                f"{TRACE_VERSION}")
        arrivals = [json.loads(line) for line in f if line.strip()]
    header["arrivals"] = arrivals
    return header


def simulate(trace: dict, policy=None, workers: int = 2,
             queue_bound: int = 64,
             latency_slo_s: float = 1.0,
             slo_target: float = 0.9,
             burn_window: int = 32) -> dict:
    """Deterministic discrete-event replay. ``policy`` is a
    :class:`~stateright_tpu.service.control.ControlPolicy` (controller
    ON) or ``None`` (controller OFF — bounded queue only). Simulated
    burn mirrors the live SLO surface's shape: the bad fraction of the
    last ``burn_window`` completions against ``latency_slo_s``, over
    the budget ``1 - slo_target``.

    Preemption is modeled at its essence: when an interactive
    arrival's deadline is at risk and no worker is free, the policy
    parks the longest-running victim without a deadline; the victim's
    REMAINING demand re-queues and resumes when capacity returns —
    work parked, never lost (completed demand is conserved exactly).
    """
    arrivals = trace["arrivals"]
    free_at = [0.0] * workers          # per-worker busy-until
    running: List[Optional[dict]] = [None] * workers
    queue: List[tuple] = []            # (-priority, seq, job)
    events: List[tuple] = []           # (t, kind_ord, seq, payload)
    lat_window: List[bool] = []        # ok/bad ring for burn
    seq = 0
    shed: List[int] = []
    done: List[dict] = []
    parked = resumed = 0
    held_soak = False

    def burn() -> float:
        if len(lat_window) < 8:
            return 0.0
        bad = sum(1 for ok in lat_window if not ok) / len(lat_window)
        return bad / max(1e-9, 1.0 - slo_target)

    def start_ready(now: float) -> None:
        nonlocal seq
        for w in range(workers):
            if running[w] is not None or not queue:
                continue
            pick = None
            for i, (_, _, job) in enumerate(queue):
                if (held_soak and policy is not None
                        and job["priority"] < 0):
                    continue
                pick = i
                break
            if pick is None:
                continue
            _, _, job = queue.pop(pick)
            job["started"] = now
            running[w] = job
            free_at[w] = now + job["remaining_s"]
            seq += 1
            heapq.heappush(events, (free_at[w], 1, seq, (w, job)))

    def tick(now: float) -> None:
        nonlocal held_soak
        if policy is None:
            return
        policy.observe(now, burn(), len(queue))
        held_soak = policy.hold_below() is not None

    for idx, arr in enumerate(arrivals):
        now = arr["t"]
        # Drain completions scheduled before this arrival, ticking the
        # policy at each so rung/engage state advances in sim time.
        while events and events[0][0] <= now:
            t_done, _, _, (w, job) = heapq.heappop(events)
            if running[w] is not job:
                continue  # stale event: job was parked off this worker
            running[w] = None
            job["finished"] = t_done
            lat = t_done - job["t"]
            lat_window.append(lat <= latency_slo_s)
            del lat_window[:-burn_window]
            if policy is not None:
                policy.note_done(t_done)
            done.append(job)
            tick(t_done)
            start_ready(t_done)
        tick(now)

        job = dict(arr)
        job["idx"] = idx
        job["remaining_s"] = job["demand_s"]
        if policy is not None:
            decision = policy.admission(now, job["tenant"],
                                        job["priority"], len(queue))
            if decision is not None:
                shed.append(idx)
                continue
        if len(queue) >= queue_bound:
            shed.append(idx)
            continue
        seq += 1
        queue.append((-job["priority"], seq, job))
        queue.sort(key=lambda item: (item[0], item[1]))
        start_ready(now)

        # Deadline-at-risk park: an interactive job still queued with
        # every worker busy — park the longest-running victim.
        if (policy is not None and job["deadline_s"] is not None
                and job.get("started") is None
                and all(r is not None for r in running)
                and policy.deadline_at_risk(now, job["t"],
                                            job["deadline_s"],
                                            queued=True)):
            victims = [(now - running[w]["started"], w)
                       for w in range(workers)
                       if running[w]["deadline_s"] is None
                       and not running[w].get("resumed")]
            if victims:
                ran, w = max(victims)
                victim = running[w]
                victim["remaining_s"] = max(
                    0.0, victim["remaining_s"] - ran)
                victim["resumed"] = True
                running[w] = None
                parked += 1
                resumed += 1  # re-queued now; runs when capacity frees
                seq += 1
                queue.append((-victim["priority"], seq, victim))
                queue.sort(key=lambda item: (item[0], item[1]))
                start_ready(now)

    # Drain everything still queued/running after the last arrival.
    while events or any(r is not None for r in running) or queue:
        if not events:
            start_ready(max(free_at))
            if not events:
                break
        t_done, _, _, (w, job) = heapq.heappop(events)
        if running[w] is not job:
            continue
        running[w] = None
        job["finished"] = t_done
        lat_window.append(t_done - job["t"] <= latency_slo_s)
        del lat_window[:-burn_window]
        if policy is not None:
            policy.note_done(t_done)
        done.append(job)
        tick(t_done)
        start_ready(t_done)

    horizon = max([trace["duration_s"]]
                  + [j["finished"] for j in done]) or 1.0
    inter = sorted(j["finished"] - j["t"] for j in done
                   if j["deadline_s"] is not None)
    met = sum(1 for j in done if j["deadline_s"] is None
              or j["finished"] - j["t"] <= j["deadline_s"])
    inter_met = sum(1 for j in done if j["deadline_s"] is not None
                    and j["finished"] - j["t"] <= j["deadline_s"])
    inter_total = sum(1 for a in arrivals
                      if a["deadline_s"] is not None)
    inter_shed = sum(1 for i in shed
                     if arrivals[i]["deadline_s"] is not None)
    return {
        "arrivals": len(arrivals),
        "completed": len(done),
        "goodput_jobs_s": round(met / horizon, 4),
        "deadline_met": met,
        "interactive_total": inter_total,
        "interactive_met": inter_met,
        "interactive_shed": inter_shed,
        "interactive_p50_s": round(
            inter[len(inter) // 2], 4) if inter else None,
        "interactive_p99_s": round(
            inter[min(len(inter) - 1,
                      int(0.99 * len(inter)))], 4) if inter else None,
        "shed": shed,
        "shed_count": len(shed),
        "parked": parked,
        "resumed": resumed,
        "final_rung": policy.rung if policy is not None else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Seeded open-loop overload traces: generate, "
                    "inspect, and simulate them against the round-21 "
                    "controller policy (see module docstring).")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=30.0,
                    help="trace length, seconds (default 30)")
    ap.add_argument("--rate", type=float, default=4.0,
                    help="base arrival rate, Hz (default 4)")
    ap.add_argument("--overload-factor", type=float, default=4.0,
                    help="rate multiplier inside the overload episode")
    ap.add_argument("--out", help="write the trace (JSONL) here")
    ap.add_argument("--load", help="replay an existing trace file "
                                   "instead of generating")
    ap.add_argument("--simulate", action="store_true",
                    help="run the discrete-event simulator")
    ap.add_argument("--ab", action="store_true",
                    help="with --simulate: controller on AND off on "
                         "the same trace")
    ap.add_argument("--workers", type=int, default=2)
    args = ap.parse_args(argv)

    if args.load:
        trace = load_trace(args.load)
    else:
        trace = gen_trace(args.seed, args.duration, rate_hz=args.rate,
                          overload_factor=args.overload_factor)
    if args.out:
        write_trace(trace, args.out)
        print(f"wrote {len(trace['arrivals'])} arrivals to {args.out}")
    if args.simulate or args.ab:
        from stateright_tpu.service.control import ControlPolicy

        results = {"on": simulate(trace, ControlPolicy(),
                                  workers=args.workers)}
        if args.ab:
            results["off"] = simulate(trace, None,
                                      workers=args.workers)
        print(json.dumps(results, indent=2))
    elif not args.out:
        header = {k: v for k, v in trace.items() if k != "arrivals"}
        header["arrivals"] = len(trace["arrivals"])
        print(json.dumps(header, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Back-to-back TPU session attempts (device_session.py, init==probe),
# strictly serial (never two JAX processes against the TPU), each under
# timeout -k (SIGTERM does not kill a wedged backend init; SIGKILL
# does). Appends every attempt to PROBELOG_r05.jsonl with timestamps —
# the accepted evidence form for wedged rounds.
cd "$(dirname "$0")/.." || exit 1
LOG=PROBELOG_r05.jsonl
# TTL so the loop can never outlive the builder into the driver's own
# bench window (bench.py also pkills strays at startup, belt+braces).
STOP_AT=${STOP_AT:-$(( $(date +%s) + 28800 ))}
while [ "$(date +%s)" -lt "$STOP_AT" ]; do
  START=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  OUT=$(SESSION_BUDGET_S=840 timeout -k 10 900 \
        python tools/device_session.py 2>/dev/null)
  RC=$?
  if [ -z "$OUT" ]; then
    echo "{\"start\": \"$START\", \"rc\": $RC, \"result\": \"wedged (no init)\"}" >> "$LOG"
  else
    echo "{\"start\": \"$START\", \"rc\": $RC, \"events\": \"begin\"}" >> "$LOG"
    echo "$OUT" >> "$LOG"
  fi
  sleep 120
done

"""Single-process TPU measurement session.

Round-5 field observation (2026-07-31 03:48 UTC): the tunnel granted
exactly ONE successful backend init, and the very next process hung in
``jax.devices()``. A probe that exits before the real work therefore
*wastes the window* (and may be what wedges it). This tool is the
remedy: the backend init IS the probe, and everything we want from a
hardware session — batch sweep, per-stage breakdown, Pallas-table A/B —
runs in the SAME process, emitting one JSON line per result as it
lands, so a mid-session wedge or an external ``timeout`` kill loses
only the in-flight point.

Run under ``timeout`` (the init hang cannot be interrupted from
Python), exactly one JAX process at a time against the TPU::

    timeout 900 python tools/device_session.py            # full session
    timeout 600 python tools/device_session.py --bench-mode  # bench.py's
        # device sub-stage: one bounded run of the BENCH_* workload

Knobs: SESSION_BUDGET_S (internal soft budget; keep it under the
external timeout so stages self-truncate instead of dying mid-run),
SESSION_CAP, SESSION_CLIENTS, and bench.py's BENCH_* for --bench-mode.
With STpu_TRACE=path set (inherited from the parent bench), every
engine this session spawns streams its wave events there; the ``done``
event records the path so the capture pairs with the result line.
Every emitted event carries ``schema_version``/``t``/``unix_t``
(``tools/trace_lint.py`` validates a captured session verbatim).
"""
import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "examples"))

#: Kept in lockstep with ``stateright_tpu.obs.schema.SCHEMA_VERSION``
#: (pinned by tests/test_obs_trace.py). Duplicated as a literal because
#: emit() must work before ANY package import — the whole point of this
#: tool is that nothing heavyweight runs before the backend-init probe.
#: v4 (round 11): the membership/elasticity event family; bench-mode
#: sessions honor the BENCH_ELASTIC_* knobs (the headline routes
#: through the elastic coordinator/worker runtime via bench._tpu_bfs,
#: and the done event's scheduler block then carries the elastic
#: lifecycle: workers, epoch, migrations, rebalances).
#: v5 (round 12): distributed observability — the done event's
#: scheduler block gains the ``elastic_obs`` straggler/merge/postmortem
#: aggregates when the headline ran elastic (session event fields
#: themselves are unchanged).
#: v7 (round 14): lockstep bump with the obs schema's job-service
#: lifecycle family (session event fields themselves are unchanged;
#: jobs run inside the service, not through this stdout protocol).
#: v8 (round 15): lockstep bump with the obs schema's single-kernel
#: wave keys (wave events gain kernel_path/rows; session event fields
#: themselves are unchanged — the done event's scheduler block now
#: carries the engine's ``wave_kernel`` telemetry organically).
#: v9 (round 16): lockstep bump with the obs schema's cross-job wave
#: multiplexing keys (wave events gain job_id/jobs_in_wave; session
#: event fields themselves are unchanged — multiplexing lives in the
#: job service, not this stdout protocol).
#: v10 (round 17): lockstep bump with the obs schema's async host I/O
#: keys (wave events gain io_stall_s, plus ckpt_begin/ckpt_done;
#: session event fields themselves are unchanged — the done event's
#: scheduler block carries ``async_io`` telemetry organically).
#: v11 (round 18): lockstep bump with the obs schema's service
#: observability events (hist_snapshot/slo_breach/anomaly; session
#: event fields themselves are unchanged — the histograms live in the
#: engines and the service, not this stdout protocol).
#: v12 (round 19): lockstep bump with the obs schema's matmul-wave
#: keys (wave events gain expand_impl; kernel_path gains +matmul
#: variants; session event fields themselves are unchanged — the done
#: event's scheduler block carries ``wave_matmul`` telemetry
#: organically).
#: v13 (round 20): lockstep bump with the obs schema's continuous
#: profiler (wave events gain cost_flops/cost_bytes/cost_ratio plus
#: profile_snapshot; session event fields themselves are unchanged).
#: v14 (round 21): lockstep bump with the obs schema's overload-
#: control family (admit/shed/park/resume/controller; session event
#: fields themselves are unchanged — the controller lives in the job
#: service, not this stdout protocol).
SESSION_SCHEMA_VERSION = 14


def emit(obj) -> None:
    """One JSON line per result, versioned and timestamped so consumers
    (bench.py's live reader, ``tools/trace_lint.py`` on a capture) can
    gate on ``schema_version`` and order events without trusting
    arrival order. ``t`` is monotonic (intra-session deltas), ``unix_t``
    wall clock (cross-session correlation)."""
    evt = {"schema_version": SESSION_SCHEMA_VERSION,
           "t": round(time.monotonic(), 6),
           "unix_t": round(time.time(), 3)}
    evt.update(obj)
    print(json.dumps(evt), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, nargs="*",
                    default=[4096, 8192, 16384])
    ap.add_argument("--cap", type=int,
                    default=int(os.environ.get("SESSION_CAP", "200000")))
    ap.add_argument("--table-bits", type=int, default=22)
    ap.add_argument("--budget", type=float,
                    default=float(os.environ.get("SESSION_BUDGET_S", "840")))
    ap.add_argument("--breakdown", type=int, default=1)
    ap.add_argument("--pallas-ab", type=int, default=1)
    ap.add_argument("--bench-mode", action="store_true")
    args = ap.parse_args()
    t0 = time.monotonic()

    def left() -> float:
        return args.budget - (time.monotonic() - t0)

    import jax

    pin = os.environ.get("SESSION_PLATFORM")
    if pin:
        # Rehearsal pin. The env var alone does NOT stop the tunneled
        # plugin from initializing in this jax build (field-tested
        # 2026-07-31: JAX_PLATFORMS=cpu still hung on a wedged tunnel);
        # the post-import config update does.
        jax.config.update("jax_platforms", pin)

    platform = jax.devices()[0].platform  # the probe; hangs are killed
    emit({"event": "init", "platform": platform,
          "sec": round(time.monotonic() - t0, 1)})

    from stateright_tpu.jit_cache import enable_persistent_jit_cache

    enable_persistent_jit_cache(platform=platform)

    import bench  # workload builder + steady-rate definition

    if args.bench_mode:
        # build_workload already folds in the BENCH_TPU_BATCH override.
        model, name, batch, table, tpu_cap, max_batch = \
            bench.build_workload(platform)

        def run_parity():
            """The 2pc parity workload ON THIS BACKEND — the backend
            that produces the headline — so the parent's gate covers
            TPU-specific engine behavior (u64 emulation, scatter
            semantics), not just a CPU rehearsal."""
            from two_phase_commit import TwoPhaseSys

            rms = int(os.environ.get("BENCH_PARITY_RMS", "5"))
            t1 = time.monotonic()
            # Bounded: on a degraded box an open-ended full enumeration
            # before the headline could burn the whole child budget. A
            # deadline-cut run reports finished=False and the parent's
            # gate falls back to its local path instead of gating on a
            # partial count.
            pdl = t1 + max(min(left() * 0.5, 180.0), 20.0)
            ptpu, prate, pfin = bench._tpu_bfs(
                TwoPhaseSys(rms), 1024, 1 << 16, symmetry=False,
                deadline=pdl, elastic_chaos=False)
            emit({"event": "parity", "platform": platform, "rms": rms,
                  "unique": ptpu.unique_state_count(),
                  "states": ptpu.state_count(),
                  "discoveries": sorted(ptpu.discoveries()),
                  "rate": round(prate, 1), "finished": pfin,
                  "sec": round(time.monotonic() - t1, 1)})

        if platform == "cpu":
            # CPU-only host: the cheap gate FIRST, so a tight watchdog
            # budget cannot leave it pending behind the slow headline
            # (ADVICE r5). On an accelerator the order is reversed —
            # tunnel-side compiles are slow and the budget must buy the
            # north-star number before anything else.
            run_parity()
        deadline = time.monotonic() + max(left() - 10.0, 5.0)
        # Resilience plumbing: with SESSION_CKPT set (the parent bench
        # supervises this child), the headline run checkpoints
        # periodically, and SESSION_RESUME (set by the parent on a
        # respawn) continues a dead predecessor's run from its newest
        # CRC-valid generation instead of restarting it.
        ckpt = os.environ.get("SESSION_CKPT") or None
        resume = os.environ.get("SESSION_RESUME") or None
        if resume:
            emit({"event": "resumed", "platform": platform,
                  "resume_from": resume})
        tpu, rate, finished = bench._tpu_bfs(model, batch, table,
                                             cap=tpu_cap, deadline=deadline,
                                             max_batch=max_batch,
                                             checkpoint_path=ckpt,
                                             resume_from=resume)
        scheduler = (tpu.scheduler_stats()
                     if hasattr(tpu, "scheduler_stats") else None)
        emit({"event": "done", "platform": platform, "workload": name,
              "batch": batch, "table": table, "cap": tpu_cap,
              "max_batch": max_batch,
              "rate": round(rate, 1), "states": tpu.state_count(),
              "unique": tpu.unique_state_count(), "finished": finished,
              "scheduler": scheduler,
              # Successor-path telemetry, explicit so a hardware A/B can
              # read K rungs / overflow redispatches / collapse ratio
              # straight off the stream (ISSUE 2).
              "succ_ladder": (scheduler or {}).get("succ_ladder"),
              "local_dedup": (scheduler or {}).get("local_dedup"),
              # Packed-arena gauges (ISSUE 4): HBM footprint next to
              # the rate, so the first real TPU window captures the
              # bandwidth story alongside the B-sweep.
              "packing": (scheduler or {}).get("packing"),
              "bytes_per_state": ((scheduler or {}).get("packing")
                                  or {}).get("bytes_per_state"),
              "arena_bytes": ((scheduler or {}).get("packing")
                              or {}).get("arena_bytes_high_water"),
              "table_bytes": ((scheduler or {}).get("packing")
                              or {}).get("table_bytes_high_water"),
              "fused_engine_error": bench.RESULT.get("fused_engine_error"),
              "trace": os.environ.get("STpu_TRACE"),
              "sec": round(time.monotonic() - t0, 1)})
        if platform != "cpu" and left() > 30:
            run_parity()
        return

    from paxos import PaxosModelCfg

    clients = int(os.environ.get("SESSION_CLIENTS", "3"))
    model = PaxosModelCfg(clients, 3).into_model()
    table = 1 << args.table_bits
    best_batch, best_rate = None, -1.0
    for B in args.batches:
        if left() < 60:
            emit({"event": "skip", "batch": B, "reason": "budget"})
            continue
        deadline = time.monotonic() + max(min(left() - 45.0, 240.0), 30.0)
        t1 = time.monotonic()
        tpu, rate, finished = bench._tpu_bfs(model, B, table, cap=args.cap,
                                             deadline=deadline)
        emit({"event": "sweep", "batch": B, "cap": args.cap,
              "rate": round(rate, 1), "states": tpu.state_count(),
              "unique": tpu.unique_state_count(), "finished": finished,
              "wall_s": round(time.monotonic() - t1, 2),
              "fused_engine_error": bench.RESULT.get("fused_engine_error")})
        bench.RESULT.pop("fused_engine_error", None)
        if rate > best_rate:
            best_batch, best_rate = B, rate

    if args.breakdown and left() > 60:
        from stateright_tpu.tpu.profiling import measure_wave_breakdown

        bd = measure_wave_breakdown(
            model, batch_size=best_batch or args.batches[0],
            table_capacity=table, max_waves=10,
            deadline_s=max(left() - 40.0, 20.0))
        bd.update({"event": "breakdown", "platform": platform,
                   "batch": best_batch or args.batches[0]})
        emit(bd)

    if args.pallas_ab and left() > 90:
        # The Pallas table holds the whole table in VMEM: 2^20 entries.
        ab_table, ab_cap = 1 << 20, min(args.cap, 120000)
        for impl in ("xla", "pallas"):
            if left() < 45:
                emit({"event": "skip", "pallas_ab": impl, "reason": "budget"})
                continue
            os.environ["BENCH_TABLE_IMPL"] = impl
            deadline = time.monotonic() + max(min(left() - 30.0, 180.0), 30.0)
            t1 = time.monotonic()
            tpu, rate, finished = bench._tpu_bfs(
                model, best_batch or args.batches[0], ab_table,
                cap=ab_cap, deadline=deadline)
            emit({"event": "pallas_ab", "table_impl": impl,
                  "batch": best_batch or args.batches[0], "cap": ab_cap,
                  "rate": round(rate, 1), "states": tpu.state_count(),
                  "finished": finished,
                  "wall_s": round(time.monotonic() - t1, 2),
                  "fused_engine_error":
                      bench.RESULT.get("fused_engine_error")})
            bench.RESULT.pop("fused_engine_error", None)
        os.environ.pop("BENCH_TABLE_IMPL", None)

    emit({"event": "session_done", "platform": platform,
          "best_batch": best_batch, "best_rate": round(best_rate, 1),
          "sec": round(time.monotonic() - t0, 1)})


if __name__ == "__main__":
    main()

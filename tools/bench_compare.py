#!/usr/bin/env python
"""Diffs BENCH_*.json result files — the regression gate for the
repo's headline throughput number.

Two files: a per-key delta table over every numeric metric the rounds
share, with the headline (``value``, states/sec) called out. More than
two (or a shell glob the caller quotes) prints the whole trajectory,
one row per round, each with its delta against the previous round::

    python tools/bench_compare.py BENCH_r07.json BENCH_r09.json
    python tools/bench_compare.py BENCH_r0*.json --max-regress 25

Exit status is the gate: non-zero when the newest file's headline
regressed more than ``--max-regress`` percent (default 20) against the
previous one — loose enough for the noisy 2-core CPU box the numbers
in this repo come from (MEASUREMENTS.md), tight enough to catch a real
cliff. ``--max-regress 0`` disables the gate (report only).

Handles both layouts the repo has shipped: the wrapped harness dump
(``{"n", "cmd", "rc", "tail", "parsed"}`` — rounds 1..7, the RESULT
dict lives under ``parsed``) and the bare RESULT dict (round 9
onward). Nested dicts (``wave_scheduler``) flatten to dotted keys.
Dependency-free; safe anywhere.

Round 20: ``BENCH_PROF=1`` runs hoist per-program roofline gauges into
the RESULT dict as ``prof.*`` keys (flops, bytes, achieved rates,
cost_ratio). They diff per-key like any other metric when both rounds
carry them, sort after the core metrics, and when only ONE side has
them (an older BENCH predating the profiler, or a disarmed run) they
are summarized in a single count line instead of itemized — old files
keep comparing cleanly.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

#: The headline metric every round's RESULT dict carries.
HEADLINE = "value"


def load_result(path: str) -> Dict[str, float]:
    """Loads one BENCH json and flattens its RESULT dict to
    ``{dotted_key: float}`` (non-numeric leaves dropped; bools are not
    metrics)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    # Wrapped harness layout: the RESULT dict is under "parsed".
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    flat: Dict[str, float] = {}

    def walk(prefix: str, obj: dict) -> None:
        for key, val in obj.items():
            name = f"{prefix}{key}"
            if isinstance(val, bool):
                continue
            if isinstance(val, (int, float)):
                flat[name] = float(val)
            elif isinstance(val, dict):
                walk(f"{name}.", val)

    walk("", doc)
    return flat


def delta_pct(old: float, new: float):
    if old == 0:
        return None
    return 100.0 * (new - old) / old


def format_diff(old_name: str, old: Dict[str, float],
                new_name: str, new: Dict[str, float]) -> str:
    width = max([len(k) for k in (set(old) & set(new))] + [6])
    header = (f"{'metric':<{width}} {old_name:>14} {new_name:>14} "
              f"{'delta%':>8}")
    lines = [header, "-" * len(header)]

    def fmt(v: float) -> str:
        return f"{v:.4g}"

    # Headline first, then everything else the rounds share; the
    # prof.* roofline block (BENCH_PROF=1, round 20) and the
    # soak_trace.* overload A/B (BENCH_SOAK_TRACE, round 21) sort last
    # so the core metrics stay where every prior round's diff put them.
    keys = sorted(set(old) & set(new),
                  key=lambda k: (k.startswith(("prof.", "soak_trace.")),
                                 k))
    if HEADLINE in keys:
        keys.remove(HEADLINE)
        keys.insert(0, HEADLINE)
    for key in keys:
        d = delta_pct(old[key], new[key])
        ds = f"{d:+.1f}" if d is not None else "-"
        mark = "  <- headline" if key == HEADLINE else ""
        lines.append(f"{key:<{width}} {fmt(old[key]):>14} "
                     f"{fmt(new[key]):>14} {ds:>8}{mark}")
    for name, extra in ((old_name, sorted(set(old) - set(new))),
                        (new_name, sorted(set(new) - set(old)))):
        # One-sided prof.* / soak_trace.* keys are expected (the other
        # round predates BENCH_PROF=1 / BENCH_SOAK_TRACE or ran
        # disarmed): count them, don't itemize.
        prof = [k for k in extra if k.startswith("prof.")]
        soak = [k for k in extra if k.startswith("soak_trace.")]
        rest = [k for k in extra
                if not k.startswith(("prof.", "soak_trace."))]
        if rest:
            lines.append(f"only in {name}: {', '.join(rest)}")
        if prof:
            lines.append(f"only in {name}: {len(prof)} prof.* roofline "
                         "key(s) (other round has no BENCH_PROF data)")
        if soak:
            lines.append(f"only in {name}: {len(soak)} soak_trace.* "
                         "overload A/B key(s) (other round has no "
                         "BENCH_SOAK_TRACE data)")
    return "\n".join(lines)


def format_trajectory(names: List[str],
                      results: List[Dict[str, float]]) -> str:
    width = max(len(n) for n in names)
    header = (f"{'round':<{width}} {'headline':>12} {'delta%':>8}")
    lines = [header, "-" * len(header)]
    prev = None
    for name, res in zip(names, results):
        head = res.get(HEADLINE)
        if head is None:
            lines.append(f"{name:<{width}} {'-':>12} {'-':>8}")
            continue
        d = delta_pct(prev, head) if prev is not None else None
        ds = f"{d:+.1f}" if d is not None else "-"
        lines.append(f"{name:<{width}} {head:>12.4g} {ds:>8}")
        prev = head
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff BENCH_*.json rounds and gate on headline "
                    "regression")
    ap.add_argument("paths", nargs="+",
                    help="two or more BENCH json files (oldest first)")
    ap.add_argument("--max-regress", type=float, default=20.0,
                    metavar="PCT",
                    help="fail when the headline drops more than PCT%% "
                         "vs the previous round (0 disables; "
                         "default %(default)s)")
    args = ap.parse_args(argv)
    if len(args.paths) < 2:
        ap.error("need at least two BENCH files to compare")

    names, results = [], []
    for path in args.paths:
        try:
            results.append(load_result(path))
        except (OSError, ValueError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        name = path.rsplit("/", 1)[-1]
        names.append(name[len("BENCH_"):-len(".json")]
                     if name.startswith("BENCH_")
                     and name.endswith(".json") else name)

    if len(results) == 2:
        print(format_diff(names[0], results[0], names[1], results[1]))
    else:
        print(format_trajectory(names, results))

    old_head = results[-2].get(HEADLINE)
    new_head = results[-1].get(HEADLINE)
    if old_head is None or new_head is None:
        print("headline: missing in one round; gate skipped")
        return 0
    d = delta_pct(old_head, new_head)
    if args.max_regress > 0 and d is not None and d < -args.max_regress:
        print(f"FAIL: headline regressed {d:.1f}% "
              f"(> {args.max_regress:g}% allowed)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

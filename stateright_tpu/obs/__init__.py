"""Run-telemetry subsystem: spans, counters, and the wave-event stream.

``STpu_TRACE=path`` streams every engine's per-dispatch wave events
(one versioned schema across classic/fused/sharded/sharded-fused and
the host BFS/DFS), plus spans and counters, as JSONL. Unset, the null
tracer makes the whole subsystem one attribute check per wave.

Consumers: ``tools/trace_lint.py`` (schema validation),
``tools/trace_export.py`` (Perfetto/Chrome trace + Prometheus dump),
``GET /.metrics`` in the explorer (live Prometheus text). See the
Observability section of ARCHITECTURE.md.
"""

from .schema import (ENGINE_IDS, EVENT_TYPES, SCHEMA_VERSION, TRACE_ENV,
                     WAVE_FIELDS, WAVE_FIELDS_V1, validate_event,
                     validate_line)
from .tracer import NULL_TRACER, NullTracer, RunTracer, tracer_from_env

__all__ = [
    "SCHEMA_VERSION", "TRACE_ENV", "ENGINE_IDS", "EVENT_TYPES",
    "WAVE_FIELDS", "WAVE_FIELDS_V1", "validate_event", "validate_line",
    "RunTracer", "NullTracer", "NULL_TRACER", "tracer_from_env",
]

"""Run-telemetry subsystem: spans, counters, and the wave-event stream.

``STpu_TRACE=path`` streams every engine's per-dispatch wave events
(one versioned schema across classic/fused/sharded/sharded-fused, the
host BFS/DFS, and the elastic coordinator + its per-worker relayed
streams), plus spans and counters, as JSONL. Unset, the null tracer
makes the whole subsystem one attribute check per wave.

Two distributed pieces ride on the same schema (round 12):
``collect.py`` merges the elastic workers' relayed streams into one
causally-ordered trace with per-round straggler attribution, and
``flight.py`` keeps an always-on bounded ring of recent events in
every engine/worker/coordinator that dumps a postmortem file on
failure — even when tracing is off.

Consumers: ``tools/trace_lint.py`` (schema validation),
``tools/trace_export.py`` (Perfetto/Chrome trace + Prometheus dump),
``tools/trace_summary.py`` (per-worker tables), ``GET /.metrics`` in
the explorer (live Prometheus text). See the Observability section of
ARCHITECTURE.md.
"""

from .anomaly import ANOMALY_ENV, SlowWaveDetector, detector_from_env
from .collect import RelayTracer, TraceCollector
from .flight import (FLIGHT_DIR_ENV, FLIGHT_ENV, FlightRecorder,
                     NULL_RECORDER, NullFlightRecorder, postmortem_path,
                     recorder_from_env)
from .hist import (BUCKET_BOUNDS, HIST_ENV, Histogram, HistogramSet,
                   NULL_OBS, NullWaveObs, SNAP_ENV, WaveObs,
                   prometheus_hist_lines, wave_obs_from_env)
from .prof import (NULL_PROF, NullWaveProfiler, PROF_ENV,
                   PROF_SAMPLE_ENV, WaveProfiler, cost_record,
                   prof_from_env, program_records,
                   prometheus_prof_lines, roofline)
from .schema import (ENGINE_IDS, EVENT_TYPES, SCHEMA_VERSION, TRACE_ENV,
                     WAVE_FIELDS, WAVE_FIELDS_V1, WAVE_FIELDS_V2,
                     validate_event, validate_line)
from .slo import SLO_ENV, SloTracker, slo_from_env
from .tracer import NULL_TRACER, NullTracer, RunTracer, tracer_from_env

__all__ = [
    "SCHEMA_VERSION", "TRACE_ENV", "ENGINE_IDS", "EVENT_TYPES",
    "WAVE_FIELDS", "WAVE_FIELDS_V1", "WAVE_FIELDS_V2", "validate_event",
    "validate_line",
    "RunTracer", "NullTracer", "NULL_TRACER", "tracer_from_env",
    "RelayTracer", "TraceCollector",
    "FlightRecorder", "NullFlightRecorder", "NULL_RECORDER",
    "recorder_from_env", "postmortem_path", "FLIGHT_ENV",
    "FLIGHT_DIR_ENV",
    "BUCKET_BOUNDS", "HIST_ENV", "SNAP_ENV", "Histogram",
    "HistogramSet", "WaveObs", "NullWaveObs", "NULL_OBS",
    "wave_obs_from_env", "prometheus_hist_lines",
    "SLO_ENV", "SloTracker", "slo_from_env",
    "ANOMALY_ENV", "SlowWaveDetector", "detector_from_env",
    "PROF_ENV", "PROF_SAMPLE_ENV", "WaveProfiler", "NullWaveProfiler",
    "NULL_PROF", "prof_from_env", "cost_record", "roofline",
    "program_records", "prometheus_prof_lines",
]

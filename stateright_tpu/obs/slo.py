"""Service-level objectives: rolling error-budget burn over live runs.

An SLO here is a ratio objective over a rolling wall-clock window:
"at least ``target`` of the events in the last ``window_s`` seconds
must be good". Latency objectives derive good/bad from a threshold
(``value <= threshold``); the wave-success objective takes good/bad
directly (a dispatch that paid an overflow regather is a bad event).
The burn rate is the classic error-budget quotient —
``bad_fraction / (1 - target)`` — so ``burn > 1`` means the window is
eating budget faster than the objective allows, which is exactly the
breach condition.

Three objectives ship by default (``STpu_SLO=1``):

- ``job_latency`` — submit-to-done seconds per service job
  (threshold 2.0 s, target p99: 0.99 of jobs under threshold);
- ``queue_wait`` — seconds a job waited for a worker slot
  (threshold 0.5 s, target 0.99);
- ``wave_success`` — dispatches without an overflow regather
  (target 0.999).

``STpu_SLO`` accepts ``k=v`` overrides (comma-separated):
``job_latency=0.25`` / ``queue_wait=0.1`` retune the latency
thresholds (seconds), ``wave_success=0.9999`` retunes that target
ratio, and ``window=30`` sets the rolling window (seconds) for all
objectives. Unknown keys are ignored (forward compatibility beats a
crashed service).

Breach lifecycle: an objective starts healthy; once a window holds at
least :data:`MIN_SAMPLES` events AND the good ratio drops below
target, it transitions to breaching and ``observe`` returns one
``slo_breach`` payload (the facade emits it through the tracer and
the flight ring — edge-triggered, so a sustained breach is one event,
not an event per observation). It recovers silently when the rolling
ratio climbs back to target; ``status()`` always shows the level.
``GET /.healthz`` returns 503 iff any objective is currently
breaching.

Disarmed (``STpu_SLO`` unset): ``slo_from_env`` returns ``None`` and
the facade never constructs a tracker — zero cost, pinned by the same
poisoned-null test as the histograms.

Dependency-free (no jax, no numpy).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["SLO_ENV", "MIN_SAMPLES", "DEFAULT_OBJECTIVES", "SloTracker",
           "slo_from_env", "prometheus_slo_lines"]

#: Environment knob: ``STpu_SLO=1`` arms the default objectives;
#: ``k=v`` pairs override (see the module docstring).
SLO_ENV = "STpu_SLO"

#: A window judges nothing until it holds this many events — a single
#: bad first event must not 503 the service.
MIN_SAMPLES = 10

_WINDOW_DEFAULT_S = 60.0

#: name -> (latency threshold seconds or None, target good-ratio).
DEFAULT_OBJECTIVES: Dict[str, tuple] = {
    "job_latency": (2.0, 0.99),
    "queue_wait": (0.5, 0.99),
    "wave_success": (None, 0.999),
}


class SloTracker:
    """Rolling-window good/bad accounting for a fixed objective set."""

    enabled = True

    def __init__(self, objectives: Optional[Dict[str, tuple]] = None,
                 window_s: float = _WINDOW_DEFAULT_S):
        self.window_s = max(1.0, float(window_s))
        self._lock = threading.Lock()
        self._objs: Dict[str, dict] = {}
        for name, (threshold, target) in (
                objectives or DEFAULT_OBJECTIVES).items():
            self._objs[name] = {
                "threshold": threshold,
                "target": float(target),
                # rolling (t, ok) events; pruned against window_s on
                # every observe — bounded by the producer's own rate.
                "events": deque(),
                "bad": 0,
                "breaching": False,
                "breaches": 0,
            }

    def observe(self, name: str, ok: Optional[bool] = None,
                value: Optional[float] = None,
                t: Optional[float] = None) -> Optional[dict]:
        """Records one event; returns an ``slo_breach`` payload on the
        healthy->breaching transition, else None."""
        obj = self._objs.get(name)
        if obj is None:
            return None
        if ok is None:
            thr = obj["threshold"]
            ok = thr is None or (value is not None and value <= thr)
        t = time.monotonic() if t is None else float(t)
        with self._lock:
            events = obj["events"]
            events.append((t, ok))
            if not ok:
                obj["bad"] += 1
            horizon = t - self.window_s
            while events and events[0][0] < horizon:
                _, old_ok = events.popleft()
                if not old_ok:
                    obj["bad"] -= 1
            total = len(events)
            bad = obj["bad"]
            ratio = (total - bad) / total if total else 1.0
            breaching = total >= MIN_SAMPLES and ratio < obj["target"]
            transition = breaching and not obj["breaching"]
            if transition:
                obj["breaches"] += 1
            obj["breaching"] = breaching
            if not transition:
                return None
            budget = 1.0 - obj["target"]
            burn = (bad / total) / budget if budget > 0 else float(bad)
            return {"objective": name, "target": obj["target"],
                    "burn": round(burn, 4),
                    "window_s": self.window_s,
                    "good": total - bad, "bad": bad}

    def status(self) -> dict:
        """The live SLO surface (``scheduler_stats()["slo"]``,
        ``GET /.healthz`` detail, the explorer ops panel)."""
        with self._lock:
            objectives = {}
            for name, obj in sorted(self._objs.items()):
                total = len(obj["events"])
                bad = obj["bad"]
                ratio = (total - bad) / total if total else 1.0
                budget = 1.0 - obj["target"]
                objectives[name] = {
                    "threshold": obj["threshold"],
                    "target": obj["target"],
                    "window_s": self.window_s,
                    "good": total - bad,
                    "bad": bad,
                    "ratio": round(ratio, 6),
                    "burn": round((bad / total) / budget, 4)
                    if total and budget > 0 else 0.0,
                    "breaching": obj["breaching"],
                    "breaches": obj["breaches"],
                }
            return {"healthy": not any(o["breaching"]
                                       for o in objectives.values()),
                    "objectives": objectives}

    @property
    def healthy(self) -> bool:
        with self._lock:
            return not any(o["breaching"] for o in self._objs.values())


def prometheus_slo_lines(status: dict) -> list:
    """The ``stpu_slo_*`` exposition families for one
    :meth:`SloTracker.status` payload — shared by the service metrics
    and the explorer's checker-mode ``GET /.metrics``."""
    lines = ["# TYPE stpu_slo_healthy gauge",
             f"stpu_slo_healthy {int(status['healthy'])}",
             "# TYPE stpu_slo_burn gauge"]
    objectives = sorted(status["objectives"].items())
    lines += [f'stpu_slo_burn{{objective="{name}"}} {obj["burn"]}'
              for name, obj in objectives]
    lines.append("# TYPE stpu_slo_breaches_total counter")
    lines += [f'stpu_slo_breaches_total{{objective="{name}"}} '
              f'{obj["breaches"]}' for name, obj in objectives]
    return lines


def slo_from_env() -> Optional[SloTracker]:
    """``None`` when ``STpu_SLO`` is unset/``0`` (the facade stays
    cost-free); a configured tracker otherwise."""
    raw = os.environ.get(SLO_ENV, "")
    if raw in ("", "0"):
        return None
    objectives = {k: list(v) for k, v in DEFAULT_OBJECTIVES.items()}
    window_s = _WINDOW_DEFAULT_S
    for part in raw.split(","):
        if "=" not in part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        try:
            num = float(val)
        except ValueError:
            continue
        if key == "window":
            window_s = num
        elif key == "wave_success":
            objectives[key][1] = num
        elif key in objectives:
            objectives[key][0] = num
    return SloTracker({k: tuple(v) for k, v in objectives.items()},
                      window_s=window_s)

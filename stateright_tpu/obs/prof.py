"""Continuous wave profiler: cost-model capture + roofline attribution.

The obs stack through round 19 can say *how fast* a run went (wave
events, latency histograms, SLOs) but not *why*: no compiled program
records its FLOP/byte cost, so the matmul-vs-step question at the heart
of ROADMAP item 2 can only be answered by hand. This module closes the
gap in three parts:

1. **Static cost capture.** Every program built through the engines'
   ``_cached_program`` funnel records its XLA cost model at compile
   time — ``compiled.cost_analysis()`` (flops, bytes accessed) and
   ``compiled.memory_analysis()`` (argument/output/temp bytes, summed
   to a peak-memory estimate) — keyed by the canonical program key.
   Records live in a **process-wide** table on purpose: the shared jit
   cache (``jit_cache.WaveProgramCache``) hands the same compiled
   program to every engine instance in the process, so a record
   captured at first build must be findable from an instance that only
   ever saw a cache hit. Hits pay a dict lookup; rebuilds pay nothing.
2. **Sampled stage timing.** Every Nth dispatch (``STpu_PROF_SAMPLE``,
   default 32 — plus the first dispatch of every program key, so every
   compiled program gets at least one measurement) is timed to a rest
   point with ``block_until_ready``. The measured seconds against the
   static record yield the roofline gauges — achieved flops/s, bytes/s,
   arithmetic intensity — emitted as a ``profile_snapshot`` event
   (schema v13) through the producer's tracer (and relay, and flight
   ring), plus the nullable wave fields ``cost_flops`` / ``cost_bytes``
   / ``cost_ratio`` stamped centrally like every versioned wave key.
3. **Compile-regression detection.** ``cost_ratio`` is the sampled wave
   seconds normalized by the program's OWN first sampled baseline —
   always finite, 1.0 at the baseline, drifting up when the same
   program gets slower. The slow-wave detector (``obs/anomaly.py``)
   reads it off the wave entry and attributes a ``cost_model`` cause
   when a key's ratio drifts from its ratio history while the program
   runs.

Honesty notes, load-bearing for reading the numbers:

- **Sampling perturbs the pipeline.** The rest-point
  ``block_until_ready`` serializes the sampled dispatch against its
  pipeline (classic dispatch-ahead, fused multi-dispatch inflight), so
  1/N waves pay a join the unprofiled run overlaps. MEASUREMENTS.md
  carries the armed-vs-disarmed A/B; at the default cadence the delta
  sits inside rep spread on the 1-core CI box.
- **CPU cost models are approximate.** The CPU backend's
  ``cost_analysis()`` reports optimized-HLO flop/byte counts (returned
  as a single-element list of dicts — handled here), with no
  ``optimal_seconds``; a fallback program that never AOT-compiled
  (``jax.jit`` lazy path) exposes no cost analysis at all and records
  null flops/bytes. ``cost_ratio`` is defined against the program's
  own measured history precisely so it stays meaningful on every
  backend, with or without a cost model.

Disarmed (``STpu_PROF`` unset): ``prof_from_env`` returns the shared
:data:`NULL_PROF` and every producer hot loop pays one attribute check
(``if self._prof.enabled:``) — the poisoned-null test pins this like
rounds 8/18.

Dependency-free beyond ``obs.schema`` (no jax, no numpy): the capture
helpers duck-type the compiled executable, so the tools and tests
import this without a backend.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional

__all__ = [
    "PROF_ENV", "PROF_SAMPLE_ENV", "WaveProfiler", "NullWaveProfiler",
    "NULL_PROF", "prof_from_env", "cost_record", "roofline",
    "program_records", "clear_program_records", "prometheus_prof_lines",
]

#: Environment knob: ``STpu_PROF=1`` arms the continuous profiler.
#: Unset/``0`` means the shared null profiler — one attribute check
#: per dispatch.
PROF_ENV = "STpu_PROF"

#: Environment knob: sample every Nth dispatch (default 32). ``1``
#: times every dispatch (offline profiling / tests); the first
#: dispatch of each program key is always sampled regardless.
PROF_SAMPLE_ENV = "STpu_PROF_SAMPLE"

_SAMPLE_DEFAULT = 32

#: Process-wide static cost records: canonical program key ->
#: ``{"flops", "bytes", "peak_bytes", "kernel_path"}``. See the module
#: docstring for why this is process-global rather than per-profiler.
_COST_LOCK = threading.Lock()
_COST_RECORDS: Dict[str, dict] = {}


def cost_record(program) -> Optional[dict]:
    """Extracts the static cost model of one AOT-compiled executable:
    ``{"flops", "bytes", "peak_bytes", "kernel_path": None}``. Returns
    ``None`` when the object exposes no ``cost_analysis`` (the lazy
    ``jax.jit`` fallback, a host callable) — callers record a null-cost
    entry so the key is still attributed. Never raises."""
    try:
        ca = program.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        # The CPU client returns a single-element list of dicts.
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None
    try:
        flops = float(ca.get("flops", 0.0) or 0.0)
        byts = float(ca.get("bytes accessed", 0.0) or 0.0)
    except (TypeError, ValueError):
        return None
    rec = {"flops": flops, "bytes": byts, "peak_bytes": None,
           "kernel_path": None}
    try:
        ma = program.memory_analysis()
        rec["peak_bytes"] = int(
            getattr(ma, "argument_size_in_bytes", 0)
            + getattr(ma, "output_size_in_bytes", 0)
            + getattr(ma, "temp_size_in_bytes", 0))
    except Exception:
        pass  # the cost half alone is still worth recording
    return rec


def roofline(rec: Optional[dict], measured_s: float) -> dict:
    """The roofline gauges for one measured execution of a program with
    static record ``rec``: achieved flops/s and bytes/s, and arithmetic
    intensity (flops per byte accessed — the roofline x-axis). All
    ``None`` when the program has no cost record."""
    out = {"flops": None, "bytes": None, "peak_bytes": None,
           "flops_per_s": None, "bytes_per_s": None, "intensity": None}
    if not rec:
        return out
    flops, byts = rec.get("flops"), rec.get("bytes")
    out["flops"], out["bytes"] = flops, byts
    out["peak_bytes"] = rec.get("peak_bytes")
    if isinstance(flops, (int, float)) and measured_s > 0:
        out["flops_per_s"] = round(flops / measured_s, 3)
    if isinstance(byts, (int, float)) and measured_s > 0:
        out["bytes_per_s"] = round(byts / measured_s, 3)
    if isinstance(flops, (int, float)) and isinstance(byts, (int, float)) \
            and byts > 0:
        out["intensity"] = round(flops / byts, 6)
    return out


def program_records(prefix: Optional[str] = None) -> Dict[str, dict]:
    """A copy of the process-wide cost-record table, optionally
    filtered to keys starting with ``prefix`` (program keys lead with
    the producer id, so a producer's own programs filter cleanly)."""
    with _COST_LOCK:
        return {k: dict(v) for k in sorted(_COST_RECORDS)
                if prefix is None or k.startswith(prefix)
                for v in (_COST_RECORDS[k],)}


def clear_program_records() -> None:
    """Drops every static record (tests only — the table is otherwise
    append-only for the life of the process, like the jit cache)."""
    with _COST_LOCK:
        _COST_RECORDS.clear()


class NullWaveProfiler:
    """The disarmed profiler: every method a no-op, ``enabled`` False.
    Hot paths must check ``enabled`` BEFORE calling anything — the
    disarmed-cost test poisons these methods, so a stray call (= a
    stray per-dispatch cost with the subsystem off) fails the suite."""

    __slots__ = ()
    enabled = False
    armed = False

    def capture(self, key, program) -> None:
        pass

    def should_sample(self, key=None) -> bool:
        return False

    def wave(self, entry, key=None, measured_s=None, tracer=None,
             flight=None) -> None:
        pass

    def stats(self) -> dict:
        return {}

    def close(self, tracer=None) -> None:
        pass


#: The shared disarmed profiler (``prof_from_env`` returns this very
#: object when ``STpu_PROF`` is unset — identity-testable).
NULL_PROF = NullWaveProfiler()


class WaveProfiler:
    """Per-producer continuous profiler: capture at compile, sample at
    dispatch, stamp at the wave event. One instance per producer
    (engine, elastic worker, offline profiling run) so the sampling
    cadence and the snapshot ordinal are per producer; the static cost
    table is shared process-wide (module docstring)."""

    enabled = True
    armed = True

    def __init__(self, producer: str, sample_every: int = _SAMPLE_DEFAULT):
        self.producer = str(producer)
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._dispatches = 0
        self._sampled = 0
        self._snap = 0
        self._captured = 0
        #: per-key first sampled seconds — the cost_ratio denominator.
        self._baseline: Dict[str, float] = {}
        #: per-key latest snapshot payload (the live-metrics surface).
        self._last: Dict[str, dict] = {}
        #: keys that have had at least one sampled dispatch.
        self._seen: set = set()

    # -- Compile-time capture ----------------------------------------------

    def capture(self, key: str, program) -> None:
        """Records ``program``'s static cost model under ``key`` if no
        record exists yet (cold path: runs at most once per program per
        process — compile dwarfs it; shared-cache hits find the
        first builder's record)."""
        with _COST_LOCK:
            if key in _COST_RECORDS:
                return
        rec = cost_record(program)
        if rec is None:
            # No AOT cost analysis (lazy-jit fallback): a null-cost
            # record still attributes the key and stops re-probing.
            rec = {"flops": None, "bytes": None, "peak_bytes": None,
                   "kernel_path": None}
        with _COST_LOCK:
            _COST_RECORDS.setdefault(key, rec)
        with self._lock:
            self._captured += 1

    # -- Dispatch-time sampling --------------------------------------------

    def should_sample(self, key: Optional[str] = None) -> bool:
        """One call per dispatch (armed paths only). True every
        ``sample_every``-th dispatch, and ALWAYS on the first dispatch
        of a new program key — so every compiled program carries at
        least one measured ``cost_ratio``. Deterministic: same dispatch
        sequence, same sampled set."""
        with self._lock:
            n = self._dispatches
            self._dispatches += 1
            first = key is not None and key not in self._seen
            if key is not None:
                self._seen.add(key)
        return first or n % self.sample_every == 0

    def wave(self, entry: dict, key: Optional[str] = None,
             measured_s: Optional[float] = None, tracer=None,
             flight=None) -> None:
        """Stamps the v13 cost fields onto one dispatch-log entry (the
        same dict the tracer, the flight ring, and the anomaly detector
        see) and, when the dispatch was sampled (``measured_s`` set),
        emits a ``profile_snapshot`` event with the roofline gauges."""
        rec = None
        if key is not None:
            with _COST_LOCK:
                rec = _COST_RECORDS.get(key)
            if rec is not None and rec.get("kernel_path") is None:
                kp = entry.get("kernel_path")
                if kp is not None:
                    with _COST_LOCK:
                        rec["kernel_path"] = kp
        entry["cost_flops"] = rec.get("flops") if rec else None
        entry["cost_bytes"] = rec.get("bytes") if rec else None
        ratio = None
        if measured_s is not None and key is not None:
            measured_s = max(float(measured_s), 1e-9)
            if math.isfinite(measured_s):
                with self._lock:
                    base = self._baseline.get(key)
                    if base is None:
                        base = self._baseline[key] = measured_s
                    self._sampled += 1
                    self._snap += 1
                    snap = self._snap
                ratio = round(measured_s / base, 6)
                evt = dict(roofline(rec, measured_s), key=key,
                           kernel_path=entry.get("kernel_path"),
                           expand_impl=entry.get("expand_impl"),
                           snap=snap, measured_s=round(measured_s, 6),
                           cost_ratio=ratio)
                with self._lock:
                    self._last[key] = dict(evt)
                if tracer is not None and tracer.enabled:
                    tracer.event("profile_snapshot", **evt)
                if flight is not None and flight.armed:
                    flight.record_event("profile_snapshot", **evt)
        entry["cost_ratio"] = ratio

    # -- Surfaces -----------------------------------------------------------

    def stats(self) -> dict:
        """The aggregated view ``scheduler_stats`` / bench /
        ``GET /.metrics`` surface as ``prof``."""
        with self._lock:
            last = {k: dict(self._last[k]) for k in sorted(self._last)}
            return {"dispatches": self._dispatches,
                    "sampled": self._sampled,
                    "sample_every": self.sample_every,
                    "captured": self._captured,
                    "programs": last}

    def close(self, tracer=None) -> None:
        """Teardown hook for API symmetry with the sibling facades.
        Snapshots are emitted per sample (nothing cumulative is held
        back), so there is nothing to flush."""


def prometheus_prof_lines(stats: dict, producer: str,
                          prefix: str = "stpu_") -> List[str]:
    """Prometheus exposition lines for one profiler's ``stats()``
    payload — the ``stpu_prof_*`` families on ``GET /.metrics``."""
    if not stats:
        return []
    esc = str(producer).replace('"', "'")
    lines = [
        f'{prefix}prof_dispatches_total{{engine="{esc}"}} '
        f'{int(stats.get("dispatches") or 0)}',
        f'{prefix}prof_sampled_total{{engine="{esc}"}} '
        f'{int(stats.get("sampled") or 0)}',
        f'{prefix}prof_programs{{engine="{esc}"}} '
        f'{len(stats.get("programs") or {})}',
    ]
    for key, snap in sorted((stats.get("programs") or {}).items()):
        kesc = str(key).replace('"', "'")
        base = f'engine="{esc}",key="{kesc}"'
        for field, family in (("flops", "prof_flops"),
                              ("bytes", "prof_bytes"),
                              ("flops_per_s", "prof_flops_per_s"),
                              ("bytes_per_s", "prof_bytes_per_s"),
                              ("intensity", "prof_intensity"),
                              ("cost_ratio", "prof_cost_ratio"),
                              ("measured_s", "prof_measured_seconds")):
            val = snap.get(field)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                lines.append(f"{prefix}{family}{{{base}}} {val}")
    return lines


def prof_from_env(producer: str):
    """The profiler factory every producer uses: the shared
    :data:`NULL_PROF` when ``STpu_PROF`` is unset/``0`` (no
    allocation, one attribute check per dispatch); an armed
    :class:`WaveProfiler` otherwise, with the ``STpu_PROF_SAMPLE``
    cadence."""
    if os.environ.get(PROF_ENV, "") in ("", "0"):
        return NULL_PROF
    try:
        sample = int(os.environ.get(PROF_SAMPLE_ENV, "")
                     or _SAMPLE_DEFAULT)
    except ValueError:
        sample = _SAMPLE_DEFAULT
    return WaveProfiler(producer, sample_every=sample)

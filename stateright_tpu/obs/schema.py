"""The versioned run-telemetry event schema.

One schema, every producer: the four device engines, the host BFS/DFS
checkers, ``profiling.py``, ``bench.py``, and ``tools/device_session.py``
all emit events that validate against the definitions here, so a single
trace file (``STpu_TRACE=path``, JSONL) can be linted
(``tools/trace_lint.py``), exported to a Perfetto-loadable Chrome trace
or a Prometheus text dump (``tools/trace_export.py``), and diffed across
rounds without per-engine parsers.

Two event families share the stream:

- **Trace events** carry a ``type`` key: ``run_start``, ``wave``,
  ``span``, ``counter``, ``gauge``, ``grow``, ``overflow_redispatch``,
  ``run_end``. The tracer stamps every one with ``schema_version``,
  ``engine``, ``run`` (a per-tracer id, so interleaved producers in one
  file separate cleanly), and ``t`` (``time.monotonic()`` seconds).
- **Session events** carry an ``event`` key — the
  ``tools/device_session.py`` stdout protocol (``init`` / ``sweep`` /
  ``done`` / ...), which predates the tracer but is versioned and
  timestamped by the same rules so ``trace_lint`` validates a captured
  session verbatim.

The WAVE event is the load-bearing one: every engine emits the exact
same field set (``WAVE_FIELDS``) per dispatch, with ``null`` for fields
an engine genuinely has no value for (e.g. the host engines have no
device hash table, so ``load_factor`` is ``null`` — but the KEY is
present; consumers never need per-engine schemas). The cross-engine
suite in ``tests/test_obs_trace.py`` pins this.

This module is dependency-free (no jax, no numpy) on purpose: the lint
tool and the tests import it without touching a backend.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = [
    "SCHEMA_VERSION", "TRACE_ENV", "EVENT_TYPES", "ENGINE_IDS",
    "SHED_REASONS",
    "WAVE_FIELDS", "WAVE_FIELDS_V1", "WAVE_FIELDS_V2",
    "WAVE_FIELDS_V5", "WAVE_FIELDS_V6", "WAVE_FIELDS_V8",
    "WAVE_FIELDS_V9", "WAVE_FIELDS_V11", "WAVE_FIELDS_V12",
    "validate_event", "validate_line",
]

#: v14: the closed vocabulary a ``shed`` event's ``reason`` must come
#: from — lives HERE (not in service/control.py) so the jax-free
#: consumers (``tools/trace_lint.py``) can validate it without pulling
#: the service package: ``slo_burn`` (admission gate engaged, priority
#: below the protected floor), ``brownout`` (the ladder raised the
#: floor over this priority), ``retry_budget`` (per-tenant token
#: bucket empty), ``queue_full`` (the bounded queue itself overflowed).
SHED_REASONS = ("slo_burn", "brownout", "retry_budget", "queue_full")

#: Bump on any field addition/removal/retyping; consumers gate on it.
#: v2 (round 9): wave events gained the packed-arena bandwidth gauges
#: ``bytes_per_state`` / ``arena_bytes`` / ``table_bytes``. v3 (round
#: 10): the resilience event family — ``fault`` (an ``STpu_FAULTS``
#: injection fired), ``recover`` (a supervised retry or in-engine
#: degradation recovered the run), ``degrade`` (graceful capability
#: reduction, e.g. the OOM batch-bucket halving), and terminal
#: ``abort`` (supervision exhausted its retries); wave fields are
#: unchanged from v2. v4 (round 11): the membership/elasticity family
#: — ``worker_lost`` (a heartbeat lease lapsed or a worker socket
#: died), ``migrate_done`` (a lost worker's partitions were rebuilt on
#: a survivor from their per-shard checkpoint generations),
#: ``rebalance`` (a joining worker received migrated partitions at a
#: drained barrier), and ``retry`` (one Supervisor retry record —
#: attempt index, jittered backoff, resume source); plus the
#: ``elastic`` coordinator as a wave-event producer. Wave fields are
#: unchanged from v2. v5 (round 12): distributed observability — wave
#: events gained the attribution keys ``worker`` (the elastic worker
#: that did the work), ``seq`` (the worker's per-process emission
#: sequence — the collector's merge/ordering key), ``epoch`` (the
#: ownership epoch the wave ran under), and ``round`` (the coordinated
#: round index); all four are ``null`` outside the elastic runtime.
#: New producers/events: ``elastic_worker`` (per-worker wave streams,
#: relayed to the coordinator and merged by ``obs/collect.py``),
#: ``straggler`` (the coordinator's per-round attribution record:
#: slowest worker, barrier wait-time share, per-worker segment
#: timings), and ``postmortem`` (the flight-recorder dump header —
#: ``obs/flight.py`` writes one per ring dump, followed by the
#: recorded events). ``retry``/``abort``/``worker_lost`` may carry an
#: optional ``dump`` rider naming the postmortem file. v6 (round 13):
#: the tiered-state-store family — wave events gained the per-tier
#: occupancy gauges ``tier_device_rows`` / ``tier_device_bytes`` /
#: ``tier_host_rows`` / ``tier_host_bytes`` / ``tier_disk_rows`` /
#: ``tier_disk_bytes`` (``null`` when the store is disarmed); new
#: event types ``spill`` (rows moved down a tier), ``page_in`` (a
#: paged-out frontier block came back ahead of dispatch), and
#: ``pressure`` (a tier crossed or reset against its byte budget —
#: the lint's monotonicity window marker). The host checkers and the
#: elastic runtime also stopped emitting permanent nulls for
#: ``capacity``/``load_factor``/``out_rows`` (real host-store
#: occupancy gauges; trace_lint enforces this for v6+ captures).
#: v7 (round 14): the job-service family (checking as a service) —
#: ``job_submit`` (a job entered the service queue: ``job`` id, the
#: corpus ``model`` name, the selected ``engine``), ``job_done`` (the
#: job ran to completion; carries its final cumulative counters), and
#: ``job_abort`` (the job left the service without completing —
#: preempted by ``DELETE /jobs/<id>``, failed past supervision, or
#: rejected; ``reason`` says which). ``tools/trace_lint.py`` asserts
#: every ``job_submit`` is eventually followed by a ``job_done`` or
#: ``job_abort`` for the SAME job id — a stream that ends with a job
#: neither finished nor acknowledged lost work. Wave fields are
#: unchanged from v6; the ``service`` meta-producer emits the family.
#: v8 (round 15): the single-kernel wave — wave events gained
#: ``kernel_path`` (which successor-path implementation the dispatch
#: ran: ``megakernel`` / ``interpret`` / ``pallas_probe`` / ``xla``;
#: ``null`` on producers with no device kernel, i.e. the host checkers
#: and the elastic coordinator) and ``rows`` (valid frontier rows the
#: dispatch consumed — with ``bucket`` x ``waves`` this yields kernel
#: occupancy, the figure megakernel A/Bs are judged against; ``null``
#: where not tracked). Wave fields are otherwise unchanged from v6.
#: v9 (round 16): cross-job wave multiplexing — wave events gained the
#: per-job attribution keys ``job_id`` (which service job the counted
#: work belongs to; ``null`` on solo-engine waves and on a mux wave's
#: TOTAL line) and ``jobs_in_wave`` (how many tenants shared the
#: dispatch; ``null`` outside the multiplexer). A mux group emits one
#: job_id-``null`` total per dispatch followed by exactly
#: ``jobs_in_wave`` job-attributed wave events whose
#: successors/candidates/novel sum to the total's —
#: ``tools/trace_lint.py`` enforces the split. New ``mux`` wave-event
#: producer (the shared group engine).
#: v10 (round 17): asynchronous host I/O — wave events gained
#: ``io_stall_s`` (seconds the wave loop spent blocked on host I/O
#: since the previous wave event: safe-point joins on the background
#: writer plus any synchronous write time; ``null`` where not
#: tracked). New event types ``ckpt_begin`` (a checkpoint
#: generation's snapshot was captured at a safe point and its write
#: started — possibly on the writer thread) and ``ckpt_done`` (that
#: generation landed durably). ``tools/trace_lint.py`` asserts every
#: ``ckpt_begin`` is eventually paired with a ``ckpt_done`` — or
#: explained by a ``fault``/``abort`` (a write that died mid-flight
#: surfaces at the next safe point) — and that a run's summed
#: ``io_stall_s`` fits inside its ``run_end`` duration window.
#: v11 (round 18): service-level observability — no wave-field
#: changes; three new event types. ``hist_snapshot`` carries one
#: producer's deterministic latency histograms (``obs/hist.py``:
#: fixed power-of-two buckets, cumulative-since-run-start counts) at a
#: bounded cadence — ``hists`` maps Prometheus-style series keys
#: (``name{label="v"}``) to ``{"buckets", "sum", "count"}`` and
#: ``snap`` is the producer's emission ordinal.
#: ``tools/trace_lint.py`` asserts per (run, series): bucket counts
#: sum to ``count``, and ``count``/``sum`` never decrease across
#: snapshots (``snap`` strictly increases per run). ``slo_breach``
#: records an objective's healthy->breaching transition
#: (``obs/slo.py``: rolling error-budget windows; edge-triggered).
#: ``anomaly`` records one slow-wave verdict from the online
#: per-program-key EWMA+MAD detector (``obs/anomaly.py``), with the
#: ``cause`` attributed from gauges already on the wave stream:
#: ``compile`` / ``io_stall`` / ``straggler`` / ``spill`` /
#: ``unknown``. Elastic workers relay their snapshots through the v5
#: relay machinery, so they merge causally like wave events; flight-
#: recorder dumps append the producer's final snapshot.
#: v12 (round 19): MXU-shaped successor generation — wave events
#: gained ``expand_impl`` (which expand-stage implementation the
#: dispatch's wave program embeds: ``matmul`` — the compiled
#: transition-table form — or ``step``, the vmapped ``DeviceModel.
#: step``; ``null`` on producers without a device wave). The v8
#: ``kernel_path`` values gained ``+matmul``-suffixed variants
#: (``xla+matmul`` / ``megakernel+matmul`` / ``interpret+matmul`` /
#: ``pallas_probe+matmul``) — the expand swap composes with every
#: kernel gate, and the recorded path must be the executed path on
#: both axes. The static per-row MAC count rides as a ``matmul_ops``
#: gauge event at run start when the plan is active.
#: v13 (round 20): the continuous wave profiler (``obs/prof.py``) —
#: wave events gained the cost-attribution keys ``cost_flops`` /
#: ``cost_bytes`` (the executed program's static XLA cost model:
#: ``cost_analysis()`` flops and bytes accessed, captured once at
#: compile and stamped on every dispatch; ``null`` when the profiler
#: is disarmed or the program never AOT-compiled) and ``cost_ratio``
#: (sampled dispatches only: measured wave seconds normalized by the
#: program's own first sampled baseline — finite by construction,
#: 1.0 at baseline; ``null`` on unsampled dispatches). New event type
#: ``profile_snapshot``: one sampled dispatch's roofline gauges —
#: achieved flops/s, bytes/s, arithmetic intensity, peak-memory
#: estimate — keyed by the canonical program key; ``snap`` is the
#: producer's sample ordinal (strictly increasing per run, like the
#: v11 hist ordinal). The v11 ``anomaly`` cause vocabulary gained
#: ``cost_model`` (a program drifting from its own cost-normalized
#: history). Elastic workers relay their snapshots through the v5
#: relay machinery like hist snapshots.
#: v14 (round 21): closed-loop overload control (service/control.py)
#: — no wave-field changes; five new event types. ``admit`` records
#: one submission the controller let through while the admission gate
#: was engaged (pressure was on but the job's priority cleared the
#: shed threshold); ``shed`` records one submission rejected at the
#: door (HTTP 429) — it ALWAYS carries a machine-readable ``reason``
#: (``slo_burn`` / ``queue_full`` / ``retry_budget`` / ``brownout``)
#: and the ``retry_after_s`` the client was told, computed from the
#: observed drain rate. ``park`` records the controller preempting a
#: running job to protect an at-risk deadline (the job is
#: checkpointed, never lost); ``resume`` records the parked job's
#: automatic resubmission (``resumed_as`` is the continuation job id).
#: ``tools/trace_lint.py`` asserts every ``park`` is eventually
#: followed by a ``resume`` or a terminal ``job_abort`` for the SAME
#: job id. ``controller`` records one brownout-ladder transition —
#: edge-triggered (consecutive events must change ``rung``), with
#: round-10 ``requested``/``kept`` honesty: ``requested`` is the rung
#: the policy asked for, ``kept`` the rung actually in force after
#: actuation.
#: v1-v13 streams still validate (against their version's field set);
#: streams NEWER than this validator are rejected with a clear
#: upgrade message instead of a cascade of field-set mismatches.
SCHEMA_VERSION = 14

#: Environment knob: set to a file path to stream JSONL events there.
#: Unset means the null tracer — the hot loop pays one attribute check.
TRACE_ENV = "STpu_TRACE"

#: Producers that emit wave events (``engine`` field values). Spans and
#: counters may additionally come from the meta-producers below.
#: ``elastic`` is the multi-worker coordinator (one wave event per
#: coordinated round, plus the membership lifecycle events);
#: ``elastic_worker`` is one elastic worker's relayed stream (schema
#: v5 — per-worker wave events, merged into the coordinator's file by
#: ``obs/collect.py``).
#: ``flight`` is the dump-time stamp on ring-buffer events whose
#: producer ran untraced (``obs/flight.py``) — postmortem files are
#: full citizens of the schema.
#: ``mux`` is the cross-job wave multiplexer (service/mux.py) — one
#: shared engine whose dispatches batch several jobs' frontiers.
ENGINE_IDS = ("classic", "fused", "sharded", "sharded_fused",
              "host_bfs", "host_dfs", "elastic", "elastic_worker",
              "flight", "mux")

#: Non-engine producers sharing the stream (spans/counters/resilience
#: events only). ``supervisor`` emits recover/abort, ``faults`` is the
#: injection registry's fallback producer for sites without an engine
#: tracer (the checkpoint writer, the bench device child).
#: ``service`` is the multi-tenant job service (stateright_tpu.service)
#: — it emits the v7 job lifecycle family into each job's trace.
META_PRODUCERS = ("profiling", "bench", "explorer", "supervisor",
                  "faults", "service")

_NULL = type(None)
_INT = (int,)            # bool is excluded explicitly in _typecheck
_NUM = (int, float)
_STR = (str,)
_BOOL = (bool,)

#: The per-dispatch wave event: field -> allowed types. EVERY engine
#: emits EVERY key. Count fields are per-dispatch deltas except
#: ``states``/``unique`` (cumulative, so a truncated trace still ends
#: on the right totals).
WAVE_FIELDS: Dict[str, tuple] = {
    "type": _STR,                  # == "wave"
    "schema_version": _INT,
    "engine": _STR,                # one of ENGINE_IDS
    "run": _STR,                   # tracer id: one checker run
    "wave": _INT,                  # dispatch index within the run
    "t": _NUM,                     # monotonic seconds at processing
    "states": _INT,                # cumulative generated states
    "unique": _INT,                # cumulative unique states
    "bucket": _INT,                # dispatch batch width B
    "waves": _INT,                 # BFS levels in this dispatch (fused >1)
    "inflight": _INT,              # pipeline depth at launch
    "compiled": _BOOL,             # interval carried a lazy XLA compile
    "successors": _INT,            # valid successors generated (delta)
    "candidates": _INT,            # distinct candidates probed (delta)
    "novel": _INT,                 # new unique states appended (delta)
    "out_rows": _INT + (_NULL,),   # successor-ladder rung K (null: n/a)
    "capacity": _INT + (_NULL,),   # visited-table capacity (null: host)
    "load_factor": _NUM + (_NULL,),  # occupancy/capacity after dispatch
    "overflow": _BOOL,             # dispatch paid an overflow regather
    # v2: packed-arena bandwidth gauges (ISSUE 4). bytes_per_state is
    # the STORED row width in bytes (packed when the model declares
    # lane_bits); arena/table bytes are device-resident footprints
    # (null where an engine has no such structure — host engines, or
    # the per-wave engines' host-side frontier).
    "bytes_per_state": _INT + (_NULL,),
    "arena_bytes": _INT + (_NULL,),
    "table_bytes": _INT + (_NULL,),
    # v5: distributed-attribution keys. ``null`` outside the elastic
    # runtime (the tracer stamps the defaults so no engine needs a
    # per-engine field set). ``seq`` is the worker's per-process
    # emission counter — it never resets across the migration tracer
    # rotation, so the collector's merge order and the lint's
    # per-worker monotonicity survive run-id rotation.
    "worker": _STR + (_NULL,),
    "seq": _INT + (_NULL,),
    "epoch": _INT + (_NULL,),
    "round": _INT + (_NULL,),
    # v6: tiered-state-store occupancy gauges (rows/bytes resident per
    # tier after the dispatch). ``null`` when the store is disarmed —
    # the tracer stamps the defaults, so no engine needs a per-engine
    # field set.
    "tier_device_rows": _INT + (_NULL,),
    "tier_device_bytes": _INT + (_NULL,),
    "tier_host_rows": _INT + (_NULL,),
    "tier_host_bytes": _INT + (_NULL,),
    "tier_disk_rows": _INT + (_NULL,),
    "tier_disk_bytes": _INT + (_NULL,),
    # v8: single-kernel-wave attribution. ``kernel_path`` names the
    # successor-path implementation the dispatch executed; ``rows`` is
    # the valid frontier rows it consumed (occupancy numerator). Both
    # ``null`` on producers without a device wave.
    "kernel_path": _STR + (_NULL,),
    "rows": _INT + (_NULL,),
    # v9: cross-job multiplexing attribution. ``job_id`` names the
    # service job a per-job wave line belongs to (``null`` on solo
    # waves and on the mux total line); ``jobs_in_wave`` is the tenant
    # count of the shared dispatch (``null`` outside the multiplexer).
    "job_id": _STR + (_NULL,),
    "jobs_in_wave": _INT + (_NULL,),
    # v10: asynchronous host I/O. Seconds the wave loop spent blocked
    # on host I/O since the previous wave event (safe-point joins on
    # the background writer + synchronous write time). ``null`` where
    # not tracked (meta-producers, relayed historical streams).
    "io_stall_s": _NUM + (_NULL,),
    # v12: which expand-stage implementation the dispatch's wave
    # program embeds: "matmul" (the compiled transition-table form,
    # ISSUE 15) or "step" (the vmapped DeviceModel.step). ``null`` on
    # producers without a device wave.
    "expand_impl": _STR + (_NULL,),
    # v13: continuous-profiler cost attribution (obs/prof.py). The
    # executed program's static XLA cost model (``null`` when the
    # profiler is disarmed, the producer has no compiled program, or
    # the program never AOT-compiled), and — on sampled dispatches
    # only — the measured-vs-own-baseline ``cost_ratio`` (finite by
    # construction; ``null`` on unsampled dispatches).
    "cost_flops": _NUM + (_NULL,),
    "cost_bytes": _NUM + (_NULL,),
    "cost_ratio": _NUM + (_NULL,),
}

#: v5 attribution keys (absent from v2-v4 wave events).
_WAVE_V5_KEYS = ("worker", "seq", "epoch", "round")

#: v6 tier gauges (absent from v1-v5 wave events).
_WAVE_V6_KEYS = ("tier_device_rows", "tier_device_bytes",
                 "tier_host_rows", "tier_host_bytes",
                 "tier_disk_rows", "tier_disk_bytes")

#: v8 single-kernel-wave keys (absent from v1-v7 wave events).
_WAVE_V8_KEYS = ("kernel_path", "rows")

#: v9 multiplexing keys (absent from v1-v8 wave events).
_WAVE_V9_KEYS = ("job_id", "jobs_in_wave")

#: v10 async-I/O keys (absent from v1-v9 wave events).
_WAVE_V10_KEYS = ("io_stall_s",)

#: v12 expand-stage attribution (absent from v1-v11 wave events).
_WAVE_V12_KEYS = ("expand_impl",)

#: v13 cost-attribution keys (absent from v1-v12 wave events).
_WAVE_V13_KEYS = ("cost_flops", "cost_bytes", "cost_ratio")

#: The v1 wave field set (no bandwidth gauges) — v1 captures validate
#: against this exactly.
WAVE_FIELDS_V1: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in ("bytes_per_state", "arena_bytes", "table_bytes")
    + _WAVE_V5_KEYS + _WAVE_V6_KEYS + _WAVE_V8_KEYS + _WAVE_V9_KEYS
    + _WAVE_V10_KEYS + _WAVE_V12_KEYS + _WAVE_V13_KEYS}

#: The v2-v4 wave field set (bandwidth gauges, no attribution keys).
WAVE_FIELDS_V2: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in _WAVE_V5_KEYS + _WAVE_V6_KEYS + _WAVE_V8_KEYS
    + _WAVE_V9_KEYS + _WAVE_V10_KEYS + _WAVE_V12_KEYS
    + _WAVE_V13_KEYS}

#: The v5 wave field set (attribution keys, no tier gauges).
WAVE_FIELDS_V5: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in _WAVE_V6_KEYS + _WAVE_V8_KEYS + _WAVE_V9_KEYS
    + _WAVE_V10_KEYS + _WAVE_V12_KEYS + _WAVE_V13_KEYS}

#: The v6-v7 wave field set (tier gauges, no kernel-path keys).
WAVE_FIELDS_V6: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in _WAVE_V8_KEYS + _WAVE_V9_KEYS + _WAVE_V10_KEYS
    + _WAVE_V12_KEYS + _WAVE_V13_KEYS}

#: The v8 wave field set (kernel-path keys, no mux attribution).
WAVE_FIELDS_V8: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in _WAVE_V9_KEYS + _WAVE_V10_KEYS + _WAVE_V12_KEYS
    + _WAVE_V13_KEYS}

#: The v9 wave field set (mux attribution, no async-I/O gauge).
WAVE_FIELDS_V9: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in _WAVE_V10_KEYS + _WAVE_V12_KEYS + _WAVE_V13_KEYS}

#: The v10-v11 wave field set (async-I/O gauge, no expand_impl).
WAVE_FIELDS_V11: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items()
    if k not in _WAVE_V12_KEYS + _WAVE_V13_KEYS}

#: The v12 wave field set (expand_impl, no cost attribution).
WAVE_FIELDS_V12: Dict[str, tuple] = {
    k: v for k, v in WAVE_FIELDS.items() if k not in _WAVE_V13_KEYS}

_WAVE_FIELDS_BY_VERSION = {1: WAVE_FIELDS_V1, 2: WAVE_FIELDS_V2,
                           3: WAVE_FIELDS_V2, 4: WAVE_FIELDS_V2,
                           5: WAVE_FIELDS_V5, 6: WAVE_FIELDS_V6,
                           7: WAVE_FIELDS_V6, 8: WAVE_FIELDS_V8,
                           9: WAVE_FIELDS_V9, 10: WAVE_FIELDS_V11,
                           # v11 added event types only; its wave
                           # field set matches v10.
                           11: WAVE_FIELDS_V11, 12: WAVE_FIELDS_V12,
                           # v14 added event types only; its wave
                           # field set matches v13.
                           13: WAVE_FIELDS, 14: WAVE_FIELDS}

#: Required fields per trace event type (beyond the stamped
#: schema_version/engine/run/t, which every event carries).
EVENT_TYPES: Dict[str, Dict[str, tuple]] = {
    "run_start": {"unix_t": _NUM, "meta": (dict,)},
    "wave": {},  # checked field-exactly against WAVE_FIELDS instead
    "span": {"name": _STR, "dur": _NUM, "depth": _INT},
    "counter": {"name": _STR, "value": _NUM, "inc": _NUM},
    "gauge": {"name": _STR, "value": _NUM},
    "grow": {"kind": _STR, "old": _INT, "new": _INT},
    "overflow_redispatch": {"bucket": _INT, "out_rows": _INT,
                            "novel": _INT},
    "run_end": {"dur": _NUM, "counters": (dict,)},
    # v3: the resilience family. trace_lint additionally asserts every
    # fault is eventually followed by a recover or a terminal abort.
    "fault": {"point": _STR, "hit": _INT, "mode": _STR},
    "recover": {"attempt": _INT, "backoff_s": _NUM,
                "resumed_from": _STR + (_NULL,)},
    "degrade": {"kind": _STR, "old": _INT, "new": _INT},
    "abort": {"reason": _STR, "attempts": _INT},
    # v4: the membership/elasticity family. trace_lint additionally
    # asserts every worker_lost is eventually followed by a
    # migrate_done or a terminal abort (the membership invariant), and
    # counts retry like recover for the fault pairing.
    "worker_lost": {"worker": _STR, "epoch": _INT},
    "worker_join": {"worker": _STR, "epoch": _INT},
    "migrate_done": {"partitions": _INT, "to": _STR, "epoch": _INT},
    "rebalance": {"partitions": _INT, "to": _STR, "epoch": _INT},
    "retry": {"attempt": _INT, "backoff_s": _NUM, "jitter_s": _NUM,
              "resumed_from": _STR + (_NULL,)},
    # v5: the distributed-observability family. ``straggler`` is the
    # coordinator's per-round attribution record — ``workers`` maps
    # each worker to its segment timings ({compute_s, exchange_s,
    # wait_s, states_s, load_share}); ``wait_share`` is the fraction
    # of worker-time the round spent idle at the barrier.
    # ``postmortem`` heads a flight-recorder dump file (obs/flight.py)
    # and is followed by the ring's recorded events verbatim.
    "straggler": {"round": _INT, "epoch": _INT,
                  "slowest": _STR + (_NULL,), "wait_share": _NUM,
                  "workers": (dict,)},
    "postmortem": {"reason": _STR, "name": _STR, "events": _INT},
    # v6: the tiered-state-store family. ``spill`` records rows moving
    # DOWN a tier (``tier`` is the destination: "host" or "disk";
    # ``kind`` is what moved: "visited" / "frontier" / "arena_span"),
    # ``page_in`` a paged-out frontier block returning ahead of
    # dispatch, and ``pressure`` a tier crossing or resetting against
    # its byte budget (trace_lint's monotonicity window marker).
    "spill": {"tier": _STR, "kind": _STR, "rows": _INT, "bytes": _INT},
    "page_in": {"tier": _STR, "kind": _STR, "rows": _INT,
                "bytes": _INT},
    "pressure": {"tier": _STR, "used": _INT, "budget": _INT},
    # v7: the job-service family. ``job`` is the service-assigned job
    # id — the lint's pairing key (every submit eventually paired with
    # a done or abort for the SAME id). ``job_done`` carries the final
    # cumulative counters so a per-job summary never needs to fold the
    # wave stream; ``job_abort``'s reason distinguishes a preemption
    # (checkpointed, resumable) from a terminal failure.
    "job_submit": {"job": _STR, "model": _STR, "job_engine": _STR},
    "job_done": {"job": _STR, "states": _INT, "unique": _INT},
    "job_abort": {"job": _STR, "reason": _STR},
    # v10: the async-I/O checkpoint lifecycle. ``gen`` is the writer's
    # per-run generation counter (monotone; rotation keeps gen-1 as
    # ``.prev``); ``async`` records whether the write ran on the
    # background writer thread or inline. ``ckpt_done`` is emitted by
    # whichever thread finished the write — trace_lint pairs begin/done
    # oldest-first per run and lets a ``fault``/``abort`` explain a
    # begin whose write died mid-flight.
    "ckpt_begin": {"gen": _INT, "path": _STR, "async": _BOOL},
    "ckpt_done": {"gen": _INT, "path": _STR, "write_s": _NUM},
    # v11: the service-observability family. ``hist_snapshot`` is one
    # producer's cumulative latency histograms at a bounded cadence
    # (``hists``: series key -> {"buckets", "sum", "count"}; ``snap``:
    # the producer's emission ordinal — trace_lint asserts per-series
    # monotonicity and sum/count consistency). ``slo_breach`` is the
    # edge-triggered healthy->breaching transition of one rolling
    # error-budget objective. ``anomaly`` is one slow-wave verdict
    # with its attributed cause (compile / io_stall / straggler /
    # spill / unknown).
    "hist_snapshot": {"hists": (dict,), "snap": _INT},
    "slo_breach": {"objective": _STR, "target": _NUM, "burn": _NUM,
                   "window_s": _NUM, "good": _INT, "bad": _INT},
    # v13: the ``anomaly`` cause vocabulary additionally includes
    # ``cost_model`` (obs/anomaly.py — a program whose measured time
    # drifts from its own cost-normalized history).
    "anomaly": {"cause": _STR, "key": _STR, "dur_s": _NUM,
                "baseline_s": _NUM, "dev_s": _NUM},
    # v13: one sampled dispatch's roofline gauges (obs/prof.py).
    # ``key`` is the canonical program key the static cost record is
    # filed under; ``snap`` is the producer's sample ordinal (strictly
    # increasing per run — the lint invariant); ``measured_s`` the
    # rest-point-timed dispatch seconds; ``cost_ratio`` measured
    # seconds over the program's own first sampled baseline (finite by
    # construction). The flops/bytes gauges are ``null`` for programs
    # with no AOT cost analysis.
    "profile_snapshot": {"key": _STR, "kernel_path": _STR + (_NULL,),
                         "expand_impl": _STR + (_NULL,), "snap": _INT,
                         "measured_s": _NUM, "cost_ratio": _NUM,
                         "flops": _NUM + (_NULL,),
                         "bytes": _NUM + (_NULL,),
                         "peak_bytes": _INT + (_NULL,),
                         "flops_per_s": _NUM + (_NULL,),
                         "bytes_per_s": _NUM + (_NULL,),
                         "intensity": _NUM + (_NULL,)},
    # v14: the overload-control family (service/control.py). ``admit``
    # is one submission let through while the admission gate was
    # engaged; ``shed`` one rejected at the door — ``reason`` is
    # mandatory and machine-readable (slo_burn / queue_full /
    # retry_budget / brownout) and ``retry_after_s`` is what the 429
    # told the client, derived from the observed drain rate. ``park``
    # / ``resume`` bracket a controller preemption: the lint pairs
    # them by exact job id (a park not eventually resumed or
    # terminally aborted lost work). ``controller`` is one
    # brownout-ladder transition — edge-triggered per run (the rung
    # must change), with requested/kept honesty.
    "admit": {"job": _STR, "tenant": _STR, "priority": _INT,
              "queue_depth": _INT},
    "shed": {"tenant": _STR, "priority": _INT, "reason": _STR,
             "retry_after_s": _NUM},
    "park": {"job": _STR, "reason": _STR},
    "resume": {"job": _STR, "resumed_as": _STR},
    "controller": {"rung": _INT, "action": _STR, "requested": _INT,
                   "kept": _INT},
}

_STAMPED = {"type": _STR, "schema_version": _INT, "engine": _STR,
            "run": _STR, "t": _NUM}

#: Required fields of a device_session stdout event (the rest of the
#: payload is event-specific and unconstrained).
SESSION_FIELDS = {"event": _STR, "schema_version": _INT, "t": _NUM,
                  "unix_t": _NUM}


def _typecheck(value, types) -> bool:
    # bool subclasses int: a field typed int/float must not accept True.
    if isinstance(value, bool):
        return bool in types
    return isinstance(value, tuple(t for t in types if t is not bool))


def _check_fields(obj: dict, fields: Dict[str, tuple],
                  where: str) -> List[str]:
    errors = []
    for name, types in fields.items():
        if name not in obj:
            errors.append(f"{where}: missing field {name!r}")
        elif not _typecheck(obj[name], types):
            errors.append(
                f"{where}: field {name!r} has type "
                f"{type(obj[name]).__name__}, expected "
                f"{'/'.join(t.__name__ for t in types)}")
    return errors


def validate_event(obj) -> List[str]:
    """Validates one decoded event (trace or session family); returns a
    list of error strings (empty = valid)."""
    if not isinstance(obj, dict):
        return ["event is not a JSON object"]
    if "event" in obj and "type" not in obj:
        where = f"session event {obj.get('event')!r}"
        errors = _check_fields(obj, SESSION_FIELDS, where)
        if (isinstance(obj.get("schema_version"), int)
                and obj["schema_version"] > SCHEMA_VERSION):
            errors.append(f"{where}: schema_version "
                          f"{obj['schema_version']} is newer than this "
                          f"validator ({SCHEMA_VERSION})")
        return errors
    etype = obj.get("type")
    where = f"trace event {etype!r}"
    if etype not in EVENT_TYPES:
        return [f"{where}: unknown type (expected one of "
                f"{sorted(EVENT_TYPES)})"]
    errors = _check_fields(obj, _STAMPED, where)
    ver = obj.get("schema_version")
    if isinstance(ver, int) and ver > SCHEMA_VERSION:
        # A capture from a NEWER build: one clear message, no cascade
        # of field-set mismatches the reader cannot act on.
        errors.append(
            f"{where}: schema_version {ver} is newer than this "
            f"validator ({SCHEMA_VERSION}); upgrade the tools to lint "
            "this capture")
        return errors
    if etype == "wave":
        # Older captures validate against THEIR version's exact field
        # set (v1 predates the bandwidth gauges).
        fields = _WAVE_FIELDS_BY_VERSION.get(
            ver if isinstance(ver, int) else SCHEMA_VERSION,
            WAVE_FIELDS)
        errors += _check_fields(obj, fields, where)
        extras = set(obj) - set(fields)
        if extras:
            # Exact field set: one schema for every engine, no
            # per-engine riders — additions go through a version bump.
            errors.append(f"{where}: unexpected fields "
                          f"{sorted(extras)}")
        if ("engine" in obj and obj.get("engine") not in ENGINE_IDS):
            errors.append(f"{where}: engine {obj.get('engine')!r} not in "
                          f"{ENGINE_IDS}")
    else:
        errors += _check_fields(obj, EVENT_TYPES[etype], where)
    return errors


def validate_line(line: str) -> List[str]:
    """Validates one raw JSONL line (blank lines are skipped)."""
    import json

    line = line.strip()
    if not line:
        return []
    try:
        obj = json.loads(line)
    except ValueError as e:
        return [f"invalid JSON: {e}"]
    return validate_event(obj)

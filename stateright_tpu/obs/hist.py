"""Deterministic latency histograms + the service-observability facade.

Every latency figure the repo publishes so far — soak p50/p99, io-stall
share, straggler wait — is computed *offline*: bench folds its own
samples, ``tools/trace_summary.py`` folds a capture, and the live
``GET /.metrics`` surface exports only counters and gauges
(``stpu_wave_seconds`` is a single gauge). This module is the online
half: fixed-bucket, mergeable latency histograms a live operator can
read mid-run, plus the :class:`WaveObs` facade that bundles them with
the SLO tracker (``obs/slo.py``) and the slow-wave anomaly detector
(``obs/anomaly.py``) behind the established disarmed-null zero-cost
pattern.

Design constraints, in order:

1. **The disarmed path is free.** ``wave_obs_from_env`` returns the
   shared :data:`NULL_OBS` singleton when none of ``STpu_HIST`` /
   ``STpu_SLO`` / ``STpu_ANOMALY`` is set; every producer hot loop
   guards with ``if self._wave_obs.enabled:`` exactly as it guards the
   tracer with ``.enabled`` and the flight recorder with ``.armed``
   (the disarmed-cost test poisons the null methods).
2. **Deterministic and mergeable.** Bucket bounds are a fixed
   power-of-two ladder (:data:`BUCKET_BOUNDS` — no adaptive resizing,
   no sampling), so two histograms of the same series merge by
   element-wise addition and the same event sequence always produces
   the same counts; snapshots diff exactly across rounds.
3. **One observation per value the producer already has.** Wave
   dispatch latency is the gap between consecutive wave events of one
   producer — the exact semantic ``tools/trace_export.py`` gives a
   wave slice, so the online histogram and the offline export agree by
   construction. Job queue/run/total latencies come from the service's
   existing ``submitted_t``/``started_t``/``finished_t`` stamps;
   elastic compute-vs-wait from the straggler attribution the
   collector already computes.

Snapshots: when armed AND tracing is live, the facade emits a
``hist_snapshot`` event (schema v11) at a bounded cadence
(``STpu_HIST_SNAP_S`` seconds, default 2) — cumulative since run
start, monotone by construction, so ``tools/trace_lint.py`` can check
count monotonicity and per-series sum/count consistency, and
``tools/trace_summary.py`` can read p50/p99 without refolding raw
waves. Elastic workers emit theirs through the relay tracer, so they
merge causally like every other relayed event. The flight recorder's
dump hook (``set_hist_source``) appends the final snapshot to a
postmortem, so a crash report carries the latency distribution at
time of death.

Dependency-free beyond the sibling obs modules (no jax, no numpy):
elastic worker processes and the tools import this without a backend.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional

from .schema import SCHEMA_VERSION

__all__ = [
    "HIST_ENV", "SNAP_ENV", "BUCKET_BOUNDS", "Histogram", "HistogramSet",
    "WaveObs", "NullWaveObs", "NULL_OBS", "wave_obs_from_env",
    "series_key", "parse_series_key", "bucket_quantile",
    "prometheus_hist_lines",
]

#: Environment knob: ``STpu_HIST=1`` arms the latency histograms.
#: Unset/``0`` contributes nothing to ``wave_obs_from_env``'s decision.
HIST_ENV = "STpu_HIST"

#: Environment knob: ``hist_snapshot`` emission cadence in seconds
#: (default 2.0). Snapshots only ever ride an enabled tracer — the
#: cadence bounds stream growth, not hot-loop cost.
SNAP_ENV = "STpu_HIST_SNAP_S"

_SNAP_DEFAULT_S = 2.0

#: Fixed log-bucket upper bounds (seconds): the power-of-two ladder
#: 2^-20 (~1 us) .. 2^6 (64 s), 27 finite buckets + implicit +Inf.
#: Fixed so histograms are deterministic and merge by element-wise
#: addition; wide enough that a sub-microsecond host wave and a
#: minute-long cold-compile dispatch both land in a real bucket.
BUCKET_BOUNDS: tuple = tuple(2.0 ** e for e in range(-20, 7))

#: Prometheus ``le`` label values for the finite bounds (exact, since
#: powers of two round-trip through float formatting losslessly).
_LE_LABELS: tuple = tuple(format(b, ".12g") for b in BUCKET_BOUNDS)


class Histogram:
    """One series: per-bucket counts (NOT cumulative — the snapshot
    invariant ``sum(buckets) == count`` stays a plain sum), plus the
    running sum and count. Not thread-safe on its own; the owning
    :class:`HistogramSet` serializes access."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        self.counts: List[int] = [0] * (len(BUCKET_BOUNDS) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect_left(BUCKET_BOUNDS, value)] += 1
        self.sum += value
        self.count += 1

    def merge(self, other: "Histogram") -> None:
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count

    def quantile(self, q: float) -> Optional[float]:
        return bucket_quantile(self.counts, self.count, q)

    def snapshot(self) -> dict:
        return {"buckets": list(self.counts),
                "sum": round(self.sum, 9), "count": self.count}


def bucket_quantile(buckets: List[int], count: int,
                    q: float) -> Optional[float]:
    """The bucket-upper-bound quantile estimate for a (non-cumulative)
    bucket list over :data:`BUCKET_BOUNDS` — what trace_summary's
    p50/p99 columns print. None when empty; the +Inf bucket reports
    the last finite bound (the estimate saturates, it never invents)."""
    if count <= 0 or not buckets:
        return None
    rank = q * count
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= rank and c:
            return BUCKET_BOUNDS[min(i, len(BUCKET_BOUNDS) - 1)]
    return BUCKET_BOUNDS[-1]


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Prometheus-style series identity: ``name{k="v",...}`` with
    sorted label keys — one deterministic string both the snapshot
    event and the exporters key on."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_series_key(key: str):
    """``(name, labels)`` back out of :func:`series_key`'s format."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v.strip('"')
    return name, labels


class HistogramSet:
    """A thread-safe registry of named, labeled histogram series."""

    def __init__(self):
        self._series: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def observe(self, name: str, value: float, **labels) -> None:
        key = series_key(name, labels)
        with self._lock:
            hist = self._series.get(key)
            if hist is None:
                hist = self._series[key] = Histogram()
            hist.observe(float(value))

    def snapshot(self) -> Dict[str, dict]:
        """``{series_key: {"buckets", "sum", "count"}}`` — the
        ``hist_snapshot`` payload. Sorted keys: deterministic JSON."""
        with self._lock:
            return {k: self._series[k].snapshot()
                    for k in sorted(self._series)}

    def quantile(self, name: str, q: float, **labels) -> Optional[float]:
        with self._lock:
            hist = self._series.get(series_key(name, labels))
            return hist.quantile(q) if hist is not None else None


def prometheus_hist_lines(snapshot: Dict[str, dict],
                          prefix: str = "stpu_") -> List[str]:
    """Prometheus exposition lines (``_bucket``/``_sum``/``_count``,
    cumulative ``le`` buckets) for one snapshot payload — shared by
    ``tools/trace_export.py`` and the live ``GET /.metrics``."""
    lines: List[str] = []
    typed = set()
    for key in sorted(snapshot):
        name, labels = parse_series_key(key)
        data = snapshot[key]
        buckets = data.get("buckets") or []
        family = f"{prefix}{name}"
        if family not in typed:
            typed.add(family)
            lines.append(f"# TYPE {family} histogram")
        base = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        sep = "," if base else ""
        cum = 0
        for i, le in enumerate(_LE_LABELS):
            cum += buckets[i] if i < len(buckets) else 0
            lines.append(f'{family}_bucket{{{base}{sep}le="{le}"}} {cum}')
        cum += buckets[len(_LE_LABELS)] if len(buckets) > len(_LE_LABELS) \
            else 0
        lines.append(f'{family}_bucket{{{base}{sep}le="+Inf"}} {cum}')
        suffix = f"{{{base}}}" if base else ""
        lines.append(f"{family}_sum{suffix} {data.get('sum', 0)}")
        lines.append(f"{family}_count{suffix} {data.get('count', 0)}")
    return lines


class NullWaveObs:
    """The disarmed facade: every method a no-op, ``enabled`` False.
    Hot paths must check ``enabled`` BEFORE calling ``wave`` — the
    disarmed-cost test poisons these methods, so a stray call (= a
    stray per-wave cost with the subsystem off) fails the suite."""

    __slots__ = ()
    enabled = False
    hist = None
    slo = None
    anomaly = None

    def wave(self, entry, tracer=None, flight=None, wait_s=None) -> None:
        pass

    def job(self, queue_s, run_s, total_s, ok=True, engine="service",
            tracer=None, flight=None) -> None:
        pass

    def elastic_report(self, worker, compute_s, wait_s) -> None:
        pass

    def maybe_snapshot(self, tracer, now=None) -> None:
        pass

    def final_snapshot_event(self) -> Optional[dict]:
        return None

    def close(self, tracer=None) -> None:
        pass

    def slo_status(self) -> Optional[dict]:
        return None

    def anomalies(self) -> list:
        return []

    @property
    def healthy(self) -> bool:
        return True


#: The shared disarmed facade (``wave_obs_from_env`` returns this very
#: object when no observability knob is set — identity-testable).
NULL_OBS = NullWaveObs()


class WaveObs:
    """Per-producer service-observability bundle: histograms + SLO
    tracker + anomaly detector, fed from the wave entries (and job
    timestamps) the producer already builds.

    Each armed component is optional — ``STpu_HIST`` / ``STpu_SLO`` /
    ``STpu_ANOMALY`` arm them independently; the facade exists iff at
    least one is set. One instance per producer (engine, service, mux
    group, elastic worker/coordinator); never shared across engines,
    so the wave-gap latency is per producer by construction.
    """

    enabled = True

    def __init__(self, producer: str, hist: Optional[HistogramSet] = None,
                 slo=None, anomaly=None, snap_s: float = _SNAP_DEFAULT_S):
        self.producer = str(producer)
        self.hist = hist
        self.slo = slo
        self.anomaly = anomaly
        self.snap_s = max(0.05, float(snap_s))
        self._lock = threading.Lock()
        self._last_t: Optional[float] = None
        self._last_snap = time.monotonic()
        self._snap = 0

    # -- Observation points ------------------------------------------------

    def wave(self, entry: dict, tracer=None, flight=None,
             wait_s: Optional[float] = None) -> None:
        """One wave event's worth of observations. ``entry`` is the
        producer's dispatch-log dict (the same one the tracer and the
        flight ring get); dispatch latency is the gap to the previous
        wave of THIS producer — trace_export's slice semantic."""
        now = entry.get("t")
        if not isinstance(now, (int, float)):
            now = time.monotonic()
        with self._lock:
            prev, self._last_t = self._last_t, now
        dur = now - prev if (prev is not None and now >= prev) else None
        kp = entry.get("kernel_path") or "none"
        if self.hist is not None:
            if dur is not None:
                self.hist.observe("wave_latency_seconds", dur,
                                  engine=self.producer, kernel_path=kp)
            io = entry.get("io_stall_s")
            if isinstance(io, (int, float)) and io > 0:
                self.hist.observe("io_stall_seconds", float(io),
                                  engine=self.producer)
        if self.slo is not None:
            breach = self.slo.observe(
                "wave_success", ok=not bool(entry.get("overflow")), t=now)
            self._emit_breach(breach, tracer, flight)
        if self.anomaly is not None and dur is not None:
            evt = self.anomaly.observe(f"{self.producer}|{kp}", dur,
                                       entry, wait_s=wait_s)
            if evt is not None:
                if tracer is not None and tracer.enabled:
                    tracer.event("anomaly", **evt)
                if flight is not None and flight.armed:
                    flight.record_event("anomaly", **evt)
        self.maybe_snapshot(tracer, now=None)

    def job(self, queue_s: float, run_s: float, total_s: float,
            ok: bool = True, engine: str = "service",
            tracer=None, flight=None) -> None:
        """One finished/aborted job's worth of observations (the
        service's ``_finish`` path — cold relative to waves)."""
        if self.hist is not None:
            self.hist.observe("job_queue_seconds", queue_s, engine=engine)
            self.hist.observe("job_run_seconds", run_s, engine=engine)
            self.hist.observe("job_latency_seconds", total_s,
                              engine=engine)
        if self.slo is not None:
            self._emit_breach(
                self.slo.observe("queue_wait", value=queue_s),
                tracer, flight)
            self._emit_breach(
                self.slo.observe("job_latency",
                                 value=total_s if ok else float("inf")),
                tracer, flight)
        self.maybe_snapshot(tracer)

    def elastic_report(self, worker: str, compute_s: float,
                       wait_s: float) -> None:
        """One worker-round segment from the straggler attribution
        (``obs/collect.py``) — the compute-vs-wait distribution."""
        if self.hist is not None:
            self.hist.observe("elastic_compute_seconds", compute_s,
                              worker=str(worker))
            self.hist.observe("elastic_wait_seconds", wait_s,
                              worker=str(worker))

    # -- Snapshots ---------------------------------------------------------

    def maybe_snapshot(self, tracer, now: Optional[float] = None) -> None:
        """Emits a ``hist_snapshot`` through an enabled tracer at the
        bounded cadence. Wall-clock gated (not event-count gated), so
        a fast producer cannot flood the stream."""
        if self.hist is None or tracer is None or not tracer.enabled:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            if now - self._last_snap < self.snap_s:
                return
            self._last_snap = now
            self._snap += 1
            snap = self._snap
        hists = self.hist.snapshot()
        if hists:
            tracer.event("hist_snapshot", hists=hists, snap=snap)

    def final_snapshot_event(self) -> Optional[dict]:
        """A fully-stamped ``hist_snapshot`` for consumers with no
        tracer in hand — the flight recorder's dump hook, so a
        postmortem carries the distribution at time of death."""
        if self.hist is None:
            return None
        hists = self.hist.snapshot()
        if not hists:
            return None
        with self._lock:
            self._snap += 1
            snap = self._snap
        return {"type": "hist_snapshot", "schema_version": SCHEMA_VERSION,
                "engine": self.producer, "run": f"hist-{self.producer}",
                "t": round(time.monotonic(), 6), "hists": hists,
                "snap": snap}

    def close(self, tracer=None) -> None:
        """Final snapshot at producer teardown (cold path), so a short
        run that never crossed the cadence still lands one."""
        if self.hist is None or tracer is None or not tracer.enabled:
            return
        hists = self.hist.snapshot()
        if not hists:
            return
        with self._lock:
            self._snap += 1
            snap = self._snap
        tracer.event("hist_snapshot", hists=hists, snap=snap)

    # -- Surfaces ----------------------------------------------------------

    def _emit_breach(self, breach: Optional[dict], tracer, flight) -> None:
        if breach is None:
            return
        if tracer is not None and tracer.enabled:
            tracer.event("slo_breach", **breach)
        if flight is not None and flight.armed:
            flight.record_event("slo_breach", **breach)

    def slo_status(self) -> Optional[dict]:
        return self.slo.status() if self.slo is not None else None

    def anomalies(self) -> list:
        return self.anomaly.recent() if self.anomaly is not None else []

    @property
    def healthy(self) -> bool:
        return self.slo.healthy if self.slo is not None else True


def wave_obs_from_env(producer: str):
    """The facade factory every producer uses: the shared
    :data:`NULL_OBS` when no knob is set (no allocation, one attribute
    check per wave); an armed :class:`WaveObs` otherwise, with exactly
    the components whose knobs are set."""
    hist_on = os.environ.get(HIST_ENV, "") not in ("", "0")
    from .anomaly import detector_from_env
    from .slo import slo_from_env

    slo = slo_from_env()
    anomaly = detector_from_env()
    if not hist_on and slo is None and anomaly is None:
        return NULL_OBS
    try:
        snap_s = float(os.environ.get(SNAP_ENV, "") or _SNAP_DEFAULT_S)
    except ValueError:
        snap_s = _SNAP_DEFAULT_S
    return WaveObs(producer, hist=HistogramSet() if hist_on else None,
                   slo=slo, anomaly=anomaly, snap_s=snap_s)

"""Distributed trace collection for the elastic runtime (schema v5).

Round 8's ``RunTracer`` assumes its producer can reach the trace file;
the elastic runtime's workers frequently cannot (a process-transport
worker on another host in the deployment this models), and even when
they can, N appenders racing one file give no causal order. This
module is the distributed half of ``obs``:

- :class:`RelayTracer` — the worker-side tracer. Same emitting surface
  as ``RunTracer`` (``wave`` / ``event`` / ``counter`` / ``gauge`` /
  ``span``), but events are stamped and **buffered in a bounded
  in-memory queue** instead of written; the worker's command loop
  drains them in bounded batches piggybacked on its round replies
  (zero extra round trips — the reply was going to the coordinator
  anyway). Every event is stamped with the worker name and a
  process-lifetime ``seq`` that survives run-id rotation, which is
  what makes downstream merge order and lint invariants possible.
  An optional ``mirror`` callable tees every stamped event into the
  worker's flight-recorder ring, so postmortems see the same stream
  the coordinator does.
- :class:`TraceCollector` — the coordinator side. Receives each
  worker's batches, assigns every event an effective ``(epoch,
  round)`` (non-wave events inherit their worker's last wave position,
  so rotation markers cannot sort ahead of the waves they follow),
  and flushes one causally-ordered merge — sorted by ``(epoch, round,
  worker, seq)`` — into the coordinator's trace file via
  ``RunTracer.emit_raw``. It also owns **straggler attribution**: per
  round, the workers' self-reported segment timings (compute,
  exchange) become barrier-wait times against the slowest worker
  (clock-skew-free: only durations cross the wire, never timestamps),
  emitted as a ``straggler`` event and aggregated for
  ``scheduler_stats()["elastic_obs"]`` / bench / ``GET /.metrics``.

Dependency-free beyond ``obs`` itself (no jax, no numpy): worker
processes import this before their backend exists.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from .schema import SCHEMA_VERSION

__all__ = ["RelayTracer", "TraceCollector"]


class RelayTracer:
    """A ``RunTracer``-shaped emitter that buffers stamped events for
    relay instead of writing a file.

    ``buffering=False`` keeps the stamping/mirroring path (the flight
    recorder is always on) but queues nothing — the coordinator runs
    untraced, so shipping events nobody will write would be pure
    overhead. ``rotate()`` starts a new run id (the migration /
    reassignment story: cumulative counters rewind with a rollback and
    the lint's monotonicity is per run), while ``seq`` keeps counting
    across rotations so per-worker order is globally checkable.
    """

    enabled = True

    #: bounded-batch knobs: the buffer never grows past ``capacity``
    #: (oldest dropped, counted) and one reply carries at most
    #: ``batch`` events.
    _CAPACITY = 4096
    _BATCH = 256

    def __init__(self, worker: str, engine: str = "elastic_worker",
                 buffering: bool = True,
                 mirror: Optional[Callable[[dict], None]] = None,
                 meta: Optional[dict] = None):
        self.worker = str(worker)
        self.engine = engine
        self._buffering = bool(buffering)
        self._mirror = mirror
        self._lock = threading.Lock()
        self._local = threading.local()
        self._buf: deque = deque()
        self._seq = 0
        self._rotation = -1
        self._wave_index = 0
        self._counters: Dict[str, float] = {}
        self._dropped = 0
        self.run = ""
        self._start_run(meta)  # also sets self._t0

    # -- Run lifecycle -----------------------------------------------------

    def _start_run(self, meta: Optional[dict]) -> None:
        self._rotation += 1
        self.run = f"{self.worker}-{os.getpid():x}-{self._rotation}"
        self._wave_index = 0
        self._counters = {}
        self._t0 = time.monotonic()  # run_end durations are per run
        self._push({"type": "run_start", "unix_t": round(time.time(), 3),
                    "meta": dict(meta or {}, worker=self.worker)})

    def rotate(self, meta: Optional[dict] = None) -> None:
        """Ends the current run and starts a fresh one (same worker,
        same seq stream). Called at every partition reassignment —
        rollback migration, join handoff, donor drop — because each
        rewinds or re-bases the cumulative counters the lint checks
        per run."""
        self._end_run()
        self._start_run(meta)

    def _end_run(self) -> None:
        with self._lock:
            counters = dict(self._counters)
        self._push({"type": "run_end",
                    "dur": round(time.monotonic() - self._t0, 6),
                    "counters": counters})

    def close(self) -> None:
        self._end_run()

    # -- Plumbing ----------------------------------------------------------

    def _push(self, fields: dict, number_wave: bool = False) -> None:
        evt = {"schema_version": SCHEMA_VERSION, "engine": self.engine,
               "run": self.run, "worker": self.worker}
        evt.update(fields)
        evt.setdefault("t", round(time.monotonic(), 6))
        with self._lock:
            if number_wave:
                # Wave index and seq are stamped under the SAME lock
                # hold: two emitting threads (the wave loop + the
                # async-I/O writer) must never take wave indices in one
                # order and seqs in the other — the lint's per-worker
                # seq monotonicity and wave contiguity both key off
                # this pairing.
                evt["wave"] = self._wave_index
                self._wave_index += 1
            self._seq += 1
            evt["seq"] = self._seq
            if self._buffering:
                if len(self._buf) >= self._CAPACITY:
                    self._buf.popleft()
                    self._dropped += 1
                self._buf.append(evt)
        if self._mirror is not None:
            self._mirror(evt)

    def drain(self, limit: Optional[int] = None) -> Tuple[List[dict], int]:
        """Up to ``limit`` buffered events (FIFO — per-worker seq order
        is the merge contract) plus the count of events dropped to the
        capacity bound since the last drain."""
        limit = self._BATCH if limit is None else int(limit)
        out: List[dict] = []
        with self._lock:
            while self._buf and len(out) < limit:
                out.append(self._buf.popleft())
            dropped, self._dropped = self._dropped, 0
        return out, dropped

    # -- Emitters (RunTracer surface) --------------------------------------

    def wave(self, fields: dict) -> None:
        evt = dict(fields, type="wave")
        for key in ("epoch", "round",
                    # v6 tier gauges: null outside a tiered-store run.
                    "tier_device_rows", "tier_device_bytes",
                    "tier_host_rows", "tier_host_bytes",
                    "tier_disk_rows", "tier_disk_bytes",
                    "kernel_path", "rows",
                    # v9 mux attribution: null outside a mux group.
                    "job_id", "jobs_in_wave",
                    # v10 async-I/O stall gauge: null where not tracked.
                    "io_stall_s",
                    # v12 expand-stage attribution: null on producers
                    # without a device wave.
                    "expand_impl",
                    # v13 cost attribution: null when the profiler is
                    # disarmed / the program has no cost model /
                    # the dispatch was not sampled.
                    "cost_flops", "cost_bytes", "cost_ratio"):
            evt.setdefault(key, None)
        self._push(evt, number_wave=True)

    def event(self, etype: str, **fields) -> None:
        fields.pop("_flush", None)
        self._push(dict(fields, type=etype))

    def counter(self, name: str, inc=1) -> None:
        with self._lock:
            total = self._counters.get(name, 0) + inc
            self._counters[name] = total
        self._push({"type": "counter", "name": name, "value": total,
                    "inc": inc})

    def gauge(self, name: str, value) -> None:
        self._push({"type": "gauge", "name": name, "value": value})

    def span_event(self, name: str, start: float, dur: float,
                   depth: int = 0, **attrs) -> None:
        evt = {"type": "span", "name": name, "t": round(start, 6),
               "dur": round(dur, 6), "depth": depth}
        if attrs:
            evt["attrs"] = attrs
        self._push(evt)

    @contextmanager
    def span(self, name: str, **attrs):
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        start = time.monotonic()
        try:
            yield
        finally:
            self._local.depth = depth
            self.span_event(name, start, time.monotonic() - start,
                            depth=depth, **attrs)


class TraceCollector:
    """Merges relayed per-worker streams into the coordinator's trace
    and attributes per-round straggler cost.

    ``tracer`` is the coordinator's live ``RunTracer`` (reassignable —
    a migration rotates it); ``flight`` is the coordinator's flight
    recorder, which sees every merged event so a ``worker_lost``
    postmortem contains the casualty's own last relayed events.
    """

    def __init__(self, tracer, flight=None, obs=None):
        self.tracer = tracer
        self.flight = flight
        #: optional ``WaveObs`` facade (obs/hist.py): the straggler
        #: fold feeds each worker-round segment's compute/wait seconds
        #: into the elastic latency histograms — the attribution is
        #: computed here anyway, so armed cost is two observes per
        #: worker-round and disarmed cost is one attribute check.
        self.obs = obs
        self._lock = threading.Lock()
        #: (epoch, round, worker, seq, evt) awaiting the next flush.
        self._pending: List[tuple] = []
        #: per-worker carried position: non-wave events (rotation
        #: markers, spans) inherit their worker's last wave (epoch,
        #: round) so a global sort cannot reorder them ahead of it.
        self._last_pos: Dict[str, Tuple[int, int]] = {}
        self._last_seq: Dict[str, int] = {}
        self.merged = 0
        self.dropped = 0
        # Straggler aggregates (fed by ``straggler``).
        self._rounds_timed = 0
        self._max_wait_share = 0.0
        self._slowest_counts: Dict[str, int] = {}
        self._worker_totals: Dict[str, dict] = {}
        self._last_round: Optional[dict] = None

    # -- Merge -------------------------------------------------------------

    def add_batch(self, worker: str, events: List[dict],
                  dropped: int = 0) -> None:
        """Buffers one worker's relayed batch (already in that
        worker's seq order — the relay drains FIFO)."""
        if not events and not dropped:
            return
        with self._lock:
            self.dropped += int(dropped)
            pos = self._last_pos.get(worker, (-1, -1))
            for evt in events:
                if not isinstance(evt, dict):
                    continue
                epoch, rnd = evt.get("epoch"), evt.get("round")
                if isinstance(epoch, int) and isinstance(rnd, int):
                    pos = (epoch, rnd)
                seq = evt.get("seq")
                seq = seq if isinstance(seq, int) \
                    else self._last_seq.get(worker, 0) + 1
                self._last_seq[worker] = seq
                self._pending.append((pos[0], pos[1], str(worker), seq,
                                      evt))
            self._last_pos[worker] = pos

    def flush(self) -> int:
        """Writes every buffered event in ``(epoch, round, worker,
        seq)`` order through the current tracer (and the flight ring).
        Called at round barriers, before tracer rotation, and at run
        end; returns the number of events written."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return 0
        pending.sort(key=lambda item: item[:4])
        tracer = self.tracer
        flight = self.flight
        for _, _, _, _, evt in pending:
            if tracer is not None and tracer.enabled:
                tracer.emit_raw(evt)
            if flight is not None and flight.armed:
                flight.record(evt)
        self.merged += len(pending)
        return len(pending)

    # -- Straggler attribution ---------------------------------------------

    def straggler(self, round_: int, epoch: int,
                  reports: Dict[str, dict]) -> Optional[dict]:
        """Folds one round's worker self-reports into the straggler
        record: per-worker compute/exchange/barrier-wait seconds,
        per-shard throughput and load share, the round's slowest
        worker, and the wait-time share (fraction of total worker-time
        the barrier burned — the multi-worker killer the GPUexplore
        scalability study measures). Durations are worker-local, so no
        cross-process clock comparison happens anywhere."""
        if not reports:
            return None
        computes = {w: float(r.get("compute_s") or 0.0)
                    for w, r in reports.items()}
        max_compute = max(computes.values())
        slowest = max(sorted(computes), key=computes.get)
        total_queued = sum(int(r.get("queued") or 0)
                           for r in reports.values())
        workers: Dict[str, dict] = {}
        wait_total = 0.0
        for w, rep in sorted(reports.items()):
            wait = max(0.0, max_compute - computes[w])
            wait_total += wait
            workers[w] = {
                "compute_s": round(computes[w], 6),
                "exchange_s": round(float(rep.get("exchange_s")
                                          or 0.0), 6),
                "wait_s": round(wait, 6),
                "states_s": round(int(rep.get("successors") or 0)
                                  / computes[w], 1)
                if computes[w] > 0 else 0.0,
                "load_share": round(int(rep.get("queued") or 0)
                                    / total_queued, 4)
                if total_queued else 0.0,
            }
        wait_share = (wait_total / (len(reports) * max_compute)
                      if max_compute > 0 else 0.0)
        record = {"round": int(round_), "epoch": int(epoch),
                  "slowest": slowest,
                  "wait_share": round(wait_share, 4),
                  "workers": workers}
        with self._lock:
            self._rounds_timed += 1
            self._max_wait_share = max(self._max_wait_share,
                                       record["wait_share"])
            self._slowest_counts[slowest] = \
                self._slowest_counts.get(slowest, 0) + 1
            self._last_round = record
            for w, seg in workers.items():
                tot = self._worker_totals.setdefault(
                    w, {"waves": 0, "compute_s": 0.0, "exchange_s": 0.0,
                        "wait_s": 0.0, "successors": 0})
                tot["waves"] += 1
                tot["compute_s"] += seg["compute_s"]
                tot["exchange_s"] += seg["exchange_s"]
                tot["wait_s"] += seg["wait_s"]
                tot["successors"] += int(
                    reports[w].get("successors") or 0)
        if self.obs is not None and self.obs.enabled:
            for w, seg in workers.items():
                self.obs.elastic_report(w, seg["compute_s"],
                                        seg["wait_s"])
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.event("straggler", **record)
        if self.flight is not None and self.flight.armed:
            self.flight.record_event("straggler", **record)
        return record

    def summary(self) -> dict:
        """The aggregated view bench / ``scheduler_stats`` /
        ``GET /.metrics`` surface as ``elastic_obs``."""
        with self._lock:
            workers = {}
            for w, tot in sorted(self._worker_totals.items()):
                busy = tot["compute_s"] + tot["wait_s"]
                workers[w] = {
                    "waves": tot["waves"],
                    "compute_s": round(tot["compute_s"], 6),
                    "exchange_s": round(tot["exchange_s"], 6),
                    "wait_s": round(tot["wait_s"], 6),
                    "states_s": round(tot["successors"]
                                      / tot["compute_s"], 1)
                    if tot["compute_s"] > 0 else 0.0,
                    "wait_share": round(tot["wait_s"] / busy, 4)
                    if busy > 0 else 0.0,
                }
            return {
                "rounds_timed": self._rounds_timed,
                "max_wait_share": round(self._max_wait_share, 4),
                "slowest": dict(sorted(self._slowest_counts.items())),
                "workers": workers,
                "last_round": self._last_round,
                "merged_events": self.merged,
                "dropped_events": self.dropped,
            }

"""Online slow-wave anomaly detection with cause attribution.

A soak's p99 tells an operator *that* waves are slow; this detector
tells them *which* wave and *why*, while it happens. Per program key
(``producer|kernel_path`` — the compile-cache identity) it keeps a
robust online baseline: an EWMA of wave dispatch latency plus an EWMA
of absolute deviation (the online stand-in for MAD, scaled by the
usual 1.4826 normal-consistency constant). A wave trips the detector
when the baseline is warm (``warmup`` observations) and its latency
exceeds ``ewma + k * max(1.4826 * dev, floor)`` — the floor keeps a
near-constant baseline (device waves on an idle box jitter by
microseconds) from flagging scheduler noise.

Attribution uses only gauges already on the wave entry — no new
instrumentation on the hot path:

- ``compile`` — the entry's ``compiled`` flag is set: the interval
  carried a lazy XLA compile (the classic cold-start tail).
- ``io_stall`` — the entry's ``io_stall_s`` covers at least half the
  excess over baseline: the wave loop sat in safe-point joins or
  synchronous host writes.
- ``straggler`` — the caller passed a barrier-wait hint (the elastic
  coordinator knows its round's wait from the straggler reports) that
  covers at least half the excess.
- ``spill`` — the host/disk tier byte gauges grew since this key's
  previous wave: the store pushed rows down a tier inside the
  interval.
- ``cost_model`` (schema v13) — the wave carried a sampled
  ``cost_ratio`` (obs/prof.py: measured seconds over the program's own
  first sampled baseline) that drifted to at least ``_COST_DRIFT``
  times this key's ratio history: the same compiled program is getting
  slower relative to its own cost-normalized past — a compile/runtime
  regression, not a workload change.
- ``unknown`` — none of the above: the honest residue (GC, CPU
  contention, a co-tenant).

The baseline updates with every observation, anomalous or not — a
sustained regression stops being "anomalous" once it IS the baseline,
which is the behavior an operator wants from a *change* detector (the
SLO tracker owns absolute levels). Fully deterministic: same
observation sequence, same verdicts.

Disarmed (``STpu_ANOMALY`` unset): ``detector_from_env`` returns
``None`` and the facade never constructs one — zero cost.
``STpu_ANOMALY=1`` arms defaults; ``k=v`` overrides: ``k`` (sigma
multiplier, default 4), ``warmup`` (observations before judging,
default 8), ``alpha`` (EWMA weight, default 0.2), ``floor`` (minimum
deviation scale in seconds, default 0.001).

Dependency-free (no jax, no numpy).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["ANOMALY_ENV", "SlowWaveDetector", "detector_from_env"]

#: Environment knob: ``STpu_ANOMALY=1`` arms the detector (optionally
#: with ``k=v`` overrides — module docstring).
ANOMALY_ENV = "STpu_ANOMALY"

#: Normal-consistency constant: MAD * 1.4826 estimates sigma.
_MAD_SIGMA = 1.4826

#: ``cost_model`` attribution threshold: the sampled ``cost_ratio``
#: must reach this multiple of the key's own ratio EWMA. Generous on
#: purpose — the latency gate (``ewma + k*scale``) already fired, this
#: only decides the label.
_COST_DRIFT = 1.5


class SlowWaveDetector:
    """Per-program-key EWMA+MAD baseline over wave dispatch latency."""

    def __init__(self, k: float = 4.0, warmup: int = 8,
                 alpha: float = 0.2, floor: float = 0.001,
                 keep: int = 64):
        self.k = float(k)
        self.warmup = max(1, int(warmup))
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self.floor = max(0.0, float(floor))
        self._lock = threading.Lock()
        self._keys: Dict[str, dict] = {}
        #: recent anomalies for the ops panel / scheduler_stats — a
        #: bounded window, oldest dropped.
        self._recent: deque = deque(maxlen=max(1, int(keep)))
        self.total = 0

    def observe(self, key: str, dur: float, entry: dict,
                wait_s: Optional[float] = None) -> Optional[dict]:
        """Judges one wave latency against its key's baseline; returns
        an ``anomaly`` event payload when it trips, else None. Always
        updates the baseline (a change detector, not a level one)."""
        dur = float(dur)
        with self._lock:
            st = self._keys.get(key)
            if st is None:
                st = self._keys[key] = {
                    "ewma": dur, "dev": 0.0, "n": 0,
                    "host_bytes": None, "disk_bytes": None,
                    "cost_ratio": None}
            verdict = None
            if st["n"] >= self.warmup:
                base = st["ewma"]
                scale = max(_MAD_SIGMA * st["dev"], self.floor)
                if dur > base + self.k * scale:
                    cause = self._attribute(st, dur, base, entry, wait_s)
                    verdict = {"cause": cause, "key": key,
                               "dur_s": round(dur, 6),
                               "baseline_s": round(base, 6),
                               "dev_s": round(scale, 6)}
                    self.total += 1
                    self._recent.append(dict(
                        verdict, at=round(time.monotonic(), 3),
                        wave=entry.get("wave")))
            a = self.alpha
            st["ewma"] += a * (dur - st["ewma"])
            st["dev"] += a * (abs(dur - st["ewma"]) - st["dev"])
            st["n"] += 1
            # Track tier growth per key for the spill attribution.
            for field, slot in (("tier_host_bytes", "host_bytes"),
                                ("tier_disk_bytes", "disk_bytes")):
                val = entry.get(field)
                if isinstance(val, int):
                    st[slot] = val
            # Track the sampled cost_ratio per key (v13) for the
            # cost_model attribution: an EWMA of the ratio history so
            # a drift is judged against the key's own normal, not the
            # absolute 1.0 anchor.
            ratio = entry.get("cost_ratio")
            if isinstance(ratio, (int, float)) \
                    and not isinstance(ratio, bool) \
                    and math.isfinite(ratio):
                prev = st["cost_ratio"]
                st["cost_ratio"] = (ratio if prev is None
                                    else prev + a * (ratio - prev))
            return verdict

    def _attribute(self, st: dict, dur: float, base: float,
                   entry: dict, wait_s: Optional[float]) -> str:
        excess = max(dur - base, 1e-9)
        if entry.get("compiled"):
            return "compile"
        io = entry.get("io_stall_s")
        if isinstance(io, (int, float)) and io >= 0.5 * excess:
            return "io_stall"
        if isinstance(wait_s, (int, float)) and wait_s >= 0.5 * excess:
            return "straggler"
        for field, slot in (("tier_host_bytes", "host_bytes"),
                            ("tier_disk_bytes", "disk_bytes")):
            val = entry.get(field)
            prev = st[slot]
            if isinstance(val, int) and isinstance(prev, int) \
                    and val > prev:
                return "spill"
        # v13: the wave carried a sampled cost_ratio that drifted past
        # the key's ratio history — the program itself regressed.
        ratio = entry.get("cost_ratio")
        prev = st.get("cost_ratio")
        if isinstance(ratio, (int, float)) \
                and not isinstance(ratio, bool) \
                and math.isfinite(ratio) \
                and isinstance(prev, (int, float)) and prev > 0 \
                and ratio >= _COST_DRIFT * prev:
            return "cost_model"
        return "unknown"

    def recent(self) -> list:
        """The bounded recent-anomaly window, oldest first."""
        with self._lock:
            return list(self._recent)

    def stats(self) -> dict:
        with self._lock:
            return {"total": self.total, "keys": len(self._keys),
                    "recent": list(self._recent)}


def detector_from_env() -> Optional[SlowWaveDetector]:
    """``None`` when ``STpu_ANOMALY`` is unset/``0``; a configured
    detector otherwise."""
    raw = os.environ.get(ANOMALY_ENV, "")
    if raw in ("", "0"):
        return None
    kwargs: Dict[str, float] = {}
    for part in raw.split(","):
        if "=" not in part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key not in ("k", "warmup", "alpha", "floor"):
            continue
        try:
            kwargs[key] = int(val) if key == "warmup" else float(val)
        except ValueError:
            continue
    return SlowWaveDetector(**kwargs)

"""``RunTracer``: the run-telemetry writer behind ``STpu_TRACE``.

Design constraints, in order:

1. **The disabled path is free.** Every engine hot loop guards emission
   with ``if self._tracer.enabled:`` — with ``STpu_TRACE`` unset,
   ``tracer_from_env`` returns the shared ``NULL_TRACER`` singleton and
   the wave loop pays exactly one attribute check per dispatch: no
   event dicts, no string formatting, no allocation
   (``tests/test_obs_trace.py`` pins this with poisoned null methods).
2. **One stream, many producers.** Several tracers may append to the
   same file (host baseline + device engine inside one bench process;
   a device child appending across a process boundary). Each tracer
   stamps its events with a unique ``run`` id and writes whole lines
   under a lock, so interleaved runs separate cleanly downstream.
3. **Crash-durable enough, cheap enough.** Writes are buffered and
   flushed every ``_FLUSH_EVERY`` events or ``_FLUSH_S`` seconds
   (whichever first), plus at run boundaries — a wedged accelerator or
   an external ``timeout`` kill loses at most half a second of events,
   while the per-wave cost stays at one ``json.dumps`` + buffered
   ``write`` (~15 us amortized; an every-event ``flush`` measured ~46
   us/event on the round-8 box and was the dominant term). A daemon
   flusher thread sweeps the buffer every ``_FLUSH_S`` even when the
   producer has gone SILENT — the wedged-accelerator case is exactly
   when the buffered tail (the events leading up to the wedge) matters
   most, and a time-check that only runs on the next write would never
   fire. Total overhead on the classic 2pc headline measured < 2% —
   MEASUREMENTS.md.

Spans nest per thread (``depth`` is a thread-local counter) and record
monotonic start + duration; counters accumulate per tracer and dump
their totals in the ``run_end`` event, so a consumer can read final
tallies without folding the stream.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from .schema import SCHEMA_VERSION, TRACE_ENV

__all__ = ["RunTracer", "NullTracer", "NULL_TRACER", "tracer_from_env"]

_RUN_SEQ = itertools.count()


class NullTracer:
    """The disabled tracer: every method is a no-op, ``enabled`` is
    False. Hot paths must check ``enabled`` BEFORE building event
    payloads — the null methods exist only so cold paths (close, span
    around a growth rehash) need no guard."""

    __slots__ = ()
    enabled = False

    def wave(self, fields) -> None:
        pass

    def event(self, etype, **fields) -> None:
        pass

    def counter(self, name, inc=1) -> None:
        pass

    def gauge(self, name, value) -> None:
        pass

    def span_event(self, name, start, dur, depth=0, **attrs) -> None:
        pass

    def emit_raw(self, evt) -> None:
        pass

    @contextmanager
    def span(self, name, **attrs):
        yield

    def close(self) -> None:
        pass


#: The shared disabled tracer (``tracer_from_env`` returns this very
#: object when ``STpu_TRACE`` is unset — identity-testable).
NULL_TRACER = NullTracer()


class RunTracer:
    """Writes one JSONL event stream for one checker/tool run."""

    enabled = True

    #: flush cadence: whichever of these trips first (see the module
    #: docstring's durability/cost trade).
    _FLUSH_EVERY = 32
    _FLUSH_S = 0.5

    def __init__(self, path: str, engine: str, meta: Optional[dict] = None):
        self.path = path
        self.engine = engine
        self.run = f"{os.getpid():x}-{next(_RUN_SEQ)}"
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._local = threading.local()
        self._t0 = time.monotonic()
        self._wave_index = 0
        self._counters: dict = {}
        self._closed = False
        self._closing = False
        self._unflushed = 0
        self._last_flush = self._t0
        self._write({"type": "run_start", "t": self._t0,
                     "unix_t": round(time.time(), 3),
                     "meta": dict(meta or {})}, flush=True)
        # Background sweep: flush the buffered tail even when the
        # producer goes silent (a wedged dispatch, an imminent external
        # kill) — the trailing events are the ones a post-mortem needs.
        self._flush_stop = threading.Event()
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True)
        self._flusher.start()

    def _flush_loop(self) -> None:
        while not self._flush_stop.wait(self._FLUSH_S):
            with self._lock:
                if self._closed:
                    return
                if self._unflushed:
                    self._f.flush()
                    self._unflushed = 0
                    self._last_flush = time.monotonic()

    # -- Plumbing --------------------------------------------------------

    def _write(self, fields: dict, number_wave: bool = False,
               flush: bool = False, final: bool = False) -> None:
        evt = {"schema_version": SCHEMA_VERSION, "engine": self.engine,
               "run": self.run}
        evt.update(fields)
        with self._lock:
            # Once a closer owns ``_closing``, only its own run_end
            # (``final``) may still land — a racing emitter that lost
            # the close race must not write AFTER run_end.
            if self._closed or (self._closing and not final):
                return
            if number_wave:
                # Numbered and written under ONE lock hold, so
                # concurrent emitters (the host engines' worker
                # threads) cannot write indices out of order — the
                # lint's contiguity check depends on this.
                evt["wave"] = self._wave_index
                self._wave_index += 1
            now = time.monotonic()
            evt["t"] = round(evt.get("t", now), 6)
            self._f.write(json.dumps(evt, separators=(",", ":"),
                                     default=_jsonable) + "\n")
            self._unflushed += 1
            if (flush or self._unflushed >= self._FLUSH_EVERY
                    or now - self._last_flush >= self._FLUSH_S):
                self._f.flush()
                self._unflushed = 0
                self._last_flush = now

    # -- Emitters --------------------------------------------------------

    def wave(self, fields: dict) -> None:
        """Emits one wave event. ``fields`` is the engine's unified
        dispatch-log entry (see ``schema.WAVE_FIELDS``); the tracer
        stamps type/version/engine/run, numbers the wave, and defaults
        the v5 attribution keys — one stamping site instead of four
        per-engine field-set edits (engines that HAVE a value, the
        elastic runtime, set it in their entry)."""
        evt = dict(fields, type="wave")
        for key in ("worker", "seq", "epoch", "round",
                    # v6 tier gauges: null outside a tiered-store run.
                    "tier_device_rows", "tier_device_bytes",
                    "tier_host_rows", "tier_host_bytes",
                    "tier_disk_rows", "tier_disk_bytes",
                    # v8 kernel-path keys: null on producers without a
                    # device wave (host checkers, elastic coordinator).
                    "kernel_path", "rows",
                    # v9 mux attribution: null on solo-engine waves.
                    "job_id", "jobs_in_wave",
                    # v10 async-I/O stall gauge: null where not tracked.
                    "io_stall_s",
                    # v12 expand-stage attribution: null on producers
                    # without a device wave.
                    "expand_impl",
                    # v13 cost attribution: null when the profiler is
                    # disarmed / the program has no cost model /
                    # the dispatch was not sampled.
                    "cost_flops", "cost_bytes", "cost_ratio"):
            evt.setdefault(key, None)
        self._write(evt, number_wave=True)

    def event(self, etype: str, **fields) -> None:
        # _flush=True forces the line out immediately — for emitters
        # about to hard-exit the process (injected child death).
        flush = bool(fields.pop("_flush", False))
        self._write(dict(fields, type=etype), flush=flush)

    def counter(self, name: str, inc=1) -> None:
        with self._lock:
            total = self._counters.get(name, 0) + inc
            self._counters[name] = total
        self._write({"type": "counter", "name": name, "value": total,
                     "inc": inc})

    def gauge(self, name: str, value) -> None:
        self._write({"type": "gauge", "name": name, "value": value})

    def emit_raw(self, evt: dict) -> None:
        """Writes one already-stamped event (no restamping, no wave
        numbering) — the ``TraceCollector``'s funnel for merged
        per-worker events, which arrive fully stamped by the worker's
        own relay tracer (``obs/collect.py``) and must keep their
        original run/worker/seq identity. ``_write``'s stamps are
        defaults the caller's fields override, so delegation preserves
        the foreign identity while sharing the one flush policy."""
        self._write(evt)

    def span_event(self, name: str, start: float, dur: float,
                   depth: int = 0, **attrs) -> None:
        """A pre-measured span (profiling.py times its stages itself)."""
        evt = {"type": "span", "name": name, "t": start,
               "dur": round(dur, 6), "depth": depth}
        if attrs:
            evt["attrs"] = attrs
        self._write(evt)

    @contextmanager
    def span(self, name: str, **attrs):
        """Measures a nested span: monotonic start/end, per-thread
        depth."""
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        start = time.monotonic()
        try:
            yield
        finally:
            self._local.depth = depth
            self.span_event(name, start, time.monotonic() - start,
                            depth=depth, **attrs)

    def close(self) -> None:
        """Writes ``run_end`` (with counter totals) and closes the
        stream. Idempotent — including against a concurrent close from
        a second thread (the async-I/O writer joins while the wave loop
        tears down): exactly one caller wins the ``_closing`` flag and
        writes ``run_end``; later emits become no-ops."""
        with self._lock:
            if self._closed or self._closing:
                return
            self._closing = True
            counters = dict(self._counters)
        self._write({"type": "run_end",
                     "dur": round(time.monotonic() - self._t0, 6),
                     "counters": counters}, flush=True, final=True)
        self._flush_stop.set()
        with self._lock:
            self._closed = True
            self._f.close()


def _jsonable(obj):
    """numpy scalars ride along in engine telemetry; coerce them."""
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            return fn()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")


def tracer_from_env(engine: str, meta: Optional[dict] = None,
                    path: Optional[str] = None):
    """The tracer factory every producer uses: ``STpu_TRACE`` set means
    a live ``RunTracer`` appending there; unset means the shared
    ``NULL_TRACER`` (no allocation, no file)."""
    path = path or os.environ.get(TRACE_ENV)
    if not path:
        return NULL_TRACER
    return RunTracer(path, engine, meta)

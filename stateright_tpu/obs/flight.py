"""The always-on flight recorder: a bounded ring of recent events.

``STpu_TRACE`` is an opt-in: most runs fly dark, and exactly those
runs are the ones whose crashes leave nothing behind. The flight
recorder closes that gap the way an aircraft FDR does — every device
engine, every elastic worker, and the elastic coordinator keep the
last ``capacity`` events in a bounded in-memory ring **even when
tracing is disabled**, and a failure (engine abort, ``worker_lost``,
an injected crash, an unhandled worker exception) dumps the ring to a
small JSONL postmortem file. The ``Supervisor`` and the elastic
coordinator attach the dump path to their ``retry`` / ``abort`` /
``worker_lost`` events, so a trace (or a bench RESULT) names the
postmortem that explains it.

Cost contract, mirroring the tracer's (round 8):

- **Recording is an append of an existing dict.** The engines already
  build one dispatch-log entry per wave whether or not tracing is on;
  ``record`` stores a *reference* in a ``deque(maxlen=N)`` — no copy,
  no serialization, no formatting. Stamping to schema-valid events
  happens once, at dump time (a cold path by definition).
- **Disarmed is one attribute check.** ``STpu_FLIGHT=0`` returns the
  shared :data:`NULL_RECORDER`; hot loops guard with
  ``if self._flight.armed:`` exactly as they guard the tracer with
  ``.enabled``, and the disarmed-cost test poisons the null methods
  (``tests/test_elastic_obs.py``, mirroring the round-8 poisoned-null
  test).

Dump files start with one ``postmortem`` header event (schema v5)
followed by the recorded events, stamped where the producer ran
untraced — so ``tools/trace_lint.py`` validates a dump,
``tools/trace_export.py`` renders one, and ``tools/trace_summary.py``
tabulates one, all with the machinery the live stream already has.

Dependency-free beyond ``obs.schema`` (no jax, no numpy): the elastic
worker processes and the tools import this without a backend.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import Optional

from .schema import SCHEMA_VERSION

__all__ = [
    "FLIGHT_ENV", "FLIGHT_DIR_ENV", "FLIGHT_CAPACITY", "FlightRecorder",
    "NullFlightRecorder", "NULL_RECORDER", "recorder_from_env",
    "postmortem_path", "dump_all", "install_signal_handlers",
]

#: Environment knob: ring capacity (events). ``0`` disarms the
#: recorder entirely (the shared null recorder — one attribute check);
#: unset means the default capacity. Unlike ``STpu_TRACE`` this
#: subsystem defaults ON: it allocates nothing per event beyond the
#: dicts its producers already build.
FLIGHT_ENV = "STpu_FLIGHT"

#: Where postmortem dumps land. Unset: the system temp directory.
FLIGHT_DIR_ENV = "STpu_FLIGHT_DIR"

#: Default ring capacity: enough waves to see the run's last seconds
#: at any realistic cadence, small enough to never matter in memory.
FLIGHT_CAPACITY = 256

_DUMP_SEQ = itertools.count()


def postmortem_path(name: str, directory: Optional[str] = None) -> str:
    """The dump path for producer ``name``: deterministic per name so
    a test or a bench drill can find a specific casualty's postmortem
    without parsing anything."""
    directory = (directory or os.environ.get(FLIGHT_DIR_ENV)
                 or tempfile.gettempdir())
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in str(name))
    return os.path.join(directory, f"stpu-postmortem-{safe}.jsonl")


class NullFlightRecorder:
    """The disarmed recorder: every method a no-op, ``armed`` False.
    Hot paths must check ``armed`` BEFORE calling ``record`` — the
    disarmed-cost test poisons these methods, so a stray call (= a
    stray per-wave cost with the subsystem off) fails the suite."""

    __slots__ = ()
    armed = False

    def record(self, evt) -> None:
        pass

    def record_event(self, etype, **fields) -> None:
        pass

    def dump(self, reason, name=None) -> Optional[str]:
        return None

    def snapshot(self) -> list:
        return []

    def set_hist_source(self, fn) -> None:
        pass


#: The shared disarmed recorder (``recorder_from_env`` returns this
#: very object under ``STpu_FLIGHT=0`` — identity-testable).
NULL_RECORDER = NullFlightRecorder()


class FlightRecorder:
    """A bounded ring of the last ``capacity`` events for one producer.

    ``name`` identifies the producer in dump headers and default dump
    paths (an engine id, a worker name, the elastic coordinator).
    ``record`` takes any dict the producer already has in hand —
    dispatch-log entries, relay-stamped trace events, lifecycle
    records; heterogeneity is fine because stamping to schema-valid
    lines happens at dump time.
    """

    armed = True

    def __init__(self, name: str, capacity: int = FLIGHT_CAPACITY,
                 directory: Optional[str] = None):
        self.name = str(name)
        self.capacity = max(1, int(capacity))
        self.directory = directory
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        #: the most recent dump's path (None until a dump happens) —
        #: what the Supervisor attaches to its retry/abort events.
        self.last_dump: Optional[str] = None
        #: optional zero-arg callable returning a stamped
        #: ``hist_snapshot`` event (or None) — ``dump`` appends it so a
        #: postmortem carries the producer's latency distribution at
        #: time of death, not just the event ring (round 18).
        self._hist_source = None

    def set_hist_source(self, fn) -> None:
        """Registers the final-histogram hook (``WaveObs.
        final_snapshot_event`` — obs/hist.py). Cold path; the ring's
        hot ``record`` never touches it."""
        self._hist_source = fn

    def record(self, evt: dict) -> None:
        """Appends one event reference to the ring. deque.append with
        maxlen is atomic under the GIL; no lock on the hot path."""
        self._ring.append(evt)

    def record_event(self, etype: str, **fields) -> None:
        """Builds and records a stamped event (cold paths only — a
        fault about to kill the process, a lifecycle transition)."""
        evt = {"type": etype, "schema_version": SCHEMA_VERSION,
               "engine": "flight", "run": f"flight-{self.name}",
               "t": round(time.monotonic(), 6)}
        evt.update(fields)
        self._ring.append(evt)

    def snapshot(self) -> list:
        """The ring's current contents, oldest first (stamped)."""
        with self._lock:
            return [self._stamp(e, i) for i, e in enumerate(self._ring)]

    def _stamp(self, evt: dict, i: int) -> dict:
        """A schema-valid copy of one recorded event. Producers that
        ran untraced recorded bare dispatch-log entries — those become
        ``wave`` events stamped with the flight producer's identity
        and ring-ordinal wave numbering (contiguous per dump, which is
        all the lint's per-run invariant needs)."""
        if "type" in evt:
            return dict(evt)
        out = {"type": "wave", "schema_version": SCHEMA_VERSION,
               "engine": "flight", "run": f"flight-{self.name}",
               "wave": i}
        out.update(evt)
        for key in ("worker", "seq", "epoch", "round",
                    # v6 tier gauges: null outside a tiered-store run.
                    "tier_device_rows", "tier_device_bytes",
                    "tier_host_rows", "tier_host_bytes",
                    "tier_disk_rows", "tier_disk_bytes",
                    "kernel_path", "rows",
                    # v9 mux attribution: null outside a mux group.
                    "job_id", "jobs_in_wave",
                    # v10 async-I/O stall gauge: null where not tracked.
                    "io_stall_s",
                    # v12 expand-stage attribution: null on producers
                    # without a device wave.
                    "expand_impl",
                    # v13 cost attribution: null when the profiler is
                    # disarmed / the program has no cost model /
                    # the dispatch was not sampled.
                    "cost_flops", "cost_bytes", "cost_ratio"):
            out.setdefault(key, None)
        return out

    def dump(self, reason: str, name: Optional[str] = None
             ) -> Optional[str]:
        """Writes the ring to a postmortem JSONL file and returns its
        path (one ``postmortem`` header event, then the recorded
        events oldest-first). ``name`` overrides the path identity —
        the coordinator dumps its own ring once per LOST worker, named
        for the casualty. Never raises: a postmortem must not turn a
        failure into a worse failure."""
        with self._lock:
            events = [self._stamp(e, i)
                      for i, e in enumerate(self._ring)]
        path = postmortem_path(name or self.name, self.directory)
        # Deterministic base name for findability, but never clobber an
        # earlier dump: a supervised engine fails once per ATTEMPT at
        # the same name, and each attempt's retry record must keep
        # naming the file that actually describes it.
        if os.path.exists(path):
            stem, ext = os.path.splitext(path)
            for n in range(2, 100):
                candidate = f"{stem}.{n}{ext}"
                if not os.path.exists(candidate):
                    path = candidate
                    break
            else:
                return None  # 99 postmortems at one name: stop digging
        header = {"type": "postmortem",
                  "schema_version": SCHEMA_VERSION, "engine": "flight",
                  "run": f"flight-{self.name}-{next(_DUMP_SEQ)}",
                  "t": round(time.monotonic(), 6),
                  "unix_t": round(time.time(), 3),
                  "reason": str(reason)[:500], "name": self.name,
                  "events": len(events)}
        final_hist = None
        if self._hist_source is not None:
            try:
                final_hist = self._hist_source()
            except Exception:
                final_hist = None  # a postmortem must never get worse
        try:
            with open(path, "w", encoding="utf-8") as f:
                f.write(json.dumps(header, separators=(",", ":"),
                                   default=_best_effort) + "\n")
                for evt in events:
                    f.write(json.dumps(evt, separators=(",", ":"),
                                       default=_best_effort) + "\n")
                if final_hist is not None:
                    f.write(json.dumps(final_hist, separators=(",", ":"),
                                       default=_best_effort) + "\n")
        except OSError:
            return None
        self.last_dump = path
        return path


def _best_effort(obj):
    """Ring contents are whatever the producer had in hand (numpy
    scalars ride along in engine telemetry); a postmortem writer must
    never raise, so unknowns degrade to repr."""
    fn = getattr(obj, "item", None)
    if callable(fn):
        return fn()
    return repr(obj)


# -- Signal-driven dumps ----------------------------------------------------
#
# A crash dumps its ring through the failure paths (Supervisor,
# coordinator, engine abort) — but a PREEMPTED run (SIGTERM from a
# scheduler, Ctrl-C from an operator) used to exit with its rings full
# and unwritten, which is exactly backwards: the cancelled soak is the
# one whose last seconds someone wants to see. ``recorder_from_env``
# therefore registers every armed ring in a process-wide weak set and
# installs (once, main thread only) SIGTERM/SIGINT handlers that dump
# every live ring before chaining to the previous disposition — the
# process still dies the way it would have, it just leaves postmortems
# first.

_SIGNAL_LOCK = threading.Lock()
_LIVE_RECORDERS: "weakref.WeakSet" = weakref.WeakSet()
_PREV_HANDLERS: dict = {}
_HANDLERS_INSTALLED = False


def dump_all(reason: str) -> list:
    """Dumps every live armed ring; returns the written paths. Never
    raises — the signal-handler path must not turn a shutdown into a
    traceback."""
    paths = []
    for rec in list(_LIVE_RECORDERS):
        try:
            path = rec.dump(reason)
        except Exception:
            path = None
        if path:
            paths.append(path)
    return paths


def _on_signal(signum, frame):
    try:
        name = signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    dump_all(f"signal-{name}")
    prev = _PREV_HANDLERS.get(signum)
    if callable(prev):
        prev(signum, frame)  # e.g. default_int_handler -> KeyboardInterrupt
    elif prev != signal.SIG_IGN:
        # SIG_DFL: re-deliver under the default disposition so the
        # process still dies with the right termination status.
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_signal_handlers() -> bool:
    """Installs the SIGTERM/SIGINT dump handlers once per process.
    Returns True when installed (now or earlier); False when it cannot
    be (not the main thread — engines spawned from worker threads
    simply leave dispositions alone)."""
    global _HANDLERS_INSTALLED
    with _SIGNAL_LOCK:
        if _HANDLERS_INSTALLED:
            return True
        try:
            for signum in (signal.SIGTERM, signal.SIGINT):
                prev = signal.getsignal(signum)
                signal.signal(signum, _on_signal)
                _PREV_HANDLERS[signum] = prev
        except ValueError:
            return False
        _HANDLERS_INSTALLED = True
        return True


def recorder_from_env(name: str, directory: Optional[str] = None,
                      capacity: Optional[int] = None):
    """The recorder factory every producer uses: armed by default
    (``STpu_FLIGHT`` unset or a positive capacity), the shared
    :data:`NULL_RECORDER` under ``STpu_FLIGHT=0``. Armed recorders
    join the signal-dump registry (weakly — a collected engine's ring
    drops out on its own)."""
    if capacity is None:
        raw = os.environ.get(FLIGHT_ENV, "")
        try:
            capacity = int(raw) if raw else FLIGHT_CAPACITY
        except ValueError:
            capacity = FLIGHT_CAPACITY
    if capacity <= 0:
        return NULL_RECORDER
    rec = FlightRecorder(name, capacity=capacity, directory=directory)
    _LIVE_RECORDERS.add(rec)
    install_signal_handlers()
    return rec

"""Symmetry reduction: rewrite plans and canonical representatives.

Counterpart of the reference's `src/checker/{representative,rewrite,
rewrite_plan}.rs` (the Symmetric-Spin canonicalization technique). A
``RewritePlan`` is built by sorting a vector-like field of the state; it
yields (a) ``reindex``: permute a per-process collection into canonical
order, and (b) ``rewrite``: remap process-id values embedded elsewhere in
the state. ``rewrite_value`` recursively walks common containers,
rewriting exactly ``Id``-typed values (scalars and other types are left
alone, like the reference's no-op ``Rewrite`` impls for scalars).

Models with plain-integer process indices (e.g. 2pc) rewrite those fields
explicitly in their ``representative`` implementations, mirroring the
reference examples.

On the TPU engine, canonicalization is a per-row sort-and-relabel of the
encoded state vector; see ``stateright_tpu.tpu``.
"""

from __future__ import annotations

from dataclasses import fields, is_dataclass, replace
from enum import Enum
from typing import Any, Callable, List, Optional, Sequence

from .actor.core import Id
from .fingerprint import fingerprint_bytes

__all__ = ["RewritePlan", "rewrite_value", "actor_model_representative",
           "sort_key"]


def sort_key(value: Any):
    """A deterministic total order over heterogeneous state values: natural
    comparison when available is NOT used (it varies with type mixes);
    instead orders by (type name, canonical digest). Used where the
    reference requires ``Ord`` on actor states of a single type."""
    return (type(value).__qualname__, fingerprint_bytes(value))


class RewritePlan:
    """Derived from a state field; indicates how process ids should be
    rewritten so the result is behaviorally equivalent under symmetry
    (`rewrite_plan.rs:19-89`)."""

    __slots__ = ("reindex_mapping", "rewrite_mapping")

    def __init__(self, reindex_mapping: List[int]):
        self.reindex_mapping = list(reindex_mapping)
        # dst position for each src index: rewrite_mapping[src] = dst
        pairs = sorted((src, dst)
                       for dst, src in enumerate(self.reindex_mapping))
        self.rewrite_mapping = [dst for _, dst in pairs]

    @staticmethod
    def from_values_to_sort(values: Sequence,
                            key: Optional[Callable] = None) -> "RewritePlan":
        """Builds a plan that sorts ``values`` (`rewrite_plan.rs:36-49`).
        ``key`` defaults to natural ordering; pass ``sort_key`` for
        heterogeneous values."""
        indexed = list(enumerate(values))
        if key is None:
            indexed.sort(key=lambda iv: iv[1])
        else:
            indexed.sort(key=lambda iv: key(iv[1]))
        return RewritePlan([i for i, _ in indexed])

    def reindex(self, indexed: Sequence) -> list:
        """Permutes a per-process collection into canonical order,
        rewriting each element (`rewrite_plan.rs:68-76`)."""
        return [rewrite_value(indexed[i], self) for i in self.reindex_mapping]

    def rewrite(self, index):
        """Remaps one process index, preserving its type
        (`rewrite_plan.rs:84-89`)."""
        return type(index)(self.rewrite_mapping[int(index)])

    def __eq__(self, other):
        return (isinstance(other, RewritePlan)
                and self.reindex_mapping == other.reindex_mapping)

    def __repr__(self):
        return (f"RewritePlan(reindex={self.reindex_mapping}, "
                f"rewrite={self.rewrite_mapping})")


def rewrite_value(value: Any, plan: RewritePlan) -> Any:
    """Structural recursion rewriting embedded ``Id`` values
    (`rewrite.rs:24-120`). Unknown object types are returned unchanged
    (scalar no-op impls); objects may define ``__rewrite__(plan)``."""
    t = type(value)
    if t is Id:
        return plan.rewrite(value)
    if value is None or t in (bool, int, float, str, bytes) \
            or isinstance(value, Enum):
        return value
    if t is tuple:
        return tuple(rewrite_value(v, plan) for v in value)
    if t is list:
        return [rewrite_value(v, plan) for v in value]
    if t is frozenset or t is set:
        return t(rewrite_value(v, plan) for v in value)
    if t is dict:
        return {rewrite_value(k, plan): rewrite_value(v, plan)
                for k, v in value.items()}
    custom = getattr(value, "__rewrite__", None)
    if custom is not None:
        return custom(plan)
    if is_dataclass(value):
        return replace(value, **{
            f.name: rewrite_value(getattr(value, f.name), plan)
            for f in fields(value)})
    if isinstance(value, tuple):  # namedtuple
        return t(*(rewrite_value(v, plan) for v in value))
    return value


def actor_model_representative(state) -> "ActorModelState":
    """Canonicalizes an ``ActorModelState`` by sorting actor states and
    rewriting ids in the network, timers, and history
    (`actor/model_state.rs:103-118`)."""
    from .actor.model_state import ActorModelState, Network

    plan = RewritePlan.from_values_to_sort(state.actor_states, key=sort_key)
    # is_timer_set is lazily sized (grown only on SetTimer); pad before
    # permuting by actor index.
    timers = list(state.is_timer_set)
    timers += [False] * (len(state.actor_states) - len(timers))
    return ActorModelState(
        actor_states=plan.reindex(state.actor_states),
        network=Network(rewrite_value(e, plan) for e in state.network),
        is_timer_set=plan.reindex(timers),
        history=rewrite_value(state.history, plan),
    )

"""Tiered state store: device arena -> host RAM -> disk segments.

See :mod:`stateright_tpu.store.tiered` for the design; the engines and
the elastic workers construct stores through :func:`store_from_config`
(``STpu_TIER_DEVICE_BYTES`` / ``STpu_TIER_HOST_BYTES`` /
``STpu_TIER_DIR`` environment knobs, or explicit engine kwargs).
"""

from .tiered import (NULL_STORE, TIER_DEVICE_ENV, TIER_DIR_ENV,
                     TIER_HOST_ENV, FrontierRef, NullStore, TieredStore,
                     load_cold_refs,
                     map_segment_visited, store_from_config)

__all__ = [
    "TIER_DEVICE_ENV", "TIER_HOST_ENV", "TIER_DIR_ENV",
    "FrontierRef", "NullStore", "NULL_STORE", "TieredStore",
    "load_cold_refs", "map_segment_visited", "store_from_config",
]

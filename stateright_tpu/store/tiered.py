"""The tiered state store: device arena -> host RAM -> disk segments.

Capacity used to end at one device's structures: when the visited
table or arena could not grow, the engines shed batch buckets and then
aborted (round-10 ``grow_oom`` degrade path). This module turns memory
pressure into a *recoverable, observable* condition, the way ScalaBFS
(arXiv:2105.11754) and the GPUexplore scalability study
(arXiv:1801.05857) exploit the memory hierarchy instead of dying at
the first tier's edge:

- **Hot**: the device-resident structures (visited table, fused
  arena) — owned by the engines, budgeted by ``device_budget`` bytes.
- **Warm**: host-RAM partitions of spilled visited fingerprints
  (``fp % n_partitions`` buckets, each a sorted ``uint64`` array), and
  the host-side frontier block queue. Budgeted by ``host_budget``.
- **Cold**: memory-mapped disk segments under ``segment_dir``. A cold
  visited segment is written in the checkpoint per-section CRC layout
  (``checkpoint_format.write_atomic``, uncompressed so the fingerprint
  section can be ``np.memmap``-ed in place), so **a cold segment IS a
  valid checkpoint shard**: ``verify_file`` validates it, keep-last-2
  rotation gives every partition file a ``.prev`` predecessor, and
  checkpoint format v5 references segments by content hash instead of
  rewriting them.

Correctness contract: spilling NEVER changes results. The engines keep
inserting into the device table as before; a spilled fingerprint that
gets re-generated is re-admitted to the device tier and the per-wave
host-side :meth:`TieredStore.probe` (sorted-array membership, batched
over the wave's novel block) filters it before it can be re-counted or
re-queued — counts, discoveries, and parent maps stay bit-identical to
an all-in-device run (the cross-engine parity suites pin this).

Fault points (round-10 registry): ``spill_fail`` (a device->host move
dies mid-spill), ``disk_full`` (a cold write raises at allocation),
``page_in_torn`` (a cold segment write lands torn — the store detects
the CRC failure on its immediate re-verify and falls back to the
rotation predecessor, CRC-verified before any parse, keeping the
unspilled rows warm; ``recover`` is emitted in-store). ``spill_fail``
and ``disk_full`` propagate to the Supervisor, whose checkpoint resume
is the recovery.

The disarmed store is the shared ``NULL_STORE`` (``active`` False) —
engine hot loops pay one attribute check per wave, the tracer/faults
contract.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "TIER_DEVICE_ENV", "TIER_HOST_ENV", "TIER_DIR_ENV",
    "FrontierRef", "TieredStore", "NullStore", "NULL_STORE",
    "store_from_config",
]

#: Environment knobs (engine kwargs override them): byte budgets for
#: the device and host tiers, and the cold segment directory. Any one
#: of them arms the store; missing budgets mean that tier is unbounded
#: and a missing dir means no cold tier (warm pressure is then logged
#: but not relieved).
TIER_DEVICE_ENV = "STpu_TIER_DEVICE_BYTES"
TIER_HOST_ENV = "STpu_TIER_HOST_BYTES"
TIER_DIR_ENV = "STpu_TIER_DIR"


def _parse_bytes(text) -> Optional[int]:
    if text is None:
        return None
    text = str(text).strip().lower()
    if not text or text == "0":
        return None
    mult = 1
    for suffix, m in (("kib", 1024), ("mib", 1 << 20), ("gib", 1 << 30),
                      ("k", 1024), ("m", 1 << 20), ("g", 1 << 30)):
        if text.endswith(suffix):
            mult = m
            text = text[:-len(suffix)]
            break
    return int(float(text) * mult)


class FrontierRef:
    """A frontier block that lives on disk: the queue entry left behind
    when :meth:`TieredStore.balance_frontier` pages a block out. The
    engines' ``_take_batch`` materializes it (with one-block-ahead
    prefetch) before the rows reach a dispatch."""

    __slots__ = ("path", "rows", "nbytes")

    def __init__(self, path: str, rows: int, nbytes: int):
        self.path = path
        self.rows = rows
        self.nbytes = nbytes


class _ColdPart:
    """One partition's cold generation: the segment file plus the
    (memory-mapped where possible) sorted fingerprint view."""

    __slots__ = ("path", "fps", "rows", "sha")

    def __init__(self, path: str, fps: np.ndarray, sha: str):
        self.path = path
        self.fps = fps
        self.rows = int(len(fps))
        self.sha = sha


def _block_bytes(block) -> int:
    return sum(int(np.asarray(a).nbytes) for a in block)


def _merge_sorted(a: Optional[np.ndarray], b: np.ndarray) -> np.ndarray:
    """Sorted union (dedup) of ``a`` (already sorted, may be None) and
    ``b`` (any order)."""
    b = np.unique(np.asarray(b, np.uint64))
    if a is None or not len(a):
        return b
    out = np.concatenate([np.asarray(a, np.uint64), b])
    out.sort(kind="mergesort")
    if len(out) > 1:
        keep = np.empty(len(out), bool)
        keep[0] = True
        np.not_equal(out[1:], out[:-1], out=keep[1:])
        out = out[keep]
    return out


def _sorted_member(arr: np.ndarray, vals: np.ndarray) -> np.ndarray:
    if arr is None or not len(arr) or not len(vals):
        return np.zeros(len(vals), bool)
    idx = np.searchsorted(arr, vals)
    idx = np.minimum(idx, len(arr) - 1)
    return arr[idx] == vals


def map_segment_visited(path: str) -> np.ndarray:
    """Memory-maps the ``visited`` section of an UNCOMPRESSED segment
    npz in place (the cold tier's whole point: probe without holding
    the fingerprints in RAM). Falls back to a full read when the member
    is compressed or the container layout is unexpected."""
    import zipfile

    try:
        with zipfile.ZipFile(path) as z:
            info = z.getinfo("visited.npy")
            if info.compress_type != zipfile.ZIP_STORED:
                raise ValueError("compressed member")
            with open(path, "rb") as f:
                # Parse the local file header for the real data start
                # (the central directory's extra field can differ).
                f.seek(info.header_offset)
                local = f.read(30)
                if local[:4] != b"PK\x03\x04":
                    raise ValueError("bad local header")
                name_len = int.from_bytes(local[26:28], "little")
                extra_len = int.from_bytes(local[28:30], "little")
                data_off = info.header_offset + 30 + name_len + extra_len
                f.seek(data_off)
                version = np.lib.format.read_magic(f)
                shape, fortran, dtype = \
                    np.lib.format._read_array_header(f, version)
                array_off = f.tell()
        if fortran or dtype != np.dtype(np.uint64) or len(shape) != 1:
            raise ValueError("unexpected visited layout")
        return np.memmap(path, dtype=np.uint64, mode="r",
                         offset=array_off, shape=shape)
    except Exception:  # noqa: BLE001 — memmap is an optimization only
        from ..checkpoint_format import load_checkpoint

        with load_checkpoint(path) as data:
            return np.array(data["visited"], np.uint64)


class NullStore:
    """The disarmed store: ``active`` is False, every probe/balance is
    a no-op, and stats report disabled. Hot loops guard with
    ``if store.active:`` — one attribute check per wave."""

    __slots__ = ()
    active = False
    device_budget = None
    spilled_rows = 0

    def probe(self, fps) -> np.ndarray:
        return np.zeros(len(fps), bool)

    def balance_frontier(self, queues) -> None:
        pass

    def attach_async(self, writer) -> None:
        pass

    def stats(self) -> dict:
        return {"enabled": False}

    def gauges(self) -> dict:
        return {}


NULL_STORE = NullStore()


class TieredStore:
    """Warm/cold membership partitions + frontier paging for one
    engine (or one elastic worker).

    ``owner`` is the engine (or any object) whose ``_tracer`` the
    store's spill/page_in/pressure events ride on — read lazily per
    emit so a ``restart_from`` tracer rotation is picked up for free.
    """

    active = True

    def __init__(self, *, device_budget: Optional[int] = None,
                 host_budget: Optional[int] = None,
                 segment_dir: Optional[str] = None,
                 n_partitions: int = 16, owner=None,
                 prefix: str = "", meta: Optional[dict] = None):
        from ..resilience.faults import fault_plan_from_env

        self.device_budget = device_budget
        self.host_budget = host_budget
        self.segment_dir = segment_dir
        if segment_dir:
            os.makedirs(segment_dir, exist_ok=True)
        self._P = max(1, int(n_partitions))
        self._owner = owner
        self._prefix = prefix
        #: header identity for cold segments (model_name, state_width,
        #: use_symmetry) — what makes a segment a valid checkpoint
        #: shard rather than a bag of bytes.
        self._meta = dict(meta or {})
        self._faults = fault_plan_from_env()
        self._lock = threading.Lock()
        self._warm: List[Optional[np.ndarray]] = [None] * self._P
        self._cold: Dict[int, _ColdPart] = {}
        self._next_spill = 0
        self._frontier_seq = 0
        self._executor = None
        self._prefetched: Dict[str, object] = {}
        # Asynchronous host I/O (round 17): the owner's background
        # writer, attached via attach_async(). Cold-segment writes are
        # handed to it; partitions with a submitted-but-unlanded write
        # sit in _spilling so the budget loop never double-submits.
        from ..io.async_io import SyncWriter

        self._aio = SyncWriter()
        self._spilling: set = set()
        # Telemetry (all folded into stats()/gauges()).
        self._spills = {"host": 0, "disk": 0}
        self._spill_bytes = 0
        self._page_ins = 0
        self._prefetch_hits = 0
        self._probes = 0
        self._probe_hits = 0
        self._arena_span_rows = 0
        self._arena_span_bytes = 0
        self._arena_span_spills = 0
        self._frontier_bytes = 0
        self._host_high_water = 0
        self._disk_high_water = 0

    def attach_async(self, writer) -> None:
        """Arms asynchronous cold-segment writes: ``writer`` is the
        owner's ``AsyncWriter`` (the engine shares ONE writer across
        checkpoints and spills, so its safe-point join covers both).
        With the knob off the default inline ``SyncWriter`` stays and
        every path behaves exactly as before round 17."""
        self._aio = writer

    # -- Event plumbing ---------------------------------------------------

    def _tracer(self):
        t = getattr(self._owner, "_tracer", None)
        return t if t is not None and getattr(t, "enabled", False) \
            else None

    def _event(self, etype: str, **fields) -> None:
        t = self._tracer()
        if t is not None:
            t.event(etype, _flush=True, **fields)

    # -- Tier accounting --------------------------------------------------

    @property
    def warm_rows(self) -> int:
        return sum(len(a) for a in self._warm if a is not None)

    @property
    def warm_bytes(self) -> int:
        return 8 * self.warm_rows

    @property
    def cold_rows(self) -> int:
        return sum(p.rows for p in self._cold.values())

    @property
    def cold_bytes(self) -> int:
        return 8 * self.cold_rows + self._frontier_bytes

    @property
    def spilled_rows(self) -> int:
        """Spilled VISITED rows (warm + cold) — what probe() checks."""
        return self.warm_rows + self.cold_rows

    def host_used(self, frontier_host_bytes: int = 0) -> int:
        return self.warm_bytes + frontier_host_bytes

    # -- Visited spill (device -> warm -> cold) ---------------------------

    def spill_mask(self, fps: np.ndarray, enough) -> np.ndarray:
        """Selects fingerprints to evict from the device tier:
        whole ``fp % P`` partitions in deterministic round-robin order
        until ``enough(keep_fps)`` says the survivors fit (or every
        partition is selected). The choice is a performance schedule,
        never semantics — membership of spilled rows is covered by
        :meth:`probe`."""
        part = (fps % np.uint64(self._P)).astype(np.int64)
        mask = np.zeros(len(fps), bool)
        for _ in range(self._P):
            if enough(fps[~mask]):
                break
            p = self._next_spill
            self._next_spill = (self._next_spill + 1) % self._P
            mask |= part == p
        return mask

    def spill_visited(self, fps: np.ndarray) -> None:
        """Absorbs evicted device fingerprints into the warm tier, then
        relieves host pressure by pushing the largest warm partitions
        to cold segments. ``spill_fail`` fires BEFORE any mutation, so
        a supervised resume sees consistent tiers."""
        self._faults.crash("spill_fail", self._tracer(), rows=len(fps))
        fps = np.asarray(fps, np.uint64)
        if not len(fps):
            return
        part = (fps % np.uint64(self._P)).astype(np.int64)
        with self._lock:
            for p in np.unique(part):
                self._warm[p] = _merge_sorted(self._warm[int(p)],
                                              fps[part == p])
            self._spills["host"] += 1
            self._spill_bytes += 8 * len(fps)
            self._host_high_water = max(self._host_high_water,
                                        self.warm_bytes)
        self._event("spill", tier="host", kind="visited",
                    rows=int(len(fps)), bytes=8 * int(len(fps)))
        self.enforce_host_budget()

    def enforce_host_budget(self, frontier_bytes: int = 0) -> None:
        """Pushes warm partitions to the cold tier while the host tier
        is over budget. Without a segment dir the pressure is recorded
        (one ``pressure`` event per crossing) but cannot be relieved."""
        if self.host_budget is None:
            return
        if self.host_used(frontier_bytes) <= self.host_budget:
            return
        if not self.segment_dir:
            self._event("pressure", tier="host",
                        used=int(self.host_used(frontier_bytes)),
                        budget=int(self.host_budget))
            return
        if self._aio.enabled:
            self._enforce_host_budget_async(frontier_bytes)
            return
        while self.host_used(frontier_bytes) > self.host_budget:
            sizes = [(0 if a is None else len(a)) for a in self._warm]
            p = int(np.argmax(sizes))
            if sizes[p] == 0:
                break
            self._spill_partition_to_disk(p)
        self._event("pressure", tier="host",
                    used=int(self.host_used(frontier_bytes)),
                    budget=int(self.host_budget))

    def _enforce_host_budget_async(self, frontier_bytes: int) -> None:
        """The async twin of the budget loop: SELECT partitions on the
        calling thread with projected sizes (submitted-but-unlanded
        spills count as already gone, so the pick sequence — argmax,
        zero it, repeat — reproduces the sync loop's partition order
        exactly, which is what keeps cold-segment bytes knob-identical)
        and hand each write to the background writer. The warm rows are
        CAPTURED here, at the rest point, so the segment's content
        matches what a sync spill would have written even if the wave
        loop merges more rows into the partition while the write is in
        flight — those later rows simply stay warm."""
        with self._lock:
            sizes = [0 if (a is None or p in self._spilling) else len(a)
                     for p, a in enumerate(self._warm)]
            pending = sum(
                0 if self._warm[p] is None else len(self._warm[p])
                for p in self._spilling)
        used = self.host_used(frontier_bytes) - 8 * pending
        submitted = 0
        while used > self.host_budget:
            p = int(np.argmax(sizes))
            if sizes[p] == 0:
                break
            with self._lock:
                warm = self._warm[p]
                if warm is None or not len(warm):
                    sizes[p] = 0
                    continue
                self._spilling.add(p)
            self._aio.submit(
                lambda p=p, warm=warm:
                self._spill_partition_to_disk(p, warm_rows=warm),
                kind="spill")
            used -= 8 * sizes[p]
            sizes[p] = 0
            submitted += 1
        self._event("pressure", tier="host", used=int(max(used, 0)),
                    budget=int(self.host_budget))

    def _segment_path(self, p: int) -> str:
        return os.path.join(self.segment_dir,
                            f"{self._prefix}tier-p{p:03d}.npz")

    def _spill_partition_to_disk(self, p: int,
                                 warm_rows: Optional[np.ndarray] = None
                                 ) -> None:
        """Writes partition ``p``'s cold generation = union(previous
        cold generation, warm rows): the checkpoint-layout segment at a
        rotating path, so keep-last-2 holds per partition. A torn
        landing (injected ``page_in_torn``, or a real crash caught by
        the immediate CRC re-verify) falls back to the rotation
        predecessor — CRC-verified before any parse — and keeps the
        new rows warm, so no fingerprint is ever lost.

        ``warm_rows`` is the async path's capture: the partition's warm
        rows AS OF submission (the rest point), so the segment content
        matches the sync write even when the wave loop keeps merging.
        Rows merged after the capture stay warm — the landing SUBTRACTS
        the captured set instead of clearing the partition. With
        ``disk_full``/``page_in_torn`` armed, the crash fires on
        whatever thread runs this — the background writer under
        ``async_io`` — and surfaces at the owner's next safe-point
        join."""
        from ..checkpoint_format import (PREV_SUFFIX, content_hash,
                                         make_header, verify_file,
                                         write_atomic)

        tracer = self._tracer()
        try:
            self._faults.crash("disk_full", tracer, partition=p)
        except BaseException:
            with self._lock:
                self._spilling.discard(p)
            raise
        with self._lock:
            warm = self._warm[p] if warm_rows is None else warm_rows
            if warm is None or not len(warm):
                self._spilling.discard(p)
                return
            prev = self._cold.get(p)
            union = _merge_sorted(None if prev is None else prev.fps,
                                  warm)
        path = self._segment_path(p)
        sha = content_hash(union)
        header = make_header(
            model_name=str(self._meta.get("model_name", "store")),
            state_width=int(self._meta.get("state_width", 0)),
            state_count=int(len(union)), unique_count=int(len(union)),
            use_symmetry=bool(self._meta.get("use_symmetry", False)),
            discoveries={},
            store_segment={"partition": p, "rows": int(len(union)),
                           "sha": sha})
        # Uncompressed: the visited section must memmap in place.
        write_atomic(path, {"header": header, "visited": union},
                     compress=False)
        if self._faults.fires("page_in_torn", tracer, mode="torn",
                              partition=p):
            # The segment write "lands torn": only a truncated prefix
            # reaches the final path (the previous generation has
            # already rotated to .prev).
            with open(path, "rb") as f:
                blob = f.read()
            with open(path, "wb") as f:
                f.write(blob[:max(8, len(blob) // 3)])
        try:
            verify_file(path)
            got = map_segment_visited(path)
            if content_hash(np.asarray(got)) != sha:
                raise ValueError("content hash mismatch after write")
        except ValueError:
            # Torn cold segment: fall back to the rotation predecessor,
            # CRC-verified before any parse. The rows we tried to push
            # stay warm (pressure persists, correctness does not care),
            # so the recovery is complete in-store.
            prev_path = path + PREV_SUFFIX
            restored = None
            if prev is not None and os.path.exists(prev_path):
                try:
                    verify_file(prev_path)
                    fps = map_segment_visited(prev_path)
                    if content_hash(np.asarray(fps)) == prev.sha:
                        restored = _ColdPart(prev_path, fps, prev.sha)
                except ValueError:
                    restored = None
            with self._lock:
                if restored is not None:
                    self._cold[p] = restored
                elif prev is not None:
                    # Keep the in-memory previous view (its file may be
                    # the rotated .prev; the arrays are still valid).
                    self._cold[p] = prev
                else:
                    self._cold.pop(p, None)
                self._spilling.discard(p)
            self._event("recover", attempt=1, backoff_s=0.0,
                        resumed_from=(restored.path if restored
                                      else None),
                        kind="cold_segment_prev")
            return
        with self._lock:
            # Install the cold generation and retire exactly the rows
            # it covers IN ONE critical section, so a concurrent probe
            # sees every fingerprint in at least one tier. Rows merged
            # into the partition after an async capture are NOT in the
            # segment — they stay warm.
            self._cold[p] = _ColdPart(path, map_segment_visited(path),
                                      sha)
            cur = self._warm[p]
            if cur is None or cur is warm:
                self._warm[p] = None
            else:
                keep = cur[~_sorted_member(warm, cur)]
                self._warm[p] = keep if len(keep) else None
            self._spilling.discard(p)
            self._spills["disk"] += 1
            self._spill_bytes += 8 * int(len(union))
            self._disk_high_water = max(self._disk_high_water,
                                        self.cold_bytes)
        self._event("spill", tier="disk", kind="visited",
                    rows=int(len(union)), bytes=8 * int(len(union)))

    # -- Membership probe --------------------------------------------------

    def probe(self, fps: np.ndarray) -> np.ndarray:
        """Batched membership of ``fps`` against every spilled
        (warm + cold) partition: True where the fingerprint was
        already visited. One call per wave — this is the honest cost
        of running past the device tier's edge."""
        fps = np.asarray(fps, np.uint64)
        present = np.zeros(len(fps), bool)
        if not len(fps) or not self.spilled_rows:
            return present
        part = (fps % np.uint64(self._P)).astype(np.int64)
        with self._lock:
            for p in np.unique(part):
                p = int(p)
                warm = self._warm[p]
                cold = self._cold.get(p)
                if warm is None and cold is None:
                    continue
                m = part == p
                vals = fps[m]
                acc = _sorted_member(warm, vals)
                if cold is not None:
                    acc |= _sorted_member(cold.fps, vals)
                present[m] = acc
            self._probes += len(fps)
            self._probe_hits += int(present.sum())
        return present

    # -- Partition-scoped surface (elastic workers) ------------------------
    #
    # The elastic workers key the store by their MODEL partition index
    # (construct with ``n_partitions == n_parts``), so a partition's
    # spilled rows can be checkpointed with, migrated with, and dropped
    # with the partition itself.

    def spill_partition_rows(self, p: int, fps: np.ndarray) -> None:
        """Moves one partition's visited rows into the store (warm,
        then cold under host pressure) — the elastic workers' spill
        path for their in-RAM visited sets."""
        self._faults.crash("spill_fail", self._tracer(), partition=p,
                          rows=len(fps))
        fps = np.asarray(fps, np.uint64)
        if not len(fps):
            return
        with self._lock:
            self._warm[p] = _merge_sorted(self._warm[p], fps)
            self._spills["host"] += 1
            self._spill_bytes += 8 * len(fps)
            self._host_high_water = max(self._host_high_water,
                                        self.warm_bytes)
        self._event("spill", tier="host", kind="visited",
                    rows=int(len(fps)), bytes=8 * int(len(fps)))
        self.enforce_host_budget()

    def probe_partition(self, p: int, vals: np.ndarray) -> np.ndarray:
        """Membership of ``vals`` against ONE partition's spilled
        tiers."""
        vals = np.asarray(vals, np.uint64)
        with self._lock:
            warm = self._warm[p]
            cold = self._cold.get(p)
            acc = _sorted_member(warm, vals)
            if cold is not None:
                acc |= _sorted_member(cold.fps, vals)
            self._probes += len(vals)
            self._probe_hits += int(acc.sum())
        return acc

    def partition_fps(self, p: int) -> np.ndarray:
        """Every spilled fingerprint of partition ``p`` (warm + cold)
        — what a per-shard checkpoint must materialize alongside the
        in-RAM set so the shard file stays self-contained."""
        with self._lock:
            warm = self._warm[p]
            cold = self._cold.get(p)
        parts = [a for a in (warm, None if cold is None else cold.fps)
                 if a is not None and len(a)]
        if not parts:
            return np.zeros(0, np.uint64)
        return np.asarray(_merge_sorted(parts[0], parts[1])
                          if len(parts) == 2 else parts[0], np.uint64)

    def drop_partition(self, p: int) -> None:
        """Forgets a partition's spilled tiers (ownership moved away —
        the adopter rebuilds from the shard checkpoint)."""
        with self._lock:
            self._warm[p] = None
            self._cold.pop(p, None)

    # -- Frontier paging (host RAM -> disk, with page-in prefetch) --------

    def balance_frontier(self, queues) -> None:
        """Pages frontier blocks out to disk while the host tier
        (warm rows + queued frontier bytes) is over budget. Blocks are
        taken from the BACK of the deepest queue (consumed last), the
        head block of each queue is never paged (it is about to
        dispatch), and each queue keeps FIFO order — paging is a
        placement decision, never a reorder."""
        if self.host_budget is None or not self.segment_dir:
            return
        total = sum(_block_bytes(b) for q in queues for b in q
                    if not isinstance(b, FrontierRef))
        if self.host_used(total) <= self.host_budget:
            return
        moved = False
        while self.host_used(total) > self.host_budget:
            best, best_bytes = None, 0
            for q in queues:
                for i in range(len(q) - 1, 0, -1):
                    b = q[i]
                    if isinstance(b, FrontierRef):
                        continue
                    nb = _block_bytes(b)
                    if nb > best_bytes:
                        best, best_bytes = (q, i), nb
                    break
            if best is None:
                break
            q, i = best
            q[i] = self._stash_block(q[i])
            total -= best_bytes
            moved = True
        if moved:
            self._event("pressure", tier="host",
                        used=int(self.host_used(total)),
                        budget=int(self.host_budget))

    def _stash_block(self, block) -> FrontierRef:
        vecs, fps, ebits = block
        self._faults.crash("disk_full", self._tracer(),
                           kind="frontier")
        with self._lock:
            seq = self._frontier_seq
            self._frontier_seq += 1
        path = os.path.join(self.segment_dir,
                            f"{self._prefix}frontier-{seq:06d}.npz")
        with open(path, "wb") as f:
            np.savez(f, vecs=vecs, fps=fps, ebits=ebits)
        nbytes = _block_bytes(block)
        with self._lock:
            self._frontier_bytes += nbytes
            self._disk_high_water = max(self._disk_high_water,
                                        self.cold_bytes)
        self._event("spill", tier="disk", kind="frontier",
                    rows=int(len(fps)), bytes=int(nbytes))
        return FrontierRef(path, int(len(fps)), int(nbytes))

    def _read_block(self, ref: FrontierRef, fire_faults: bool = True):
        if fire_faults:
            self._faults.crash("page_in_torn", self._tracer(),
                               path=ref.path)
        try:
            with np.load(ref.path) as data:
                return (np.array(data["vecs"]), np.array(data["fps"]),
                        np.array(data["ebits"]))
        except Exception as e:  # noqa: BLE001 — torn/missing stash
            raise ValueError(
                f"frontier block {ref.path!r} is unreadable (torn "
                f"write or missing file): {e}; resume from the last "
                "checkpoint") from e

    def prefetch(self, ref: Optional[FrontierRef]) -> None:
        """Submits the NEXT page-in to the background reader so the
        disk read overlaps the current dispatch (the double-buffered
        host<->disk transfer of the paging story)."""
        if ref is None or ref.path in self._prefetched:
            return
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="stpu-page")
        # Faults fire at CONSUMPTION, not in the reader thread: a
        # prefetched future the run never collects (early stop) must
        # not swallow an injected crash after its 'fault' event was
        # already emitted — the lint would see an unpaired fault on an
        # otherwise clean stream. Real read errors still surface at
        # .result(); never consumed means the block was never needed.
        self._prefetched[ref.path] = self._executor.submit(
            self._read_block, ref, False)

    def prefetch_window(self, refs) -> None:
        """Submits SEVERAL upcoming page-ins to the background reader
        (round 17: the store-level prefetcher every engine shares —
        the engines widen from one-block-ahead to a window when
        ``async_io`` is on; ``_prefetched`` dedups by path, so
        re-submitting a block already in flight is free)."""
        for ref in refs:
            self.prefetch(ref)

    def fetch_frontier(self, ref: FrontierRef, prefetch=None):
        """Materializes a paged-out block (``page_in``), consuming any
        prefetched read, deleting the stash file, and queueing the next
        prefetch — ``prefetch`` is one ref or a window of them."""
        fut = self._prefetched.pop(ref.path, None)
        if fut is not None:
            # The injected-fault point the reader thread skipped.
            self._faults.crash("page_in_torn", self._tracer(),
                               path=ref.path)
            block = fut.result()
            self._prefetch_hits += 1
        else:
            block = self._read_block(ref)
        try:
            os.unlink(ref.path)
        except OSError:
            pass
        with self._lock:
            self._frontier_bytes = max(0,
                                       self._frontier_bytes - ref.nbytes)
            self._page_ins += 1
        self._event("page_in", tier="disk", kind="frontier",
                    rows=int(ref.rows), bytes=int(ref.nbytes))
        # A tier shrank: mark the reset point for the lint's
        # monotonicity window.
        self._event("pressure", tier="disk", used=int(self.cold_bytes),
                    budget=int(self.host_budget or 0))
        if isinstance(prefetch, (list, tuple)):
            self.prefetch_window(prefetch)
        else:
            self.prefetch(prefetch)
        return block

    def load_ref(self, ref: FrontierRef):
        """Non-consuming read of a paged-out block (checkpoint
        snapshots need the rows but the queue keeps the ref)."""
        return self._read_block(ref)

    # -- Arena-span accounting (the fused engines' device->host tier) -----

    def note_arena_span(self, rows: int, nbytes: int) -> None:
        """Records one fused-engine arena-span spill: the expanded
        prefix left the device arena for the host parent log (the warm
        tier for arena data)."""
        with self._lock:
            self._arena_span_spills += 1
            self._arena_span_rows += int(rows)
            self._arena_span_bytes += int(nbytes)
            self._spill_bytes += int(nbytes)
            self._spills["host"] += 1
            self._host_high_water = max(
                self._host_high_water,
                self.warm_bytes + self._arena_span_bytes)
        self._event("spill", tier="host", kind="arena_span",
                    rows=int(rows), bytes=int(nbytes))

    def note_device_pressure(self, used: int, budget: int) -> None:
        """Records that a device structure had to exceed its budget
        (nothing left to spill) — the postmortem breadcrumb."""
        self._event("pressure", tier="device", used=int(used),
                    budget=int(budget))

    # -- Checkpoint integration (format v5) --------------------------------

    def warm_fps(self) -> np.ndarray:
        """Every warm fingerprint (the snapshot's visited section
        carries hot + warm; cold travels by reference)."""
        with self._lock:
            arrs = [a for a in self._warm if a is not None and len(a)]
        if not arrs:
            return np.zeros(0, np.uint64)
        return np.concatenate(arrs)

    def checkpoint_refs(self) -> Optional[dict]:
        """The v5 header section: cold segments by content hash — a
        checkpoint of a spilled run moves only hot+warm bytes."""
        with self._lock:
            if not self._cold:
                return None
            cold = []
            for p, part in sorted(self._cold.items()):
                ref = {"partition": p,
                       "file": os.path.basename(part.path),
                       "sha": part.sha, "rows": part.rows}
                # A segment attached from a previous checkpoint may
                # live OUTSIDE this store's segment_dir (a resume
                # under a different tier_dir): record its real home,
                # or a second-generation resume could not find it.
                part_dir = os.path.dirname(part.path)
                if part_dir and part_dir != self.segment_dir:
                    ref["dir"] = part_dir
                cold.append(ref)
            return {"segment_dir": self.segment_dir, "cold": cold}

    def attach_refs(self, refs: dict, base_dir: Optional[str] = None):
        """Resume: re-attaches the cold segments a v5 checkpoint
        references, verifying CRCs and content hashes; a current file
        that fails falls back to its rotation predecessor when THAT
        matches the referenced hash. Returns the attached row count."""
        from ..checkpoint_format import (PREV_SUFFIX, content_hash,
                                         verify_file)

        search = [d for d in (refs.get("segment_dir"), base_dir,
                              self.segment_dir) if d]
        attached = 0
        for ref in refs.get("cold", ()):
            p = int(ref["partition"])
            want = str(ref["sha"])
            found = None
            # Per-ref home first (a segment inherited across resumes
            # under a different tier_dir), then the shared dirs.
            ref_dir = ref.get("dir")
            dirs = ([ref_dir] if ref_dir else []) + search
            for d in dirs:
                for cand in (os.path.join(d, ref["file"]),
                             os.path.join(d, ref["file"]) + PREV_SUFFIX):
                    if not os.path.exists(cand):
                        continue
                    try:
                        verify_file(cand)
                        fps = map_segment_visited(cand)
                        if content_hash(np.asarray(fps)) == want:
                            found = _ColdPart(cand, fps, want)
                            break
                    except ValueError:
                        continue
                if found is not None:
                    break
            if found is None:
                raise ValueError(
                    f"checkpoint references cold segment "
                    f"{ref['file']!r} (partition {p}, sha {want}) but "
                    "no generation on disk matches — the segment is "
                    "missing or corrupt beyond its rotation "
                    "predecessor")
            with self._lock:
                self._cold[p] = found
            attached += found.rows
        return attached

    def reset(self) -> None:
        """Drops warm/cold/frontier state (restart_from reloads from
        the checkpoint's refs); config and counters survive."""
        with self._lock:
            self._warm = [None] * self._P
            self._cold = {}
            self._prefetched.clear()
            self._spilling.clear()
            self._frontier_bytes = 0

    # -- Telemetry ----------------------------------------------------------

    def gauges(self) -> dict:
        """The per-wave tier gauges (obs schema v6 wave-event keys for
        the host/disk tiers; the engine adds the device tier)."""
        return {
            "tier_host_rows": int(self.warm_rows
                                  + self._arena_span_rows),
            "tier_host_bytes": int(self.warm_bytes
                                   + self._arena_span_bytes),
            "tier_disk_rows": int(self.cold_rows),
            "tier_disk_bytes": int(self.cold_bytes),
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "device_budget": self.device_budget,
                "host_budget": self.host_budget,
                "segment_dir": self.segment_dir,
                "partitions": self._P,
                "host": {"rows": int(self.warm_rows),
                         "bytes": int(self.warm_bytes),
                         "high_water_bytes": int(self._host_high_water)},
                "disk": {"rows": int(self.cold_rows),
                         "bytes": int(self.cold_bytes),
                         "segments": len(self._cold),
                         "spills_in_flight": len(self._spilling),
                         "high_water_bytes": int(self._disk_high_water)},
                "frontier": {"stashed_bytes": int(self._frontier_bytes),
                             "page_ins": int(self._page_ins),
                             "prefetch_hits": int(self._prefetch_hits)},
                "spills": dict(self._spills),
                "spill_bytes": int(self._spill_bytes),
                "probes": int(self._probes),
                "probe_hits": int(self._probe_hits),
                "arena_spans": {"spills": int(self._arena_span_spills),
                                "rows": int(self._arena_span_rows),
                                "bytes": int(self._arena_span_bytes)},
            }


def load_cold_refs(refs: dict, base_dir: Optional[str] = None) -> np.ndarray:
    """Materializes the cold segments a v5 checkpoint references into
    one fingerprint array (the store-less resume path: slower, never
    wrong). Same verification + rotation-predecessor fallback as
    :meth:`TieredStore.attach_refs`."""
    tmp = TieredStore()
    tmp.attach_refs(refs, base_dir=base_dir)
    parts = [np.asarray(p.fps, np.uint64)
             for _, p in sorted(tmp._cold.items())]
    return np.concatenate(parts) if parts else np.zeros(0, np.uint64)


def store_from_config(*, device_bytes=None, host_bytes=None,
                      segment_dir=None, n_partitions=None, owner=None,
                      prefix: str = "", meta=None):
    """The store factory every engine uses: explicit kwargs override
    the ``STpu_TIER_*`` environment knobs; nothing configured means the
    shared ``NULL_STORE`` (one attribute check per wave)."""
    device_bytes = (_parse_bytes(os.environ.get(TIER_DEVICE_ENV))
                    if device_bytes is None else int(device_bytes))
    host_bytes = (_parse_bytes(os.environ.get(TIER_HOST_ENV))
                  if host_bytes is None else int(host_bytes))
    segment_dir = (os.environ.get(TIER_DIR_ENV) or None
                   if segment_dir is None else segment_dir)
    if device_bytes is None and host_bytes is None and not segment_dir:
        return NULL_STORE
    return TieredStore(
        device_budget=device_bytes, host_budget=host_bytes,
        segment_dir=segment_dir,
        n_partitions=int(n_partitions) if n_partitions else 16,
        owner=owner, prefix=prefix, meta=meta)

"""Stable 64-bit state fingerprinting.

Counterpart of the reference's keyed stable hashing (`src/lib.rs:302-344`):
states are deduplicated, paths are encoded, and explorer URLs are formed
purely from 64-bit fingerprints, so the hash must be stable across
processes, runs, and machines (CPython's builtin ``hash`` is randomized per
process and therefore unusable). We hash a canonical type-tagged byte
encoding with keyed blake2b, which runs at C speed in CPython.

Unordered collections (``set``/``frozenset``/``dict``) are hashed
order-insensitively by hashing each element independently and feeding the
*sorted* element digests into the outer hash, mirroring the reference's
``HashableHashSet``/``HashableHashMap`` semantics (`src/util.rs:123-144`).

The same encoding doubles as the host-side reference implementation for the
device fingerprint kernel: the TPU engine hashes *encoded state vectors*
with a matching construction so host and device agree on identity.
"""

from __future__ import annotations

import struct
from dataclasses import fields, is_dataclass
from enum import Enum
from hashlib import blake2b
from typing import Any, Callable

__all__ = [
    "fingerprint",
    "fingerprint_bytes",
    "stable_encode",
    "register_encoder",
]

_KEY = b"stateright-tpu.v1"
_MASK64 = (1 << 64) - 1

# Type tags for the canonical encoding. Distinct tags keep e.g. 1 and True
# and "1" from colliding.
_T_NONE = b"\x00"
_T_FALSE = b"\x01"
_T_TRUE = b"\x02"
_T_INT = b"\x03"
_T_FLOAT = b"\x04"
_T_STR = b"\x05"
_T_BYTES = b"\x06"
_T_SEQ = b"\x07"
_T_SET = b"\x08"
_T_MAP = b"\x09"
_T_OBJ = b"\x0a"
_T_ENUM = b"\x0b"
_T_CUSTOM = b"\x0c"
_T_BIGINT = b"\x0d"

_pack_i64 = struct.Struct("<q").pack
_pack_u32 = struct.Struct("<I").pack
_pack_f64 = struct.Struct("<d").pack

# type -> encoder(value, buf) for user-registered types.
_EXTRA_ENCODERS: dict[type, Callable[[Any, bytearray], None]] = {}

# class -> tuple of dataclass field names (cached; dataclasses.fields is slow).
_DC_FIELDS: dict[type, tuple[str, ...]] = {}


def register_encoder(cls: type, encode: Callable[[Any, bytearray], None]) -> None:
    """Registers a canonical-encoding function for a user type.

    ``encode(value, buf)`` must append a deterministic byte encoding of
    ``value`` to ``buf``. Prefer frozen dataclasses, which are supported
    natively, before reaching for this.
    """
    _EXTRA_ENCODERS[cls] = encode


def _encode_int(v: int, buf: bytearray) -> None:
    if -(1 << 63) <= v < (1 << 63):
        buf += _T_INT
        buf += _pack_i64(v)
    else:  # bignum gets its own tag so the encoding stays injective
        nbytes = (v.bit_length() + 8) // 8
        buf += _T_BIGINT + _pack_u32(nbytes) + v.to_bytes(nbytes, "little", signed=True)


def _encode_str(v: str, buf: bytearray) -> None:
    raw = v.encode("utf-8")
    buf += _T_STR + _pack_u32(len(raw)) + raw


def _encode_seq(v, buf: bytearray) -> None:
    buf += _T_SEQ + _pack_u32(len(v))
    for item in v:
        _encode(item, buf)


def _encode_set(v, buf: bytearray) -> None:
    # Order-insensitive: sorted element digests (util.rs:123-144).
    buf += _T_SET + _pack_u32(len(v))
    for digest in sorted(fingerprint_bytes(item) for item in v):
        buf += digest


def _encode_map(v, buf: bytearray) -> None:
    buf += _T_MAP + _pack_u32(len(v))
    for digest in sorted(fingerprint_bytes(kv) for kv in v.items()):
        buf += digest


def _encode(value: Any, buf: bytearray) -> None:
    # Order of checks matters: bool is a subclass of int; Enum members of
    # int-backed enums are ints.
    t = type(value)
    if value is None:
        buf += _T_NONE
    elif t is bool:
        buf += _T_TRUE if value else _T_FALSE
    elif t is int:
        _encode_int(value, buf)
    elif t is str:
        _encode_str(value, buf)
    elif t is tuple or t is list:
        _encode_seq(value, buf)
    elif t is frozenset or t is set:
        _encode_set(value, buf)
    elif t is dict:
        _encode_map(value, buf)
    elif t is float:
        buf += _T_FLOAT + _pack_f64(value)
    elif t is bytes:
        buf += _T_BYTES + _pack_u32(len(value)) + value
    elif isinstance(value, Enum):
        name = t.__qualname__.encode("utf-8")
        member = value.name.encode("utf-8")
        buf += _T_ENUM + _pack_u32(len(name)) + name + _pack_u32(len(member)) + member
    elif t in _EXTRA_ENCODERS:
        qual = t.__qualname__.encode("utf-8")
        buf += _T_CUSTOM + _pack_u32(len(qual)) + qual
        _EXTRA_ENCODERS[t](value, buf)
    elif is_dataclass(value):
        names = _DC_FIELDS.get(t)
        if names is None:
            names = tuple(f.name for f in fields(value))
            _DC_FIELDS[t] = names
        qual = t.__qualname__.encode("utf-8")
        buf += _T_OBJ + _pack_u32(len(qual)) + qual + _pack_u32(len(names))
        for name in names:
            _encode(getattr(value, name), buf)
    elif isinstance(value, tuple):  # namedtuple and tuple subclasses
        buf += _T_SEQ + _pack_u32(len(value))
        for item in value:
            _encode(item, buf)
    elif isinstance(value, int):  # int subclasses, e.g. actor Id
        _encode_int(int(value), buf)
    elif isinstance(value, str):
        _encode_str(value, buf)
    elif isinstance(value, (list, frozenset, set, dict)):
        # A subclass that redefines equality (e.g. OrderedDict's
        # order-sensitive __eq__) would fingerprint-collide values its own
        # __eq__ distinguishes; require an explicit encoder for those.
        if type(value).__eq__ not in (
                list.__eq__, set.__eq__, frozenset.__eq__, dict.__eq__):
            raise TypeError(
                f"cannot fingerprint {type(value).__qualname__}: it "
                "overrides __eq__ with non-standard semantics; use "
                "register_encoder or __fingerprint__")
        if isinstance(value, list):
            _encode_seq(value, buf)
        elif isinstance(value, dict):
            _encode_map(value, buf)
        else:
            _encode_set(value, buf)
    else:
        custom = getattr(value, "__fingerprint__", None)
        if custom is not None:
            qual = t.__qualname__.encode("utf-8")
            buf += _T_CUSTOM + _pack_u32(len(qual)) + qual
            _encode(custom(), buf)
        else:
            raise TypeError(
                f"cannot fingerprint value of type {t.__module__}.{t.__qualname__}; "
                "use a frozen dataclass, builtin container, Enum, or define "
                "__fingerprint__()/register_encoder"
            )


def stable_encode(value: Any) -> bytes:
    """Returns the canonical byte encoding used for fingerprinting."""
    buf = bytearray()
    _encode(value, buf)
    return bytes(buf)


def fingerprint_bytes(value: Any) -> bytes:
    """Returns the 8-byte stable digest of ``value``."""
    buf = bytearray()
    _encode(value, buf)
    return blake2b(bytes(buf), digest_size=8, key=_KEY).digest()


def fingerprint(value: Any) -> int:
    """Converts a state to a nonzero 64-bit ``Fingerprint`` (lib.rs:307-311)."""
    fp = int.from_bytes(fingerprint_bytes(value), "big")
    return fp if fp != 0 else 1

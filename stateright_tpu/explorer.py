"""Interactive web explorer: browse a model's state graph while checking.

Counterpart of the reference's `src/checker/explorer.rs:71-240` and its
JSON API contract:

- ``GET /.status`` → ``{done, model, state_count, unique_state_count,
  properties: [[expectation, name, encoded_discovery_path|null], ...],
  recent_path: str|null}`` (`explorer.rs:12-22,133-157`). Expectations
  serialize as ``"Always"``/``"Sometimes"``/``"Eventually"`` — the strings
  the UI classifies on (`ui/app.js:22-38`).
- ``GET /.states/{fp1}/{fp2}/...`` → a JSON list of "state views": for an
  empty fingerprint path, the init states; otherwise every candidate next
  step of the state reached by replaying the fingerprints
  (`Path.final_state`), INCLUDING actions the model ignores (returned with
  no ``state`` field — useful for debugging, `explorer.rs:225-232`).
  Unknown fingerprints → 404.
- ``GET /.metrics`` → live run telemetry in Prometheus exposition
  format (states/s over a sliding sample window, cumulative counts,
  and — when the checker keeps a wave-event dispatch log, i.e. the
  device engines — table load factor, wave cadence, and overflow
  totals). Same metric families as ``tools/trace_export.py --prom``,
  so a dashboard scrapes a live checker and a dead run's trace
  identically; the UI's status line polls it for its throughput
  readout.
- ``/``, ``/app.css``, ``/app.js`` → the static UI under ``ui/``.

Checking runs in background BFS while the server blocks; a ``Snapshot``
visitor captures one recent path, re-armed every 4 seconds by a helper
thread (`explorer.rs:57-88`), surfaced as ``recent_path`` for the UI's
progress line.

**Checking as a service** (round 14): the same server plumbing also
fronts the multi-tenant job service (``stateright_tpu.service``) via
``serve_service``. The job API:

- ``POST /jobs`` → submit ``{model, params?, engine?, knobs?,
  properties?}`` (or ``{resume: "<job id>"}`` to continue a preempted
  job from its checkpoint); returns the job status payload. 400 for a
  rejected spec, 409 for a state conflict.
- ``GET /jobs`` → every job's status; ``GET /jobs/<id>`` → one job
  (live counters while running; counters + property verdicts + shared
  program-cache hits when done).
- ``GET /jobs/<id>/trace`` → the job's obs JSONL stream verbatim
  (lintable by ``tools/trace_lint.py``).
- ``DELETE /jobs/<id>`` → preempt to a resumable checkpoint.
- ``GET /.corpus`` → the model registry listing.
- ``GET /.metrics`` additionally carries the ``stpu_job_*`` families.

**Service-level observability** (round 18, ``obs/hist.py``): when any
of ``STpu_HIST`` / ``STpu_SLO`` / ``STpu_ANOMALY`` is armed,

- ``GET /.metrics`` additionally serves the live latency histogram
  families (``stpu_*_seconds_bucket/_sum/_count``) and the
  ``stpu_slo_*`` surface;
- ``GET /.healthz`` → 200 while every SLO objective holds, 503 the
  moment one is breaching, JSON detail either way (disarmed runs
  always answer 200 ``{"slo": "disarmed"}`` — a health check must not
  require the observability knobs);
- ``GET /.ops`` → the ops-panel JSON: per-participant SLO status,
  recent slow-wave anomalies with attributed cause, and per-series
  p50/p99 latency quantiles (the UI's ops panel polls it).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pprint import pformat
from typing import Optional

from .checker.path import Path
from .checker.visitor import CheckerVisitor
from .fingerprint import fingerprint
from .model import Expectation

__all__ = ["serve", "serve_service", "Explorer", "Snapshot"]

_UI_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ui")

# serde's serialization of the reference's unit enum (`lib.rs:290-300`).
_EXPECTATION_NAMES = {
    Expectation.ALWAYS: "Always",
    Expectation.SOMETIMES: "Sometimes",
    Expectation.EVENTUALLY: "Eventually",
}


class Snapshot(CheckerVisitor):
    """Captures one recently visited path; re-armed periodically so the
    status page shows checking progress (`explorer.rs:57-69`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed = True
        self._actions: Optional[list] = None

    def visit(self, model, path: Path) -> None:
        if not self._armed:  # cheap unlocked check first, like the RwLock
            return
        with self._lock:
            if not self._armed:
                return
            self._armed = False
            self._actions = path.into_actions()

    def rearm(self) -> None:
        with self._lock:
            self._armed = True

    def recent_path(self) -> Optional[str]:
        with self._lock:
            if self._actions is None:
                return None
            return "[" + ", ".join(map(str, self._actions)) + "]"


class Explorer:
    """The request handlers, separated from HTTP plumbing so tests can
    call them directly (the reference tests its handlers the same way,
    `explorer.rs:258-276`)."""

    def __init__(self, checker, snapshot: Optional[Snapshot] = None,
                 service=None):
        self.checker = checker  # None in pure job-service mode
        self.snapshot = snapshot
        #: attached job service (stateright_tpu.service.JobService):
        #: adds the /jobs routes and the stpu_job_* metric families.
        self.service = service
        # (monotonic t, states) samples fed by /.metrics polls; the
        # states/s gauge is the slope across the window, so it tracks
        # the LIVE rate rather than the since-start average.
        self._rate_samples: deque = deque(maxlen=32)
        # Incremental dispatch_log folds: a long device run accumulates
        # tens of thousands of entries, and a 2 s poll cadence must not
        # re-scan them all per scrape — only entries beyond _dlog_seen
        # are folded in (the log is append-only; index reads race-free
        # under the GIL).
        self._dlog_seen = 0
        self._waves_total = 0
        self._overflow_total = 0

    def metrics(self) -> str:
        """Live telemetry in Prometheus exposition format (the
        ``GET /.metrics`` payload)."""
        checker = self.checker
        lines: list = []
        if checker is None:
            # Pure job-service mode: only the stpu_job_* families.
            if self.service is not None:
                lines += self.service.metrics_lines()
            return "\n".join(lines) + "\n"
        now = time.monotonic()
        states = checker.state_count()
        unique = checker.unique_state_count()
        self._rate_samples.append((now, states))
        t0, s0 = self._rate_samples[0]
        rate = (states - s0) / (now - t0) if now > t0 else 0.0
        lines += [
            "# TYPE stpu_states_total counter",
            f"stpu_states_total {states}",
            "# TYPE stpu_unique_states_total counter",
            f"stpu_unique_states_total {unique}",
            "# TYPE stpu_states_per_sec gauge",
            f"stpu_states_per_sec {rate:.1f}",
            "# TYPE stpu_done gauge",
            f"stpu_done {int(bool(checker.is_done()))}",
        ]
        # Wave-event telemetry: present on any checker with a unified
        # dispatch log (the device engines); host checkers just omit
        # these families. Totals fold incrementally — only entries
        # appended since the last scrape are visited.
        dlog = getattr(checker, "dispatch_log", None)
        n = len(dlog) if dlog is not None else 0
        if n:
            for i in range(self._dlog_seen, n):
                e = dlog[i]
                self._waves_total += e.get("waves", 1)
                self._overflow_total += 1 if e.get("overflow") else 0
            self._dlog_seen = n
            last = dlog[n - 1]
            lines += ["# TYPE stpu_waves_total counter",
                      f"stpu_waves_total {self._waves_total}",
                      "# TYPE stpu_overflow_redispatches_total counter",
                      f"stpu_overflow_redispatches_total "
                      f"{self._overflow_total}"]
            if last.get("load_factor") is not None:
                lines += ["# TYPE stpu_table_load_factor gauge",
                          f"stpu_table_load_factor "
                          f"{last['load_factor']}"]
            tail = [dlog[i] for i in range(max(0, n - 9), n)]
            if len(tail) >= 2 and tail[-1]["t"] > tail[0]["t"]:
                cadence = ((tail[-1]["t"] - tail[0]["t"])
                           / (len(tail) - 1))
                lines += ["# TYPE stpu_wave_seconds gauge",
                          f"stpu_wave_seconds {cadence:.4f}"]
        # Tiered-state-store families (schema v6): live per-tier
        # occupancy + spill counters off the engine's store stats
        # (cheap — running aggregates, not the event stream). Host
        # checkers have no store_stats and just omit the families.
        store_fn = getattr(checker, "store_stats", None)
        if callable(store_fn):
            st = store_fn()
            if st.get("enabled"):
                lines.append("# TYPE stpu_tier_rows gauge")
                lines.append("# TYPE stpu_tier_bytes gauge")
                for tier, rows_, bytes_ in (
                        ("device", st.get("device", {}).get("rows"),
                         st.get("device", {}).get("table_bytes")),
                        ("host", st["host"]["rows"],
                         st["host"]["bytes"]),
                        ("disk", st["disk"]["rows"],
                         st["disk"]["bytes"])):
                    if rows_ is not None:
                        lines.append(
                            f'stpu_tier_rows{{tier="{tier}"}} {rows_}')
                    if bytes_ is not None:
                        lines.append(
                            f'stpu_tier_bytes{{tier="{tier}"}} '
                            f"{bytes_}")
                lines += [
                    "# TYPE stpu_tier_spills_total counter",
                    f"stpu_tier_spills_total "
                    f"{sum(st['spills'].values())}",
                    "# TYPE stpu_tier_spill_bytes_total counter",
                    f"stpu_tier_spill_bytes_total {st['spill_bytes']}",
                    "# TYPE stpu_tier_page_ins_total counter",
                    f"stpu_tier_page_ins_total "
                    f"{st['frontier']['page_ins']}",
                ]
                if st.get("resident_ratio") is not None:
                    lines += ["# TYPE stpu_tier_resident_ratio gauge",
                              f"stpu_tier_resident_ratio "
                              f"{st['resident_ratio']}"]
        # Elastic distributed-observability families (schema v5): the
        # coordinator's live straggler aggregates, per-worker. Cheap —
        # elastic_obs reads running aggregates, not the event stream.
        obs_fn = getattr(checker, "elastic_obs", None)
        if callable(obs_fn):
            obs = obs_fn()
            lines += ["# TYPE stpu_elastic_max_wait_share gauge",
                      f"stpu_elastic_max_wait_share "
                      f"{obs.get('max_wait_share', 0.0)}",
                      # Round-18 naming audit: counters end in
                      # ``_total``; the deprecated bare duals shipped
                      # one round and are gone.
                      "# TYPE stpu_elastic_merged_events_total counter",
                      f"stpu_elastic_merged_events_total "
                      f"{obs.get('merged_events', 0)}",
                      "# TYPE stpu_elastic_postmortems_total counter",
                      f"stpu_elastic_postmortems_total "
                      f"{len(obs.get('postmortems', ()))}"]
            for fam, field, mtype in (
                    ("stpu_elastic_worker_wait_share", "wait_share",
                     "gauge"),
                    ("stpu_elastic_worker_states_per_sec", "states_s",
                     "gauge"),
                    ("stpu_elastic_worker_wait_seconds_total", "wait_s",
                     "counter")):
                workers = obs.get("workers", {})
                if workers:
                    lines.append(f"# TYPE {fam} {mtype}")
                    lines += [f'{fam}{{worker="{w}"}} {seg[field]}'
                              for w, seg in workers.items()]
            ages = obs.get("heartbeat_ages", {})
            if ages:
                lines.append(
                    "# TYPE stpu_elastic_heartbeat_age_seconds gauge")
                lines += [f'stpu_elastic_heartbeat_age_seconds'
                          f'{{worker="{w}"}} {age}'
                          for w, age in ages.items()]
        # Round-18 service observability: the foreground checker's
        # live latency histogram families, plus its SLO surface when
        # no service owns that family set.
        wobs = getattr(checker, "_wave_obs", None)
        if wobs is not None and wobs.enabled:
            if wobs.hist is not None:
                from .obs.hist import prometheus_hist_lines

                lines += prometheus_hist_lines(wobs.hist.snapshot())
            if self.service is None:
                slo = wobs.slo_status()
                if slo is not None:
                    from .obs.slo import prometheus_slo_lines

                    lines += prometheus_slo_lines(slo)
        # Continuous-profiler families (schema v13): per compiled
        # program, the XLA cost model + last sampled roofline gauges
        # off the checker's armed WaveProfiler (running aggregates —
        # disarmed checkers omit the families entirely).
        prof = getattr(checker, "_prof", None)
        if prof is not None and prof.enabled:
            from .obs.prof import prometheus_prof_lines

            lines += prometheus_prof_lines(
                prof.stats(), getattr(checker, "_ENGINE_ID", "engine"))
        # Job-service families (schema v7): per-job counters plus the
        # shared program-cache hit/miss totals, when a service shares
        # the server with a foreground checker.
        if self.service is not None:
            lines += self.service.metrics_lines()
        return "\n".join(lines) + "\n"

    # -- Round-18 health / ops surface -------------------------------------

    def _obs_sources(self) -> list:
        """The armed WaveObs facades this server fronts: the job
        service's, then the foreground checker's."""
        out = []
        svc = getattr(self.service, "_obs", None)
        if svc is not None and svc.enabled:
            out.append(svc)
        chk = getattr(self.checker, "_wave_obs", None)
        if chk is not None and chk.enabled:
            out.append(chk)
        return out

    def healthz(self):
        """``GET /.healthz`` → ``(status, payload)``: 200 while every
        armed SLO objective holds, 503 when any is breaching. A server
        with no armed SLO answers 200 (health must not require the
        observability knobs). With an armed overload controller the
        body carries its state (queue depth, shed totals, parked jobs,
        brownout rung) — an external probe sees WHY the service is
        degraded, not just that it is."""
        control = (self.service.control_status()
                   if self.service is not None else None)
        with_slo = [(src, src.slo_status())
                    for src in self._obs_sources()]
        with_slo = [(src, st) for src, st in with_slo if st is not None]
        if not with_slo:
            payload = {"healthy": True, "slo": "disarmed"}
            if control is not None:
                payload["control"] = control
            return 200, payload
        healthy = all(st["healthy"] for _, st in with_slo)
        payload = {
            "healthy": healthy,
            "participants": {src.producer: st for src, st in with_slo}}
        if control is not None:
            payload["control"] = control
        return (200 if healthy else 503), payload

    def ops(self) -> dict:
        """``GET /.ops`` → the live ops-panel payload: per-participant
        SLO status, recent anomalies (cause-attributed slow waves),
        and per-series p50/p99 from the live histograms."""
        from .obs.hist import bucket_quantile

        out: dict = {"healthy": True, "participants": {}}
        for src in self._obs_sources():
            st = src.slo_status()
            hist = {}
            if src.hist is not None:
                for key, data in src.hist.snapshot().items():
                    hist[key] = {
                        "count": data["count"],
                        "p50": bucket_quantile(
                            data["buckets"], data["count"], 0.5),
                        "p99": bucket_quantile(
                            data["buckets"], data["count"], 0.99)}
            out["participants"][src.producer] = {
                "slo": st, "anomalies": src.anomalies(), "hist": hist}
            if st is not None and not st["healthy"]:
                out["healthy"] = False
        # Continuous-profiler panel data (schema v13): the foreground
        # checker's per-program roofline table, when armed.
        prof = getattr(self.checker, "_prof", None)
        if prof is not None and prof.enabled:
            out["prof"] = prof.stats()
        # Overload-controller tile (round 21): admission gate, brownout
        # rung, shed/park/resume totals — when the service is armed.
        if self.service is not None:
            control = self.service.control_status()
            if control is not None:
                out["control"] = control
        return out

    def status(self) -> dict:
        checker = self.checker
        model = checker.model()
        return {
            "done": checker.is_done(),
            "model": type(model).__module__ + "." + type(model).__qualname__,
            "state_count": checker.state_count(),
            "unique_state_count": checker.unique_state_count(),
            "properties": [
                [_EXPECTATION_NAMES[p.expectation], p.name,
                 (lambda d: d.encode() if d else None)(
                     checker.discovery(p.name))]
                for p in model.properties()],
            "recent_path":
                self.snapshot.recent_path() if self.snapshot else None,
        }

    def states(self, fingerprints_str: str):
        """Returns (http_status, payload). ``fingerprints_str`` is the raw
        URL remainder after ``/.states`` (e.g. ``/123/456``)."""
        model = self.checker.model()
        s = fingerprints_str.rstrip("/")
        parts = s.split("/")
        fps = []
        for part in parts[1:] if parts and parts[0] == "" else parts:
            try:
                fps.append(int(part))
            except ValueError:
                return 404, f"Unable to parse fingerprints {s}"

        views = []
        if not fps:
            for state in model.init_states():
                views.append(self._view(model, None, None, state,
                                        [(state, None)]))
            return 200, views

        # Replay the prefix ONCE; each successor row extends it by one
        # step rather than re-replaying from init per row.
        try:
            prefix = Path.from_fingerprints(model, fps)
        except Exception:
            return 404, f"Unable to find state following fingerprints {s}"
        prefix_pairs = prefix.into_vec()
        last_state = prefix_pairs[-1][0]
        actions: list = []
        model.actions(last_state, actions)
        for action in actions:
            outcome = model.format_step(last_state, action)
            state = model.next_state(last_state, action)
            if state is None:
                # Ignored actions are still returned, minus the state —
                # useful for debugging (`explorer.rs:225-230`).
                views.append({"action": model.format_action(action)})
            else:
                pairs = (prefix_pairs[:-1]
                         + [(last_state, action), (state, None)])
                views.append(self._view(
                    model, model.format_action(action), outcome, state,
                    pairs))
        return 200, views

    def _view(self, model, action, outcome, state, path_pairs) -> dict:
        view = {}
        if action is not None:
            view["action"] = action
        if outcome is not None:
            view["outcome"] = outcome
        view["state"] = pformat(state)
        view["fingerprint"] = str(fingerprint(state))
        try:
            svg = model.as_svg(Path(path_pairs))
        except Exception:
            svg = None
        if svg is not None:
            view["svg"] = svg
        return view


def _job_errors(call):
    """Maps service exceptions to HTTP (status, payload, headers): a
    rejected spec is the tenant's fault (400), a state conflict 409, a
    full queue or controller shed 429 (admission control — retryable,
    and a shed carries ``Retry-After`` from the observed drain rate
    plus a structured body with the machine-readable reason), an
    unknown id 404 — anything else is a real 500."""
    from .service import JobConflict, JobError, JobQueueFull, JobShed

    try:
        return 200, call(), None
    except JobError as e:
        return 400, str(e), None
    except JobShed as e:
        # RFC 7231 Retry-After is integer delta-seconds; round UP so
        # an obedient client never retries before the queue drained.
        return 429, {"error": str(e), "reason": e.reason,
                     "retry_after_s": e.retry_after_s}, \
            {"Retry-After": str(max(1, math.ceil(e.retry_after_s)))}
    except JobQueueFull as e:
        return 429, str(e), None
    except JobConflict as e:
        return 409, str(e), None
    except KeyError as e:
        return 404, str(e), None
    except Exception as e:  # noqa: BLE001 — the server must answer
        return 500, f"{type(e).__name__}: {e}", None


class _Handler(BaseHTTPRequestHandler):
    explorer: Explorer = None  # set per server class

    def do_GET(self):  # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        service = self.explorer.service
        checker = self.explorer.checker
        if path == "/.metrics":
            self._text(200, self.explorer.metrics(),
                       content_type="text/plain; version=0.0.4")
        elif path == "/.healthz":
            status, payload = self.explorer.healthz()
            self._json(status, payload)
        elif path == "/.ops":
            self._json(200, self.explorer.ops())
        elif service is not None and path == "/jobs":
            self._json(200, service.jobs())
        elif service is not None and path == "/.corpus":
            self._json(200, service.registry.describe())
        elif service is not None and path.startswith("/jobs/"):
            self._job_get(service, path[len("/jobs/"):])
        elif checker is None:
            self._text(404, "not found (job-service mode: use /jobs)")
        elif path == "/.status":
            self._json(200, self.explorer.status())
        elif path.startswith("/.states"):
            status, payload = self.explorer.states(path[len("/.states"):])
            if status == 200:
                self._json(200, payload)
            else:
                self._text(status, payload)
        elif path in ("/", "/index.htm", "/index.html"):
            self._file("index.htm", "text/html")
        elif path == "/app.css":
            self._file("app.css", "text/css")
        elif path == "/app.js":
            self._file("app.js", "application/javascript")
        else:
            self._text(404, "not found")

    def _job_get(self, service, rest: str) -> None:
        job_id, _, sub = rest.partition("/")
        try:
            if sub == "trace":
                # The job's obs JSONL stream, verbatim — the file the
                # engine + the service lifecycle events append to.
                with open(service.trace_file(job_id), "rb") as f:
                    body = f.read()
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif sub == "":
                self._json(200, service.status(job_id))
            else:
                self._text(404, f"unknown job route {sub!r}")
        except KeyError as e:
            self._text(404, str(e))
        except OSError as e:
            self._text(404, f"trace unavailable: {e}")

    def do_POST(self):  # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        service = self.explorer.service
        if service is None or path != "/jobs":
            self._text(404, "not found")
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            spec = json.loads(self.rfile.read(length) or b"{}")
        except ValueError as e:
            self._text(400, f"invalid JSON body: {e}")
            return
        self._job_reply(_job_errors(lambda: service.submit(spec)))

    def do_DELETE(self):  # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        service = self.explorer.service
        if service is None or not path.startswith("/jobs/"):
            self._text(404, "not found")
            return
        job_id = path[len("/jobs/"):].rstrip("/")
        self._job_reply(_job_errors(lambda: service.preempt(job_id)))

    def _job_reply(self, result) -> None:
        status, payload, headers = result
        if status == 200 or isinstance(payload, dict):
            self._json(status, payload, headers=headers)
        else:
            self._text(status, payload, headers=headers)

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, status: int, payload, headers=None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, message: str,
              content_type: str = "text/plain", headers=None) -> None:
        body = message.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, value)
        self.end_headers()
        self.wfile.write(body)

    def _file(self, name: str, content_type: str) -> None:
        try:
            with open(os.path.join(_UI_DIR, name), "rb") as f:
                body = f.read()
        except OSError:
            self._text(404, f"missing UI file {name}")
            return
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _parse_address(addresses) -> tuple:
    if isinstance(addresses, tuple):
        return addresses
    host, _, port = str(addresses).rpartition(":")
    return (host or "localhost", int(port))


def serve_service(service=None, addresses=("127.0.0.1", 0),
                  block: bool = True, checker=None, snapshot=None,
                  **service_kwargs):
    """Serves the multi-tenant job API (``stateright_tpu.service``)
    over the explorer's HTTP plumbing. ``service=None`` creates a
    :class:`~stateright_tpu.service.JobService` with
    ``service_kwargs`` (workers, data_dir, registry, ...). An optional
    foreground ``checker`` keeps the classic explorer routes alive on
    the same server. With ``block=False`` returns
    ``(service, server)`` — call ``server.shutdown()`` and
    ``service.close()`` when finished."""
    from .service import JobService

    if service is None:
        service = JobService(**service_kwargs)
    explorer = Explorer(checker, snapshot, service=service)
    handler = type("BoundHandler", (_Handler,), {"explorer": explorer})
    server = ThreadingHTTPServer(_parse_address(addresses), handler)
    host, port = server.server_address[:2]
    print(f"Serving checks. binding={host}:{port} "
          f"corpus={service.registry.names()}")
    if not block:
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return service, server
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        service.close()
    return service


def serve(checker_builder, addresses, block: bool = True):
    """Spawns background BFS checking with a snapshot visitor, then serves
    the explorer HTTP API (`explorer.rs:71-129`). With ``block=False``
    (for tests/embedding) returns ``(checker, server)`` — call
    ``server.shutdown()`` when finished."""
    snapshot = Snapshot()

    def rearm_loop():
        while True:
            time.sleep(4)
            snapshot.rearm()

    threading.Thread(target=rearm_loop, daemon=True).start()
    checker = checker_builder.visitor(snapshot).spawn_bfs()

    explorer = Explorer(checker, snapshot)
    handler = type("BoundHandler", (_Handler,), {"explorer": explorer})
    server = ThreadingHTTPServer(_parse_address(addresses), handler)
    host, port = server.server_address[:2]
    print(f"Exploring. binding={host}:{port}")
    if not block:
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return checker, server
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
    return checker

"""Cross-job wave multiplexing: many concurrent checks, one device wave.

The round-14 job service runs each admitted job on its own engine
instance — correct, but wasteful at the service's target shape: many
concurrent SMALL jobs of the same corpus shape (same canonical
model/params/engine/knob cache key), each dispatching half-empty waves
that leave the device idle between host round-trips. The multiplexer
(round 16) admits same-shape jobs as *tenants* of one shared
``MuxGroup``: each group wave draws a batch from several tenants'
frontiers at once — packed rows carry a trailing tenant lane
(``tpu/packing.py``: ``PackedLayout.with_tenant_lane``) — and one
``build_mux_wave`` dispatch expands all of them, splitting the stats
vector per job via segment sums over that lane.

Isolation inside the shared visited table is by fingerprint tagging:
each tenant admission draws a unique 64-bit tag (splitmix-mixed
admission counter — NEVER reused, so a departing tenant's residual
entries can't falsely collide with a newcomer's states) and the wave
XORs dedup fingerprints with the owning tenant's tag before probing.
One open-addressing table therefore holds per-(job, state) entries and
tenants never dedup against each other; the added collision hazard is
the same 2^-64 class as the existing fingerprint/sentinel hazard.
Path fingerprints stay untagged, so parent maps, discoveries, and
checkpoints read real state fingerprints.

Bit-identity with solo runs is the load-bearing property (the
differential suite in ``tests/test_mux.py`` pins it): a tenant's rows
are assembled contiguously in its own queue order, the wave's
first-occurrence dedup and stable compaction preserve that order, and
cross-tenant fingerprints never collide — so each tenant's counts,
verdicts, discoveries, parents, and checkpoint bytes are exactly what
its solo engine would produce. The scope caveat is the same one the
cross-B parity suite carries: identity of the FULL surfaces holds for
runs that exhaust their frontier (or preempt-resume chains thereof);
an early-stopped run (``target_state_count``) stops at wave
granularity, so the service only multiplexes jobs without one.

Honesty notes (single-host scope):

- The group runs in ONE process against one device; this is service
  throughput for many small jobs, not distributed checking (the
  sharded/elastic engines own that axis).
- Tenant admission and table growth seed the device table through a
  host rebuild of the tagged fingerprint set (O(live states)) — cheap
  at the many-small-jobs target shape, and a wave-boundary operation,
  never per-wave.
- Mux jobs bypass the resilience ``Supervisor`` (a tenant failure
  fails that job; preempt/resume is the recovery story), and the
  multiplexer keeps the per-wave host boundary — no fused multi-wave
  device loop (``_MUX_CAPABLE`` is False on the fused engine).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..checker.path import Path
from ..model import Expectation
from ..obs.hist import wave_obs_from_env
from ..obs.tracer import tracer_from_env
from .control import NULL_CONTROL
from ..tpu.engine import (batch_bucket_ladder, build_mux_wave,
                          host_table_insert, pick_bucket)
from ..tpu.hashing import SENTINEL, host_fp64
from ..tpu.packing import compile_layout

__all__ = ["MuxGroup", "TenantHandle", "MUX_KNOBS"]

#: Knobs a job may set and still be mux-eligible: pure performance
#: schedules shared by the whole group. Anything else (symmetry,
#: tiered-store budgets, ``target_state_count`` — whose early stop is
#: wave-granular and therefore composition-dependent) routes the job to
#: a solo engine.
MUX_KNOBS = frozenset({"batch_size", "max_batch_size", "table_capacity",
                       "checkpoint_every_waves", "async_io"})

_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """The tenant-tag mixer (splitmix64 finalizer): admission counter
    in, well-distributed 64-bit tag out."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


class _Tenant:
    """One admitted job's state inside a group. All mutable fields are
    guarded by the group's condition variable."""

    __slots__ = ("id", "slot", "tag", "ckpt_path", "tracer", "pending",
                 "parents", "parent_log", "parents_consumed",
                 "visited_blocks", "state_count", "unique_count",
                 "discoveries", "preempt_requested", "preempted",
                 "done", "error", "prog_hits", "prog_misses", "waves")

    def __init__(self, job_id: str, slot: int, tag: int,
                 ckpt_path: Optional[str], tracer):
        self.id = job_id
        self.slot = slot
        self.tag = tag
        self.ckpt_path = ckpt_path
        self.tracer = tracer
        self.pending: deque = deque()
        self.parents: Dict[int, Optional[int]] = {}
        self.parent_log: List = []
        self.parents_consumed = 0
        #: untagged dedup fingerprints, one block per producing wave
        #: (seed block first) — concatenated, this IS the tenant's
        #: visited set, which is how checkpoints and table rebuilds
        #: never need to untag a table scan.
        self.visited_blocks: List[np.ndarray] = []
        self.state_count = 0
        self.unique_count = 0
        self.discoveries: Dict[str, int] = {}
        self.preempt_requested = False
        self.preempted = False
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.prog_hits = 0
        self.prog_misses = 0
        self.waves = 0

    def rows_queued(self) -> int:
        return sum(len(b[1]) for b in self.pending)

    def rows_visited(self) -> int:
        return sum(len(b) for b in self.visited_blocks)


#: slot placeholder between reservation and seeded admission.
_RESERVED = object()


class TenantHandle:
    """The checker-shaped façade the job service holds for one tenant:
    the same count/discovery/preempt/join surface ``TpuBfsChecker``
    exposes, backed by the shared group."""

    def __init__(self, group: "MuxGroup", tenant: _Tenant):
        self._g = group
        self._t = tenant

    @property
    def preempted(self) -> bool:
        return self._t.preempted

    def state_count(self) -> int:
        with self._g._cv:
            return self._t.state_count

    def unique_state_count(self) -> int:
        with self._g._cv:
            return self._t.unique_count

    def discoveries(self) -> Dict[str, Path]:
        return self._g._tenant_discoveries(self._t)

    def preempt(self) -> None:
        with self._g._cv:
            self._t.preempt_requested = True
            self._g._cv.notify_all()

    def join(self) -> "TenantHandle":
        self._t.done.wait()
        if self._t.error is not None:
            raise self._t.error
        return self

    def is_done(self) -> bool:
        return self._t.done.is_set()

    def scheduler_stats(self) -> dict:
        g = self._g
        with g._cv:
            return {
                "engine": "mux",
                "jobs_in_group": len(g._live),
                "jobs_in_group_high_water": g._live_high_water,
                "group_waves": g._wave_count,
                "program_cache": {
                    "shared": g._prog_cache is not None,
                    "hits": self._t.prog_hits + g._prog_hits,
                    "misses": self._t.prog_misses + g._prog_misses,
                },
                "async_io": g._aio.stats(),
                "slo": g._wave_obs.slo_status(),
                "anomalies": g._wave_obs.anomalies(),
            }


class MuxGroup:
    """One shared engine multiplexing same-shape jobs' waves.

    The group owns a worker thread running the wave loop; tenants join
    at wave boundaries (``admit``), drain to their own checkpoint
    generation on preempt, and retire individually on completion
    without disturbing co-scheduled jobs. When the last tenant leaves
    the group closes itself (the service then builds a fresh group for
    the next same-shape arrival)."""

    def __init__(self, model, *, knobs: Optional[dict] = None,
                 program_cache=None, program_key: Optional[tuple] = None,
                 trace_path: Optional[str] = None, max_jobs: int = 8,
                 control=None):
        knobs = dict(knobs or {})
        bad = set(knobs) - MUX_KNOBS
        if bad:
            raise ValueError(f"knobs {sorted(bad)} are not mux-eligible")
        self._model = model
        dm = model.device_model()
        self._dm = dm
        self._properties = model.properties()
        if len(self._properties) > 32:
            raise NotImplementedError("at most 32 properties on device")
        device_props = dm.device_properties()
        self._prop_fns = [device_props.get(p.name)
                          for p in self._properties]
        self._ebits_all = 0
        self._eventually_idx: List[int] = []
        for i, p in enumerate(self._properties):
            if p.expectation is Expectation.EVENTUALLY:
                self._ebits_all |= 1 << i
                self._eventually_idx.append(i)

        self._B = max(1, int(knobs.get("batch_size", 1024)))
        self._buckets = batch_bucket_ladder(
            self._B, knobs.get("max_batch_size"))
        self._B_max = self._buckets[-1]
        self._F = dm.max_fanout
        self._W = dm.state_width
        lane_bits = getattr(dm, "lane_bits", lambda: None)()
        self._base = compile_layout(lane_bits, self._W)
        self._pack_on = (jax.default_backend() != "cpu"
                         and self._base.packs)
        #: storage width of a MODEL row (what solo engines store and
        #: what tenant checkpoints carry).
        self._Wrow = self._base.packed_width if self._pack_on else self._W
        #: the tenant-lane layout the wave program runs on; mux rows
        #: are one word wider (``packed[..., :-1]`` is exactly the solo
        #: storage row). With packing OFF the storage row is the raw
        #: register row, so the tenant lane derives from the IDENTITY
        #: layout — the model's bitfield plan must not leak into where
        #: the wave program finds the model part / tenant word.
        self._mux = (compile_layout(lane_bits, self._W) if self._pack_on
                     else compile_layout(None, self._W)
                     ).with_tenant_lane()
        self._Wmux = self._Wrow + 1
        self._ckpt_every = max(1, int(knobs.get(
            "checkpoint_every_waves", 64)))
        self._capacity = 1 << max(
            12, (int(knobs.get("table_capacity", 1 << 16)) - 1)
            .bit_length())

        self._J = max(1, int(max_jobs))
        self._prog_cache = program_cache if program_key is not None \
            else None
        self._prog_key = tuple(program_key) if program_key is not None \
            else None
        self._prog_hits = 0
        self._prog_misses = 0
        self._programs: dict = {}
        self._compile_dirty = False

        self._cv = threading.Condition()
        self._slots: List = [None] * self._J
        self._tags = np.zeros(self._J, np.uint64)
        self._tag_dev = jnp.asarray(self._tags)
        self._used_tags: set = set()
        self._live: List[_Tenant] = []
        self._joining: List[_Tenant] = []
        self._adm_seq = 0
        self._rr = 0
        self._ever = False
        self._stop = False
        self._closed = False
        self._live_rows = 0
        self._dead_rows = 0
        self._live_high_water = 0
        self._states_total = 0
        self._unique_total = 0
        self._wave_count = 0
        self._visited = None  # built by the first _rebuild_table
        # Round 17: background writer shared by tenant checkpoint
        # generations and the incremental visited-table folds. Knob-off
        # keeps the inline SyncWriter (submit == call, joins are
        # no-ops) — the pre-round-17 wave loop, unchanged.
        from ..io.async_io import writer_from_config

        self._aio = writer_from_config(knobs.get("async_io"),
                                       name="stpu-aio-mux")
        #: host mirror of the device table, kept current by per-wave
        #: background folds of each tenant's novel keys (async only) so
        #: a joiners-only boundary can skip the full host rebuild.
        self._shadow: Optional[np.ndarray] = None

        self._trace_path = trace_path
        self._tracer = tracer_from_env("mux", path=trace_path, meta={
            "model": type(model).__name__,
            "batch_size": self._B,
            "bucket_ladder": list(self._buckets),
            "table_capacity": self._capacity,
            "max_jobs": self._J,
            "state_width": self._W})
        #: service observability (obs/hist.py): group-wave latency
        #: histograms / SLO / anomaly attribution over the TOTAL line's
        #: entry (per-tenant latency belongs to the job service).
        self._wave_obs = wave_obs_from_env("mux")
        #: round-21 overload controller: armed, it adapts the per-wave
        #: batch budget from observed wave latency (and the brownout
        #: ladder) in `_wave`; disarmed NULL_CONTROL keeps the fixed
        #: B_max cap — the pre-round-21 allocation, unchanged.
        self._control = control if control is not None else NULL_CONTROL
        self._wave_t0: Optional[float] = None

        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- Admission ---------------------------------------------------------

    def admit(self, job_id: str, *, trace_path: Optional[str] = None,
              checkpoint_path: Optional[str] = None,
              resume_from: Optional[str] = None
              ) -> Optional[TenantHandle]:
        """Admits one job as a tenant; returns its handle, or ``None``
        when the group cannot take it (every slot busy, or the group
        already closed) — the service then opens a fresh group. Host
        seeding (init-state encode or checkpoint load) runs outside the
        group lock; the wave loop integrates the tenant's fingerprints
        into the shared table at its next wave boundary."""
        with self._cv:
            if self._closed or self._stop:
                return None
            free = [s for s in range(self._J) if self._slots[s] is None]
            if not free:
                return None
            slot = free[0]
            self._slots[slot] = _RESERVED
            self._adm_seq += 1
            tag = _splitmix64(self._adm_seq)
            while tag in self._used_tags or tag == 0:
                self._adm_seq += 1
                tag = _splitmix64(self._adm_seq)
            self._used_tags.add(tag)
        try:
            tenant = self._build_tenant(job_id, slot, tag, trace_path,
                                        checkpoint_path, resume_from)
        except BaseException:
            with self._cv:
                self._slots[slot] = None
            raise
        # Per-admission shared-program resolution: the group builds the
        # wave program once, but EVERY admission resolves it through
        # the process-wide cache so the Nth same-shape job records a
        # genuine hit — the same amortization signal a solo engine's
        # scheduler_stats carries.
        self._admission_program(tenant)
        with self._cv:
            if self._closed or self._stop:
                # The group drained and closed while we seeded; the
                # caller opens a fresh group.
                self._slots[slot] = None
                tenant.tracer.close()
                return None
            self._slots[slot] = tenant
            self._tags[slot] = np.uint64(tag)
            self._joining.append(tenant)
            self._cv.notify_all()
        return TenantHandle(self, tenant)

    def _build_tenant(self, job_id, slot, tag, trace_path, ckpt_path,
                      resume_from) -> _Tenant:
        tracer = tracer_from_env("mux", path=trace_path, meta={
            "model": type(self._model).__name__, "job": job_id,
            "mux_slot": slot})
        t = _Tenant(job_id, slot, tag, ckpt_path, tracer)
        if resume_from is not None:
            self._load_tenant_checkpoint(t, resume_from)
            return t
        model, dm = self._model, self._dm
        init_states = [s for s in model.init_states()
                       if model.within_boundary(s)]
        t.state_count = len(init_states)
        seen: set = set()
        vecs: List[np.ndarray] = []
        fps: List[int] = []
        for s in init_states:
            vec = np.asarray(dm.encode(s), np.uint32)
            fp = host_fp64(vec)
            if fp in seen:
                continue
            seen.add(fp)
            vecs.append(vec)
            fps.append(fp)
        fps_arr = np.array(fps, np.uint64)
        if vecs:
            seed = np.stack(vecs).astype(np.uint32)
            t.pending.append((
                self._rows_with_tag(seed, slot), fps_arr,
                np.full(len(fps), self._ebits_all, np.uint32)))
        t.unique_count = len(fps)
        t.parent_log = [(fps_arr, None)]
        t.visited_blocks = [fps_arr]
        return t

    def _load_tenant_checkpoint(self, t: _Tenant, path: str) -> None:
        """Mirror of the solo engine's ``_load_checkpoint``: restores
        counts/discoveries/pending/parents and the visited set from a
        (solo- or mux-written — they are byte-identical) snapshot."""
        from ..checkpoint_format import (load_checkpoint, pending_rows,
                                         validate_header)

        with load_checkpoint(path) as data:
            header = validate_header(
                data, model_name=type(self._model).__name__,
                state_width=self._W, use_symmetry=False)
            t.state_count = int(header["state_count"])
            t.unique_count = int(header["unique_count"])
            t.discoveries = {k: int(v) for k, v
                             in header["discoveries"].items()}
            vecs = pending_rows(data, header, self._W)
            if self._pack_on:
                self._base.check_fits(vecs)
            fps = np.asarray(data["pending_fps"], np.uint64)
            ebits = np.asarray(data["pending_ebits"], np.uint32)
            if len(fps):
                t.pending.append((self._rows_with_tag(vecs, t.slot),
                                  fps, ebits))
            t.parents = {
                int(c): (None if r else int(p))
                for c, p, r in zip(data["parent_child"].tolist(),
                                   data["parent_parent"].tolist(),
                                   data["parent_rooted"].tolist())}
            visited = np.asarray(data["visited"], np.uint64)
            refs = header.get("store")
            if refs:
                # A snapshot of a tiered-store run: materialize the
                # cold segments (the mux has no store; slower, never
                # wrong — the solo engine's no-store branch).
                from ..store.tiered import load_cold_refs

                cold = load_cold_refs(refs, base_dir=os.path.dirname(
                    os.path.abspath(path)))
                if len(cold):
                    visited = np.concatenate([visited, cold])
            t.visited_blocks = [visited]

    def _rows_with_tag(self, model_rows: np.ndarray,
                       slot: int) -> np.ndarray:
        """UNPACKED model rows -> storage rows with the tenant word."""
        model_rows = np.asarray(model_rows, np.uint32)
        tags = np.full(len(model_rows), slot, np.uint32)
        if self._pack_on:
            self._base.check_fits(model_rows)
            return self._mux.pack_tenant_np(model_rows, tags)
        return np.concatenate([model_rows, tags[:, None]], axis=1)

    # -- Shared wave program ----------------------------------------------

    def _shared_key(self, bucket: int) -> tuple:
        return (self._prog_key, "mux", self._pack_on, False, self._J,
                bucket, self._capacity)

    def _build_program(self, bucket: int):
        return build_mux_wave(self._dm, bucket, self._capacity,
                              self._prop_fns, False, max_jobs=self._J,
                              layout=self._mux, pack_on=self._pack_on)

    def _admission_program(self, tenant: _Tenant) -> None:
        if self._prog_cache is None:
            return
        _, hit = self._prog_cache.get_or_build(
            self._shared_key(self._B), lambda: self._build_program(
                self._B))
        if hit:
            tenant.prog_hits += 1
        else:
            tenant.prog_misses += 1

    def _program(self, bucket: int):
        key = (bucket, self._capacity)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        if self._prog_cache is not None:
            prog, hit = self._prog_cache.get_or_build(
                self._shared_key(bucket),
                lambda: self._build_program(bucket))
            if hit:
                self._prog_hits += 1
            else:
                self._prog_misses += 1
                self._compile_dirty = True
        else:
            prog = self._build_program(bucket)
            self._compile_dirty = True
        self._programs[key] = prog
        return prog

    # -- Shared visited table ---------------------------------------------

    def _rebuild_table(self) -> None:
        """Rebuilds the device table from the LIVE tenants' tagged
        fingerprint sets (dropping any dead tenants' residual entries),
        growing capacity first if needed. A wave-boundary host
        operation — admission, growth, and dead-entry compaction all
        land here."""
        while self._capacity // 2 < (self._live_rows
                                     + 2 * self._B_max * self._F):
            self._capacity *= 2
        table = np.full(self._capacity, SENTINEL, np.uint64)
        for t in self._live:
            if t.visited_blocks:
                fps = np.concatenate(
                    [np.asarray(b, np.uint64)
                     for b in t.visited_blocks])
                host_table_insert(table, fps ^ np.uint64(t.tag))
        self._visited = jax.device_put(jnp.asarray(table))
        # The freshly built table IS the new shadow (device holds its
        # own copy; later in-place folds never touch device memory).
        self._shadow = table if self._aio.enabled else None
        self._dead_rows = 0

    def _integrate_joiners(self, joiners) -> None:
        """Folds joiners into the shared table at a wave boundary.

        Knob off this is the full host rebuild. Knob on, the per-wave
        background folds have kept ``_shadow`` membership-identical to
        the device table, so a clean boundary (no dead entries to
        compact, no growth needed) only inserts the joiners' rows and
        re-uploads — the incremental path. Probe placement can differ
        from a full rebuild; membership (the only thing lookups see)
        cannot, and dead entries force the full path exactly where the
        sync rebuild would have dropped them."""
        if (not self._aio.enabled or self._shadow is None
                or self._dead_rows
                or self._capacity // 2 < (self._live_rows
                                          + 2 * self._B_max * self._F)):
            self._rebuild_table()
            return
        self._aio.join()  # pending folds land before the upload
        for t in joiners:
            if t.visited_blocks:
                fps = np.concatenate([np.asarray(b, np.uint64)
                                      for b in t.visited_blocks])
                host_table_insert(self._shadow, fps ^ np.uint64(t.tag))
        self._visited = jax.device_put(jnp.asarray(self._shadow))

    def _table_stale(self) -> bool:
        occupied = self._live_rows + self._dead_rows
        return (self._visited is None
                or occupied + 2 * self._B_max * self._F
                > self._capacity // 2
                or self._dead_rows > max(self._live_rows, 4096))

    # -- Wave loop ---------------------------------------------------------

    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while not (self._joining or self._live
                               or self._stop or self._ever):
                        self._cv.wait(timeout=0.5)
                    if not self._joining and not self._live:
                        # Drained (or stopped before first admission):
                        # the group is done for good.
                        self._closed = True
                        return
                    joiners, self._joining = self._joining, []
                    if joiners:
                        self._ever = True
                        self._live.extend(joiners)
                        for t in joiners:
                            self._live_rows += t.rows_visited()
                        self._live_high_water = max(
                            self._live_high_water, len(self._live))
                        self._tag_dev = jnp.asarray(self._tags)
                    if self._stop:
                        for t in self._live:
                            t.preempt_requested = True
                if joiners:
                    self._integrate_joiners(joiners)
                # Wave boundary: retire finished tenants first (a run
                # that drained naturally completed — mirror of the solo
                # loop exiting before it rechecks the preempt flag),
                # then preempted ones (each drains to its own
                # checkpoint generation without touching the others).
                for t in list(self._live):
                    if (not t.pending
                            or len(t.discoveries)
                            == len(self._properties)):
                        self._retire(t, preempted=False)
                for t in list(self._live):
                    if t.preempt_requested:
                        self._retire(t, preempted=True)
                if not self._live:
                    continue
                if self._wave_count % self._ckpt_every == 0 \
                        and self._wave_count:
                    # Safe point: join any still-pending generation
                    # before starting a new one — per-tenant rotation
                    # order holds, and a writer-thread fault from the
                    # previous cycle surfaces HERE (group failure, the
                    # Supervisor-visible crash, same as the sync path).
                    self._aio.join()
                    for t in self._live:
                        if t.ckpt_path is not None:
                            self._write_tenant_checkpoint(t)
                if self._table_stale():
                    old = self._capacity
                    self._rebuild_table()
                    if self._tracer.enabled and self._capacity != old:
                        self._tracer.event("grow", kind="table",
                                           old=old, new=self._capacity)
                self._wave()
        except BaseException as e:  # surfaced at every tenant's join()
            with self._cv:
                self._closed = True
                pending = self._live + self._joining
                self._live, self._joining = [], []
                for t in pending:
                    if t.error is None:
                        t.error = e
            for t in pending:
                t.tracer.close()
                t.done.set()
        finally:
            with self._cv:
                self._closed = True
            self._aio.close()  # drains; never raises
            if self._wave_obs.enabled:
                self._wave_obs.close(self._tracer)
            self._tracer.close()

    def _wave(self) -> None:
        self._wave_t0 = time.monotonic()
        with self._cv:
            order = (self._live[self._rr % len(self._live):]
                     + self._live[:self._rr % len(self._live)])
            self._rr += 1
            queued = [t.rows_queued() for t in order]
        # Adaptive sizing (round 21): an armed controller caps the
        # wave budget from observed per-wave latency for this program
        # key (stepping down the bucket ladder while p90 exceeds the
        # target, plus one rung under brownout), never below one row
        # per live tenant — the fairness floor survives adaptation.
        # Tenant rows are still assembled contiguously in queue order,
        # so the split stays allocation-independent and bit-identity
        # with solo runs holds at ANY budget.
        cap = (self._control.mux_budget(self._prog_key, self._buckets,
                                        len(order))
               if self._control.armed else self._B_max)
        budget = min(sum(queued), cap)
        # Fair allocation with contiguous per-tenant segments: equal
        # shares first (rotated start, so no tenant owns the front of
        # the batch), then leftover capacity to whoever still has rows.
        share = budget // len(order)
        alloc = [min(q, share) for q in queued]
        left = budget - sum(alloc)
        for i, q in enumerate(queued):
            if left <= 0:
                break
            extra = min(q - alloc[i], left)
            alloc[i] += extra
            left -= extra
        bucket = pick_bucket(self._buckets, budget)
        batch_vecs = np.zeros((bucket, self._Wmux), np.uint32)
        batch_fps = np.zeros(bucket, np.uint64)
        batch_ebits = np.zeros(bucket, np.uint32)
        segments: List[tuple] = []
        row = 0
        for t, take in zip(order, alloc):
            if not take:
                continue
            lo = row
            taken = 0
            while t.pending and taken < take:
                vecs, fps, ebits = t.pending[0]
                k = len(fps)
                use = min(k, take - taken)
                if use == k:
                    t.pending.popleft()
                else:
                    t.pending[0] = (vecs[use:], fps[use:], ebits[use:])
                    vecs, fps, ebits = (vecs[:use], fps[:use],
                                        ebits[:use])
                batch_vecs[row:row + use] = vecs
                batch_fps[row:row + use] = fps
                batch_ebits[row:row + use] = ebits
                row += use
                taken += use
            segments.append((t, lo, row))
        n = row
        valid = np.arange(bucket) < n
        outs = self._program(bucket)(
            jnp.asarray(batch_vecs), jnp.asarray(valid), self._tag_dev,
            self._visited)
        (conds_out, terminal, seg_succ, seg_cand, seg_novel, new_count,
         new_vecs, new_fps, new_dedup, new_parent,
         self._visited) = outs
        self._process(segments, bucket, n, batch_vecs, batch_fps,
                      batch_ebits, valid, conds_out, terminal,
                      seg_succ, seg_cand, seg_novel, new_count,
                      new_vecs, new_fps, new_dedup, new_parent)

    def _host_conds(self, conds_out, batch_vecs, n) -> List[np.ndarray]:
        """Mirror of the solo engine's ``_eval_host_conds`` over mux
        rows (the tenant word is stripped before decode)."""
        model = self._model
        conds: List[np.ndarray] = []
        it = iter(conds_out)
        decoded: Optional[list] = None
        for i, fn in enumerate(self._prop_fns):
            if fn is not None:
                conds.append(np.asarray(next(it)))
                continue
            if decoded is None:
                decode = self._dm.decode
                rows = batch_vecs[:, :-1]
                unpacked = (self._base.unpack_np(rows) if self._pack_on
                            else rows)
                decoded = [(r, decode(unpacked[r])) for r in range(n)]
            cond = np.zeros(len(batch_vecs), bool)
            prop_cond = self._properties[i].condition
            for r, state in decoded:
                cond[r] = bool(prop_cond(model, state))
            conds.append(cond)
        return conds

    def _check_error_lane(self, new_vecs: np.ndarray) -> None:
        lane = self._dm.error_lane
        if lane is None or not new_vecs.size:
            return
        rows = new_vecs[:, :-1]
        col = (self._base.lane_np(rows, lane) if self._pack_on
               else rows[:, lane])
        if col.any():
            raise RuntimeError(
                f"device model error lane {lane} is set in a generated "
                "state: an encoding capacity was exceeded (for actor "
                "models: raise net_slots)")

    def _process(self, segments, bucket, n, batch_vecs, batch_fps,
                 batch_ebits, valid, conds_out, terminal, seg_succ,
                 seg_cand, seg_novel, new_count, new_vecs, new_fps,
                 new_dedup, new_parent) -> None:
        properties = self._properties
        conds = self._host_conds(conds_out, batch_vecs, n)
        terminal = np.asarray(terminal)
        k = int(new_count)
        new_vecs = np.asarray(new_vecs)[:k]
        new_fps = np.asarray(new_fps)[:k]
        new_dedup = np.asarray(new_dedup)[:k]
        parent_rows = np.asarray(new_parent)[:k]
        seg_succ = np.asarray(seg_succ)
        seg_cand = np.asarray(seg_cand)
        seg_novel = np.asarray(seg_novel)
        ebits_after = batch_ebits.copy()
        for i in self._eventually_idx:
            ebits_after &= ~np.where(conds[i], np.uint32(1 << i),
                                     np.uint32(0))
        jobs_in_wave = len(segments)
        succ_total = cand_total = 0
        per_job: List[tuple] = []
        for t, lo, hi in segments:
            sel = (parent_rows >= lo) & (parent_rows < hi)
            t_k = int(sel.sum())
            t_succ = int(seg_succ[t.slot])
            t_cand = int(seg_cand[t.slot])
            if t_k != int(seg_novel[t.slot]):
                raise RuntimeError(
                    f"mux wave split inconsistency: segment of job "
                    f"{t.id} claims {int(seg_novel[t.slot])} novel "
                    f"rows, parent ranges yield {t_k}")
            succ_total += t_succ
            cand_total += t_cand
            failure: Optional[BaseException] = None
            try:
                self._check_error_lane(new_vecs[sel])
            except RuntimeError as e:
                failure = e
            with self._cv:
                t.state_count += t_succ
                # ALWAYS/SOMETIMES discoveries: first hit in the
                # tenant's queue order (its rows are contiguous and
                # ordered, so "first row in the segment" IS the solo
                # rule).
                for i, prop in enumerate(properties):
                    if prop.name in t.discoveries:
                        continue
                    if prop.expectation is Expectation.ALWAYS:
                        hits = valid[lo:hi] & ~conds[i][lo:hi]
                    elif prop.expectation is Expectation.SOMETIMES:
                        hits = valid[lo:hi] & conds[i][lo:hi]
                    else:
                        continue
                    rows = np.flatnonzero(hits)
                    if rows.size:
                        t.discoveries[prop.name] = int(
                            batch_fps[lo + rows[0]])
                for r in np.flatnonzero(terminal[lo:hi]
                                        & (ebits_after[lo:hi] != 0)):
                    for i in self._eventually_idx:
                        prop = properties[i]
                        if (ebits_after[lo + r] >> i) & 1 \
                                and prop.name not in t.discoveries:
                            t.discoveries[prop.name] = int(
                                batch_fps[lo + r])
                if t_k and failure is None:
                    t.parent_log.append(
                        (new_fps[sel], batch_fps[parent_rows[sel]]))
                    t.unique_count += t_k
                    t.pending.append((new_vecs[sel], new_fps[sel],
                                      ebits_after[parent_rows[sel]]))
                    t.visited_blocks.append(new_dedup[sel])
                    self._live_rows += t_k
                elif t_k:
                    # The failed tenant's insertions stay in the table
                    # as dead entries until the next rebuild.
                    self._dead_rows += t_k
                if failure is not None:
                    t.error = failure
                t.waves += 1
            if t_k and failure is None and self._shadow is not None:
                # Background fold: mirror the device table's in-place
                # insertions into the host shadow. The shadow array is
                # captured at submit time — a full rebuild may swap it
                # mid-flight, in which case the fold lands on the
                # retired array (harmless: the rebuild re-inserted
                # these keys from visited_blocks).
                shadow = self._shadow
                keys = new_dedup[sel] ^ np.uint64(t.tag)
                self._aio.submit(
                    lambda shadow=shadow, keys=keys:
                        host_table_insert(shadow, keys),
                    kind="fold")
            per_job.append((t, hi - lo, t_succ, t_cand, t_k))
        with self._cv:
            self._states_total += succ_total
            self._unique_total += k
            self._wave_count += 1
            states, unique = self._states_total, self._unique_total
        for t, _, _, _, _ in per_job:
            if t.error is not None and not t.done.is_set():
                self._retire_failed(t)
        compiled = self._compile_dirty
        self._compile_dirty = False
        total_entry = None
        if self._tracer.enabled or self._wave_obs.enabled:
            total_entry = self._wave_entry(
                states, unique, bucket, n, succ_total, cand_total, k,
                compiled, None, jobs_in_wave)
        if self._tracer.enabled:
            # One TOTAL line (job_id null, jobs_in_wave = J) followed
            # by exactly J attributed lines whose deltas sum to it —
            # the v9 split trace_lint enforces. Every line carries the
            # GROUP-cumulative states/unique (the lint's per-run
            # monotone counters); tenant cumulatives live in the
            # per-job trace files under their own run ids.
            self._tracer.wave(total_entry)
            for t, t_rows, t_succ, t_cand, t_k in per_job:
                self._tracer.wave(self._wave_entry(
                    states, unique, bucket, t_rows, t_succ, t_cand,
                    t_k, False, t.id, jobs_in_wave))
        if self._wave_obs.enabled:
            # Group-wave latency over the TOTAL line (entries carry no
            # "t" — the facade stamps its own monotonic clock).
            self._wave_obs.wave(total_entry, self._tracer)
        for t, t_rows, t_succ, t_cand, t_k in per_job:
            if t.tracer.enabled:
                with self._cv:
                    t_states, t_unique = t.state_count, t.unique_count
                t.tracer.wave(self._wave_entry(
                    t_states, t_unique, bucket, t_rows, t_succ, t_cand,
                    t_k, compiled, t.id, jobs_in_wave))
        if self._control.armed and self._wave_t0 is not None:
            # Feed the adaptive-budget histogram (compile waves are
            # excluded inside — a lazy XLA build is not a latency
            # regression).
            self._control.note_wave(
                self._prog_key, time.monotonic() - self._wave_t0,
                compiled=compiled)

    def _wave_entry(self, states, unique, bucket, rows, succ, cand,
                    novel, compiled, job_id, jobs_in_wave) -> dict:
        occupied = self._live_rows + self._dead_rows
        return {
            "states": int(states), "unique": int(unique),
            "bucket": int(bucket), "waves": 1, "inflight": 0,
            "compiled": bool(compiled), "successors": int(succ),
            "candidates": int(cand), "novel": int(novel),
            "out_rows": int(bucket * self._F),
            "capacity": int(self._capacity),
            "load_factor": round(occupied / self._capacity, 4),
            "overflow": False, "bytes_per_state": 4 * self._Wmux,
            "arena_bytes": None, "table_bytes": self._capacity * 8,
            "kernel_path": "xla", "rows": int(rows),
            "job_id": job_id, "jobs_in_wave": int(jobs_in_wave),
        }

    # -- Retirement / checkpoints ------------------------------------------

    def _retire(self, t: _Tenant, preempted: bool) -> None:
        # Surface any pending writer fault from OTHER tenants' periodic
        # generations BEFORE this tenant's final one: a deferred group
        # failure must stay a group failure (the Supervisor-visible
        # crash), not be swallowed as this tenant's own checkpoint
        # error. Raises into _run's handler, exactly like the sync
        # path's inline fault.
        self._aio.join()
        try:
            if t.ckpt_path is not None:
                self._write_tenant_checkpoint(t)
                # The final generation must be durable before done is
                # set — the client reads the file right after join().
                self._aio.join()
        except BaseException as e:  # noqa: BLE001 — fail THIS tenant
            t.error = e
        with self._cv:
            t.preempted = preempted and t.error is None
            self._live.remove(t)
            self._slots[t.slot] = None
            rows = t.rows_visited()
            self._live_rows -= rows
            self._dead_rows += rows
        t.tracer.close()
        t.done.set()

    def _retire_failed(self, t: _Tenant) -> None:
        with self._cv:
            if t in self._live:
                self._live.remove(t)
                self._slots[t.slot] = None
                rows = t.rows_visited()
                self._live_rows -= rows
                self._dead_rows += rows
        t.tracer.close()
        t.done.set()

    def _write_tenant_checkpoint(self, t: _Tenant) -> None:
        from ..checkpoint_format import write_atomic

        # Snapshot capture is synchronous (bit-identical content either
        # knob); only CRC/serialize/rename rides the writer. FIFO + the
        # safe-point joins preserve per-tenant generation order.
        payload = self._tenant_snapshot(t)
        path = t.ckpt_path
        self._aio.submit(lambda: write_atomic(path, payload),
                         kind="checkpoint")

    def _tenant_snapshot(self, t: _Tenant) -> dict:
        """Mirror of the solo engine's ``_snapshot`` for ONE tenant —
        same header fields, same canonical (sorted) visited order, and
        pending rows with the tenant word stripped, so the bytes match
        a solo run of the same job section for section."""
        from ..checkpoint_format import make_header

        parents = self._tenant_parent_map(t)
        n = len(parents)
        child = np.fromiter(parents.keys(), np.uint64, n)
        parent = np.fromiter((0 if v is None else v
                              for v in parents.values()), np.uint64, n)
        rooted = np.fromiter((v is None for v in parents.values()),
                             bool, n)
        with self._cv:
            blocks = list(t.pending)
            visited_blocks = list(t.visited_blocks)
            state_count, unique_count = t.state_count, t.unique_count
            discoveries = dict(t.discoveries)
        if blocks:
            vecs = np.concatenate([b[0][:, :-1] for b in blocks])
            fps = np.concatenate([b[1] for b in blocks])
            ebits = np.concatenate([b[2] for b in blocks])
        else:
            vecs = np.zeros((0, self._Wrow), np.uint32)
            fps = np.zeros(0, np.uint64)
            ebits = np.zeros(0, np.uint32)
        visited = (np.concatenate([np.asarray(b, np.uint64)
                                   for b in visited_blocks])
                   if visited_blocks else np.zeros(0, np.uint64))
        visited = np.sort(visited)
        header = make_header(
            model_name=type(self._model).__name__,
            state_width=self._W, state_count=state_count,
            unique_count=unique_count, use_symmetry=False,
            discoveries=discoveries,
            row_format="packed" if self._pack_on else "u32",
            lane_bits=self._base.specs if self._pack_on else None,
            packed_width=self._Wrow if self._pack_on else None,
            store=None)
        return dict(header=header, visited=visited, pending_vecs=vecs,
                    pending_fps=fps, pending_ebits=ebits,
                    parent_child=child, parent_parent=parent,
                    parent_rooted=rooted)

    # -- Paths / discoveries -----------------------------------------------

    def _tenant_parent_map(self, t: _Tenant) -> Dict[int, Optional[int]]:
        with self._cv:
            log = t.parent_log
            while t.parents_consumed < len(log):
                child_fps, parent_fps = log[t.parents_consumed]
                if parent_fps is None:
                    for f in child_fps:
                        t.parents.setdefault(int(f), None)
                else:
                    for f, p in zip(child_fps.tolist(),
                                    parent_fps.tolist()):
                        t.parents.setdefault(f, p)
                log[t.parents_consumed] = None
                t.parents_consumed += 1
        return t.parents

    def _fingerprint_state(self, state) -> int:
        return host_fp64(np.asarray(self._dm.encode(state), np.uint32))

    def _tenant_discoveries(self, t: _Tenant) -> Dict[str, Path]:
        with self._cv:
            found = list(t.discoveries.items())
        parents = self._tenant_parent_map(t)
        out: Dict[str, Path] = {}
        for name, fp in found:
            fingerprints: deque = deque()
            next_fp = fp
            while next_fp in parents:
                source = parents[next_fp]
                fingerprints.appendleft(next_fp)
                if source is None:
                    break
                next_fp = source
            out[name] = Path.from_fingerprints(
                self._model, fingerprints,
                fingerprint_fn=self._fingerprint_state)
        return out

    # -- Lifecycle ---------------------------------------------------------

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def jobs_in_group(self) -> int:
        with self._cv:
            return len(self._live) + len(self._joining)

    def close(self, timeout: float = 30.0) -> None:
        """Stops the group: live tenants preempt (draining to their
        checkpoints), then the loop exits. Idempotent."""
        with self._cv:
            self._stop = True
            for t in self._live:
                t.preempt_requested = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout)

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout=timeout)

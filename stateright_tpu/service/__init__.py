"""Checking-as-a-service: the multi-tenant job layer (ROADMAP item 5).

- ``registry`` — the named protocol corpus (8 existing models + the
  round-14 viewstamped-replication addition) and the canonical
  parameter keys that scope cross-job compiled-program sharing;
- ``jobs`` — the supervised worker-pool scheduler: per-job checkpoint
  generations (preempt → resume), per-job trace streams, shared
  ``WaveProgramCache``, priority/quota queue policy with bounded-depth
  admission control;
- ``mux`` — cross-job wave multiplexing (round 16): same-shape jobs
  share one engine whose waves batch several frontiers per device
  dispatch, with per-job results bit-identical to solo runs (imported
  lazily by ``jobs`` — it pulls jax);
- ``control`` — closed-loop overload control (round 21): SLO-driven
  admission/shedding with Retry-After, deadline-aware park/auto-resume
  preemption, adaptive mux wave sizing, and the brownout ladder —
  armed via ``STpu_CONTROL``, disarmed a poisoned-null singleton;
- ``diff`` — the differential fuzz gate cross-validating every corpus
  model's device form against the host semantics.

The HTTP surface (``POST /jobs`` & co.) lives in
``stateright_tpu.explorer`` (``serve_service``), extending the
explorer's server plumbing; ``tools/service_client.py`` is the CLI.
"""

from .control import (CONTROL_ENV, NULL_CONTROL, ControlPolicy,
                      NullControl, OverloadController, control_from_env)
from .diff import DiffMismatch, diff_check, diff_walk, fuzz_gate
from .jobs import (Job, JobConflict, JobError, JobQueueFull,
                   JobService, JobShed)
from .registry import CorpusEntry, ModelRegistry, default_registry

__all__ = [
    "CorpusEntry", "ModelRegistry", "default_registry",
    "Job", "JobService", "JobError", "JobConflict", "JobQueueFull",
    "JobShed",
    "CONTROL_ENV", "ControlPolicy", "OverloadController", "NullControl",
    "NULL_CONTROL", "control_from_env",
    "DiffMismatch", "diff_walk", "diff_check", "fuzz_gate",
]

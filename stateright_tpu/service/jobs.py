"""``JobService``: the multi-tenant checking scheduler.

A job names a corpus model (``service/registry.py``) plus parameters,
an engine (``classic`` / ``fused`` device engines, or ``host`` BFS),
and a small allowlisted knob set. Jobs queue into a bounded worker
pool; each runs under the round-10 :class:`Supervisor` with

- its **own checkpoint generation** (``<data_dir>/<job>.ckpt.npz``,
  format v5 with keep-last-2 rotation) — crash retries resume from the
  newest valid snapshot, and a *preempted* job (``DELETE /jobs/<id>``
  → the engine's cooperative ``preempt()``) leaves a resumable image a
  resubmission (``{"resume": "<id>"}``) continues bit-identically;
- its **own trace stream** (``<data_dir>/<job>.trace.jsonl``): the
  service emits the v7 ``job_submit``/``job_done``/``job_abort``
  lifecycle events and the engine appends its run there (worker-tagged
  run ids from obs v5 mean even interleaved producers separate), so
  ``GET /jobs/<id>/trace`` is a file read and ``tools/trace_lint.py``
  validates each job end to end;
- the **shared wave-program cache** (``jit_cache.WaveProgramCache``)
  keyed by the registry's ``(model, canonical params)`` — the Nth
  submission of a hot model skips XLA compilation entirely, surfaced
  per job (``jit_cache`` in the status payload) and in the service
  metrics.

Round 16 adds **cross-job wave multiplexing**: concurrent jobs of the
same corpus shape — same canonical ``(model, params)`` registry key,
same engine, same knob set — are admitted as tenants of one shared
:class:`~stateright_tpu.service.mux.MuxGroup`, whose waves batch the
tenants' frontiers into ONE device dispatch (``service/mux.py``). The
per-job surfaces (``GET /jobs/<id>`` counters, verdicts, checkpoint
bytes, trace stream) stay exactly what a solo engine produces. The
queue itself grew scheduling policy: ``priority`` (higher first, FIFO
within), per-``tenant`` running quotas honored at queue POP, and a
bounded depth whose overflow maps to HTTP 429 (:class:`JobQueueFull`).

Scope honesty (ARCHITECTURE "Elasticity"): the pool schedules jobs
across OS threads of ONE process on one host — the same
single-host scope as the elastic runtime's process workers. Multi-host
serving is not claimed here.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..jit_cache import WaveProgramCache
from ..obs.hist import prometheus_hist_lines, wave_obs_from_env
from ..obs.tracer import RunTracer
from ..resilience.supervisor import Supervisor, newest_valid_checkpoint
from .control import control_from_env
from .registry import ModelRegistry, default_registry

__all__ = ["Job", "JobService", "JobError", "JobConflict",
           "JobQueueFull", "JobShed"]

#: engine knobs a submission may set, with their coercion types —
#: everything else in the engine signature is the service's business
#: (checkpoint/trace paths, program cache), not the tenant's.
_KNOBS = {
    "batch_size": int,
    "max_batch_size": int,
    "table_capacity": int,
    "target_state_count": int,
    "checkpoint_every_waves": int,
    "waves_per_dispatch": int,
    "table_impl": str,
    "pack_arena": bool,
    "succ_ladder": bool,
    # Single-kernel wave (round 15): tenants may A/B the megakernel;
    # bit-identical either way, and the shared program cache keys on
    # it, so mixed-knob jobs never share the wrong executable.
    "wave_kernel": bool,
    # Background host I/O (round 17): bit-identical either way; the
    # mux shape key includes it, so mixed-knob jobs never share a
    # group with the wrong writer policy.
    "async_io": bool,
    # Matmul-form expand (round 19): tenants may A/B the compiled
    # transition-table path; bit-identical either way (irregular
    # models gate to the step path), and the shared program cache
    # keys on the resolved plan.
    "wave_matmul": bool,
}

_ENGINES = ("classic", "fused", "host")


class JobError(ValueError):
    """A submission the service rejects (HTTP 400)."""


class JobConflict(RuntimeError):
    """A valid request the job's current state cannot honor (409)."""


class JobQueueFull(RuntimeError):
    """Admission control: the bounded queue is at capacity (429)."""


class JobShed(JobQueueFull):
    """Round 21: the overload controller shed this submission (429 +
    ``Retry-After``). Subclasses :class:`JobQueueFull` so pre-round-21
    callers that catch-and-retry on queue pressure keep working; the
    extra fields carry the machine-readable reason and the
    drain-derived retry hint the HTTP layer surfaces."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(
            f"submission shed by overload controller ({reason}); "
            f"retry after {retry_after_s}s")
        self.reason = reason
        self.retry_after_s = retry_after_s


#: Priority aging (round 21): a queued entry gains one effective
#: priority level per ``_AGE_EVERY_POPS`` jobs dispatched past it, up
#: to ``_AGE_MAX_BOOST`` levels. The clock is POP COUNT, not wall time
#: — deterministic under test and proportional to actual bypass, so a
#: saturated high-priority stream can delay a low-priority job by at
#: most ``_AGE_EVERY_POPS * (gap + _AGE_MAX_BOOST)`` dispatches, never
#: forever. Ties at equal effective priority stay FIFO.
_AGE_EVERY_POPS = 4
_AGE_MAX_BOOST = 8


class _JobQueue:
    """The scheduler's queue: priority-ordered (higher first, FIFO
    within a priority, with bounded pop-count aging so a saturated
    high-priority stream cannot starve low priorities forever),
    bounded (``put`` raises :class:`JobQueueFull` at capacity), with
    per-tenant RUNNING quotas enforced at pop — a tenant at quota is
    skipped, not starved: its entries stay in place and become
    eligible the moment one of its jobs finishes. The overload
    controller's brownout rung 3 sets a HOLD floor: entries whose base
    priority is below it are paused in place (skipped, not dropped)
    until the ladder steps back up.

    The queue owns its own condition variable and tracks active
    counts internally (``task_done``), so the pop path never needs the
    service lock — the lock-ordering hazard of a worker blocking on
    the queue while holding service state simply cannot arise."""

    def __init__(self, max_queued: Optional[int] = None,
                 tenant_quota: Optional[int] = None):
        self._cv = threading.Condition()
        self._items: List[tuple] = []
        self._seq = 0
        self._max = max_queued
        self._quota = tenant_quota
        self._active: Dict[str, int] = {}
        self._closed = False
        self._pops = 0
        self._hold: Optional[int] = None

    def put(self, job_id: str, tenant: Optional[str] = None,
            priority: int = 0) -> None:
        with self._cv:
            if self._max is not None and len(self._items) >= self._max:
                raise JobQueueFull(
                    f"job queue is full ({len(self._items)}/"
                    f"{self._max}); retry after a job finishes")
            self._seq += 1
            self._items.append((-int(priority), self._seq, job_id,
                                tenant, self._pops))
            self._items.sort()
            self._cv.notify()

    def set_hold(self, threshold: Optional[int]) -> None:
        """Brownout rung 3 actuator: pause (don't drop) queued entries
        whose BASE priority is below ``threshold``; ``None`` releases
        the hold. Held entries keep their seq and aging credit."""
        with self._cv:
            self._hold = threshold
            self._cv.notify_all()

    def pop(self) -> Optional[Tuple[str, Optional[str]]]:
        """Blocks for the next runnable entry; ``None`` means the
        queue closed. The caller MUST pair a non-None pop with ONE
        ``task_done(tenant)`` once the job leaves "running". Selection
        is by EFFECTIVE priority — base plus the bounded age boost —
        with FIFO tie-break, over entries passing the quota and hold
        filters."""
        with self._cv:
            while True:
                if self._closed:
                    return None
                best_i, best_key = -1, None
                for i, (neg_pri, seq, job_id, tenant,
                        born) in enumerate(self._items):
                    if self._hold is not None and -neg_pri < self._hold:
                        continue
                    if (self._quota is not None and tenant is not None
                            and self._active.get(tenant, 0)
                            >= self._quota):
                        continue
                    boost = min(_AGE_MAX_BOOST,
                                (self._pops - born) // _AGE_EVERY_POPS)
                    key = (-neg_pri + boost, -seq)
                    if best_key is None or key > best_key:
                        best_i, best_key = i, key
                if best_i >= 0:
                    _, _, job_id, tenant, _ = self._items.pop(best_i)
                    self._pops += 1
                    if tenant is not None:
                        self._active[tenant] = \
                            self._active.get(tenant, 0) + 1
                    return job_id, tenant
                self._cv.wait(timeout=0.5)

    def task_done(self, tenant: Optional[str]) -> None:
        with self._cv:
            if tenant is not None:
                count = self._active.get(tenant, 0) - 1
                if count > 0:
                    self._active[tenant] = count
                else:
                    self._active.pop(tenant, None)
            self._cv.notify_all()

    def cancel(self, job_id: str) -> bool:
        """Removes a still-queued entry (``DELETE`` on a queued job)."""
        with self._cv:
            for i, item in enumerate(self._items):
                if item[2] == job_id:
                    self._items.pop(i)
                    return True
            return False

    def qsize(self) -> int:
        with self._cv:
            return len(self._items)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class Job:
    """One submission's record. All mutation happens under the
    service lock; the engine reference is read lock-free for live
    counters (its count methods are thread-safe)."""

    def __init__(self, job_id: str, spec: dict, trace_path: str,
                 checkpoint_path: Optional[str]):
        self.id = job_id
        self.spec = spec
        self.trace_path = trace_path
        self.checkpoint_path = checkpoint_path
        self.state = "queued"
        self.error: Optional[str] = None
        self.resume_of: Optional[str] = None
        self.submitted_t = time.monotonic()
        self.started_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.checker = None
        self.model = None
        self.resumed_by: Optional[str] = None
        self.preempt_requested = False
        self.tracer: Optional[RunTracer] = None
        self.result: Dict = {}
        #: the canonical registry cache key, computed ONCE at submit —
        #: the status-poll and engine-build paths read this instead of
        #: re-canonicalizing the params dict per request.
        self.program_key: Optional[tuple] = None

    def runtime(self) -> Optional[float]:
        if self.started_t is None:
            return None
        end = self.finished_t if self.finished_t is not None \
            else time.monotonic()
        return end - self.started_t


class JobService:
    """The scheduler: ``workers`` daemon threads drain a FIFO queue.
    ``data_dir`` holds per-job checkpoints and traces (a fresh temp
    dir by default); ``program_cache`` is shared across every device
    job the service runs."""

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 workers: int = 2, data_dir: Optional[str] = None,
                 program_cache: Optional[WaveProgramCache] = None,
                 mux: bool = True, mux_max_jobs: int = 8,
                 max_queued: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 control=None):
        self.registry = registry or default_registry()
        self.data_dir = data_dir or tempfile.mkdtemp(
            prefix="stpu-service-")
        os.makedirs(self.data_dir, exist_ok=True)
        self.program_cache = program_cache or WaveProgramCache()
        self._lock = threading.Lock()
        self._jobs: Dict[str, Job] = {}
        self._order: List[str] = []
        self._seq = 0
        self._queue = _JobQueue(max_queued=max_queued,
                                tenant_quota=tenant_quota)
        self._mux = bool(mux)
        self._mux_max_jobs = max(1, int(mux_max_jobs))
        self._mux_lock = threading.Lock()
        #: open group per corpus shape — (program_key, engine, knobs);
        #: closed groups are replaced lazily on the next admission.
        self._mux_groups: Dict[tuple, object] = {}
        self._mux_all: List[object] = []
        #: service observability (obs/hist.py): job queue/run/total
        #: latency histograms + the service SLO surface (/.healthz).
        #: Disarmed = the shared NULL_OBS (zero per-job cost).
        self._obs = wave_obs_from_env("service")
        #: round-21 overload controller: STpu_CONTROL (or an explicit
        #: instance) arms the closed loop; disarmed = NULL_CONTROL,
        #: and every hot-path consult is behind an `.armed` check.
        self._control = control if control is not None \
            else control_from_env()
        if self._control.armed:
            self._control.bind(self)
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"stpu-job-worker-{i}")
            for i in range(max(1, int(workers)))]
        for t in self._workers:
            t.start()

    # -- Submission --------------------------------------------------------

    def submit(self, spec: dict) -> dict:
        """Validates and enqueues one job; returns its status payload.
        ``spec`` keys: ``model`` (+ optional ``params``), optional
        ``engine`` (default ``classic``), ``knobs``, ``properties``
        (verdict selection), ``priority`` (int; higher pops first),
        ``tenant`` (quota label for the pop-time running cap), or
        ``resume`` naming an earlier preempted/failed job to continue
        from its checkpoint generation. Raises :class:`JobQueueFull`
        (HTTP 429) when the bounded queue is at capacity."""
        if not isinstance(spec, dict):
            raise JobError("job spec must be a JSON object")
        resume_of: Optional[Job] = None
        if spec.get("resume") is not None:
            resume_of = self._job(str(spec["resume"]))
            with self._lock:
                if resume_of.state not in ("preempted", "failed"):
                    raise JobConflict(
                        f"job {resume_of.id} is {resume_of.state}; only "
                        "preempted/failed jobs can be resumed")
                if resume_of.checkpoint_path is None:
                    raise JobConflict(
                        f"job {resume_of.id} has no checkpoint to "
                        "resume from (host-engine jobs are not "
                        "resumable)")
            base = dict(resume_of.spec)
            base.update({k: v for k, v in spec.items() if k != "resume"})
            spec = base

        model_name = spec.get("model")
        if not isinstance(model_name, str):
            raise JobError("job spec needs a 'model' (corpus name); "
                           f"registered: {self.registry.names()}")
        engine = spec.get("engine", "classic")
        if engine not in _ENGINES:
            raise JobError(f"engine must be one of {_ENGINES}, "
                           f"got {engine!r}")
        try:
            model, params = self.registry.build(model_name,
                                                spec.get("params"))
        except KeyError as e:
            raise JobError(str(e)) from e
        except ValueError as e:
            raise JobError(str(e)) from e
        knobs = self._check_knobs(spec.get("knobs"))
        prop_names = [p.name for p in model.properties()]
        selected = spec.get("properties")
        if selected is not None:
            unknown = [p for p in selected if p not in prop_names]
            if unknown:
                raise JobError(
                    f"model {model_name!r} has no properties {unknown}; "
                    f"available: {prop_names}")
        if engine != "host" and getattr(model, "device_model",
                                        None) is None:
            raise JobError(
                f"model {model_name!r} has no device form; submit with "
                "engine='host'")

        try:
            priority = int(spec.get("priority", 0) or 0)
        except (TypeError, ValueError) as e:
            raise JobError(f"priority must be an integer: {e}") from e
        tenant = spec.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            raise JobError("tenant must be a string label")
        deadline_s = spec.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError) as e:
                raise JobError(
                    f"deadline_s must be a number of seconds: {e}"
                ) from e
            if deadline_s <= 0:
                raise JobError("deadline_s must be > 0")

        # Overload admission (round 21): consulted BEFORE the job
        # record exists, so a shed allocates nothing and rolls back
        # nothing. Resumes bypass the gate — a parked job re-entering
        # is the controller DRAINING pressure, not new demand.
        if self._control.armed and resume_of is None:
            decision = self._control.admission(
                tenant, priority, self._queue.qsize())
            if decision is not None:
                raise JobShed(*decision)

        clean_spec = {"model": model_name, "params": params,
                      "engine": engine, "knobs": knobs,
                      "properties": selected, "priority": priority,
                      "tenant": tenant, "deadline_s": deadline_s}
        with self._lock:
            self._seq += 1
            job_id = f"j-{self._seq:04d}"
            trace_path = os.path.join(self.data_dir,
                                      f"{job_id}.trace.jsonl")
            if resume_of is not None:
                # Claim the predecessor under the same lock that
                # allocates the id: a second resume of the same job
                # would put two live Supervisors on ONE checkpoint
                # rotation (interleaved writes rotate each other's
                # snapshots away) — first claim wins, later ones 409.
                if resume_of.resumed_by is not None:
                    raise JobConflict(
                        f"job {resume_of.id} was already resumed by "
                        f"{resume_of.resumed_by}")
                # Continue the predecessor's checkpoint generation:
                # the Supervisor resumes from its newest valid
                # snapshot, so the resubmission picks up exactly where
                # the preemption stopped.
                ckpt = resume_of.checkpoint_path
            else:
                ckpt = (os.path.join(self.data_dir,
                                     f"{job_id}.ckpt.npz")
                        if engine != "host" else None)
            job = Job(job_id, clean_spec, trace_path, ckpt)
            job.model = model
            job.program_key = self.registry.program_key(model_name,
                                                        params)
            if resume_of is not None:
                job.resume_of = resume_of.id
                resume_of.resumed_by = job_id
            job.tracer = RunTracer(trace_path, "service",
                                   meta={"job": job_id,
                                         "model": model_name})
            job.tracer.event("job_submit", job=job_id,
                             model=model_name, job_engine=engine,
                             _flush=True)
            self._jobs[job_id] = job
            self._order.append(job_id)
        try:
            self._queue.put(job_id, tenant=tenant, priority=priority)
        except JobQueueFull:
            # Admission rejected: roll the registration back so the
            # overflow leaves no phantom record (429 is retryable).
            with self._lock:
                self._jobs.pop(job_id, None)
                if job_id in self._order:
                    self._order.remove(job_id)
                if resume_of is not None:
                    resume_of.resumed_by = None
                tracer, job.tracer = job.tracer, None
            if tracer is not None:
                tracer.event("job_abort", job=job_id,
                             reason="queue_full", _flush=True)
                tracer.close()
            if self._control.armed:
                # Count + event the overflow as a shed and upgrade the
                # plain 429 with a drain-derived Retry-After.
                retry_after = self._control.note_queue_full(
                    tenant, priority, self._queue.qsize())
                raise JobShed("queue_full", retry_after) from None
            raise
        if self._control.armed and resume_of is None:
            self._control.note_admitted(job_id, tenant, priority,
                                        self._queue.qsize())
        return self.status(job_id)

    def _check_knobs(self, knobs) -> dict:
        out = {}
        for key, value in (knobs or {}).items():
            want = _KNOBS.get(key)
            if want is None:
                raise JobError(f"unknown engine knob {key!r}; "
                               f"accepts {sorted(_KNOBS)}")
            try:
                out[key] = bool(value) if want is bool else want(value)
            except (TypeError, ValueError) as e:
                raise JobError(f"knob {key!r}: {e}") from e
        return out

    # -- Execution ---------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            popped = self._queue.pop()
            if popped is None:
                return
            job_id, tenant = popped
            try:
                job = self._jobs.get(job_id)
                if job is None:
                    continue
                with self._lock:
                    if job.state != "queued":
                        continue  # cancelled while queued
                    job.state = "running"
                    job.started_t = time.monotonic()
                try:
                    self._run_job(job)
                except Exception as e:  # noqa: BLE001 — the job record
                    # is the failure surface; the service must survive
                    self._finish(job, "failed",
                                 error=f"{type(e).__name__}: {e}"[:300])
            finally:
                # Quota release happens exactly once per pop, whatever
                # the job's fate — a leak here would starve the tenant.
                self._queue.task_done(tenant)

    def _factory(self, job: Job):
        engine = job.spec["engine"]
        knobs = dict(job.spec["knobs"])
        target = knobs.pop("target_state_count", None)

        def build(resume_from=None):
            builder = job.model.checker()
            if target:
                builder.target_state_count(target)
            if engine == "host":
                checker = builder.spawn_bfs()
            else:
                build_knobs = dict(knobs)
                if (self._control.armed
                        and "checkpoint_every_waves" in build_knobs):
                    # Brownout rung 2: widen the cadence for runs
                    # STARTED under pressure (cadence is sampled once
                    # per engine build; counters are cadence-
                    # independent, so bit-identity holds).
                    build_knobs["checkpoint_every_waves"] = \
                        self._control.ckpt_every(
                            build_knobs["checkpoint_every_waves"])
                checker = builder.spawn_tpu_bfs(
                    fused=(engine == "fused"),
                    checkpoint_path=job.checkpoint_path,
                    trace_path=job.trace_path,
                    program_cache=self.program_cache,
                    program_key=job.program_key,
                    resume_from=resume_from,
                    **build_knobs)
            with self._lock:
                job.checker = checker
                preempt_now = job.preempt_requested
            if preempt_now and hasattr(checker, "preempt"):
                # A DELETE raced the engine build: honor it at the
                # first wave boundary.
                checker.preempt()
            return checker

        return build

    def _mux_factory(self, job: Job, first_handle):
        """Supervisor factory for a mux tenant. Attempt 1 returns the
        pre-admitted handle; a retry (the group crashed, failing every
        tenant) re-admits into a fresh group resuming from the newest
        valid generation of THIS tenant's checkpoint — per-tenant
        counters survive the shared crash. No slot on the retry falls
        back to a supervised solo engine (the mux's bit-identity
        contract makes that a pure placement change)."""
        state = {"handle": first_handle}
        solo = self._factory(job)

        def build(resume_from=None):
            handle = state.pop("handle", None)
            if handle is None:
                handle = self._mux_admit_with(job, resume_from)
            if handle is None:
                return solo(resume_from=resume_from)
            with self._lock:
                job.checker = handle
                preempt_now = job.preempt_requested
            if preempt_now:
                # A DELETE raced the admission: honor it at the
                # group's next wave boundary.
                handle.preempt()
            return handle

        return build

    def _run_job(self, job: Job) -> None:
        if self._mux_eligible(job):
            handle = self._mux_admit(job)
            if handle is not None:
                # Round 17 (satellite): the mux path used to join the
                # handle directly, so a group crash (e.g. an injected
                # fault in a tenant checkpoint write) was terminal for
                # every tenant. Route it through the same Supervisor
                # the solo engines get.
                checker = Supervisor(
                    self._mux_factory(job, handle),
                    checkpoint_path=job.checkpoint_path,
                    trace_path=job.trace_path).run()
                self._finish(job, "preempted"
                             if getattr(checker, "preempted", False)
                             else "done")
                return
            # No slot / no valid resume image / group races: the solo
            # path below is always a correct fallback (bit-identical
            # results are the mux's contract, not a new semantics).
        factory = self._factory(job)
        if job.spec["engine"] == "host":
            checker = factory()
            checker.join()
        else:
            # Retry/abort events land in the JOB's trace stream, so a
            # job's whole supervised life lints as one file.
            checker = Supervisor(
                factory, checkpoint_path=job.checkpoint_path,
                trace_path=job.trace_path).run()
        if getattr(checker, "preempted", False):
            self._finish(job, "preempted")
        else:
            self._finish(job, "done")

    def _mux_eligible(self, job: Job) -> bool:
        """Multiplexing admission policy: classic engine only (the
        fused engine's device-resident loop declares itself
        ``_MUX_CAPABLE = False``), and only performance-schedule knobs
        — notably NOT ``target_state_count``, whose wave-granular early
        stop would make residual counts depend on who shared the wave
        (the solo-identity contract would silently break)."""
        if not self._mux or job.spec["engine"] != "classic":
            return False
        try:
            from ..tpu.engine import TpuBfsChecker
            from .mux import MUX_KNOBS
        except ImportError:
            return False
        if not getattr(TpuBfsChecker, "_MUX_CAPABLE", False):
            return False
        return not (set(job.spec["knobs"]) - MUX_KNOBS)

    def _mux_admit(self, job: Job):
        """Admits the job into the open group for its corpus shape
        (creating one if needed); returns a TenantHandle or ``None``
        for the solo fallback. Shape key = cached canonical registry
        key + engine + exact knob set — the same safety condition the
        shared program cache uses, tightened to identical schedules."""
        resume_from = None
        if job.resume_of is not None:
            if job.checkpoint_path is None:
                return None
            resume_from = newest_valid_checkpoint(job.checkpoint_path)
            if resume_from is None:
                return None  # let the Supervisor surface the failure
        return self._mux_admit_with(job, resume_from)

    def _mux_admit_with(self, job: Job, resume_from: Optional[str]):
        """The group-lookup/admit loop with an explicit resume image
        (the Supervisor's retry path passes the newest valid generation
        of the tenant's own checkpoint)."""
        from .mux import MuxGroup

        key = (job.program_key, job.spec["engine"],
               tuple(sorted(job.spec["knobs"].items())))
        try:
            for _ in range(2):
                with self._mux_lock:
                    group = self._mux_groups.get(key)
                    if group is None or group.closed:
                        trace = os.path.join(
                            self.data_dir,
                            f"mux-{len(self._mux_all):03d}"
                            ".trace.jsonl")
                        group = MuxGroup(
                            job.model, knobs=job.spec["knobs"],
                            program_cache=self.program_cache,
                            program_key=job.program_key,
                            trace_path=trace,
                            max_jobs=self._mux_max_jobs,
                            control=self._control)
                        self._mux_groups[key] = group
                        self._mux_all.append(group)
                handle = group.admit(
                    job.id, trace_path=job.trace_path,
                    checkpoint_path=job.checkpoint_path,
                    resume_from=resume_from)
                if handle is not None:
                    return handle
                with self._mux_lock:
                    if (self._mux_groups.get(key) is group
                            and group.closed):
                        # Drained-and-closed between lookup and admit:
                        # retry once against a fresh group.
                        self._mux_groups.pop(key, None)
                        continue
                return None  # every slot busy — run solo
        except Exception:  # noqa: BLE001 — admission is an
            # optimization; any failure routes to the solo engine
            return None
        return None

    def _finish(self, job: Job, state: str,
                error: Optional[str] = None) -> None:
        checker = job.checker
        result: Dict = {}
        if checker is not None:
            try:
                result["states"] = checker.state_count()
                result["unique"] = checker.unique_state_count()
                if state == "done":
                    result["properties"] = self._verdicts(job, checker)
                stats_fn = getattr(checker, "scheduler_stats", None)
                result["jit_cache"] = (
                    stats_fn().get("program_cache")
                    if callable(stats_fn) else None)  # None: host engine
            except Exception as e:  # noqa: BLE001 — a torn engine must
                # not mask the job outcome
                result["result_error"] = f"{type(e).__name__}: {e}"[:200]
        with self._lock:
            job.state = state
            job.error = error
            job.finished_t = time.monotonic()
            job.result = result
            tracer = job.tracer
            job.tracer = None
        if self._obs.enabled and job.started_t is not None:
            # Job latency observations from the stamps the record
            # already carries; breach/snapshot events ride the job's
            # own trace stream while it is still open.
            self._obs.job(
                queue_s=job.started_t - job.submitted_t,
                run_s=job.finished_t - job.started_t,
                total_s=job.finished_t - job.submitted_t,
                ok=(state == "done"),
                engine=job.spec["engine"], tracer=tracer)
        if self._control.armed:
            self._control.note_done(ok=(state == "done"))
        if tracer is not None:
            if state == "done":
                tracer.event("job_done", job=job.id,
                             states=result.get("states", 0),
                             unique=result.get("unique", 0),
                             _flush=True)
            else:
                reason = state if error is None \
                    else f"{state}: {error}"
                tracer.event("job_abort", job=job.id, reason=reason,
                             _flush=True)
            tracer.close()

    def _verdicts(self, job: Job, checker) -> List[List]:
        """Explorer-style property rows, filtered to the submission's
        selection: ``[expectation, name, encoded_discovery|None]``."""
        from ..explorer import _EXPECTATION_NAMES

        selected = job.spec.get("properties")
        discoveries = checker.discoveries()
        rows = []
        for prop in job.model.properties():
            if selected is not None and prop.name not in selected:
                continue
            path = discoveries.get(prop.name)
            rows.append([_EXPECTATION_NAMES[prop.expectation], prop.name,
                        path.encode() if path is not None else None])
        return rows

    # -- Introspection / control ------------------------------------------

    def _job(self, job_id: str) -> Job:
        job = self._jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return job

    def status(self, job_id: str) -> dict:
        job = self._job(job_id)
        with self._lock:
            payload = {
                "id": job.id,
                "state": job.state,
                "model": job.spec["model"],
                "params": job.spec["params"],
                "engine": job.spec["engine"],
                "knobs": job.spec["knobs"],
                "priority": job.spec.get("priority", 0),
                "tenant": job.spec.get("tenant"),
                "deadline_s": job.spec.get("deadline_s"),
                "resume_of": job.resume_of,
                "error": job.error,
                "runtime_s": (round(job.runtime(), 3)
                              if job.started_t is not None else None),
                "checkpoint": job.checkpoint_path,
            }
            checker, result, state = job.checker, dict(job.result), \
                job.state
        if state == "running" and checker is not None:
            try:
                payload["states"] = checker.state_count()
                payload["unique"] = checker.unique_state_count()
            except Exception:  # noqa: BLE001 — a mid-teardown engine
                pass
        else:
            payload.update(result)
        return payload

    def jobs(self) -> List[dict]:
        with self._lock:
            order = list(self._order)
        return [self.status(job_id) for job_id in order]

    def trace_file(self, job_id: str) -> str:
        return self._job(job_id).trace_path

    def control_status(self) -> Optional[dict]:
        """The controller block ``/.healthz`` / ``/.ops`` embed;
        ``None`` when disarmed (probes see the pre-round-21 shape)."""
        return (self._control.status() if self._control.armed
                else None)

    def preempt(self, job_id: str) -> dict:
        """``DELETE /jobs/<id>``: stop the job at its next safe point,
        keeping the checkpoint for a later ``resume`` submission.
        Queued jobs are CANCELLED: removed from the queue outright and
        recorded as ``job_abort`` with reason ``cancelled`` (they never
        ran, so there is nothing to resume). Running host-engine jobs
        cannot be preempted (no checkpoint to resume — 409)."""
        job = self._job(job_id)
        tracer = checker = None
        cancelled = False
        with self._lock:
            state = job.state
            if state == "queued":
                job.state = "cancelled"
                job.finished_t = time.monotonic()
                tracer = job.tracer
                job.tracer = None
                cancelled = True
            elif state == "running":
                # Gate on the ENGINE, not the checker instance: a
                # DELETE racing the engine build (checker still None)
                # must 409 for a host job rather than return success
                # for a preempt the host engine can never honor.
                if job.spec["engine"] == "host":
                    raise JobConflict(
                        f"job {job_id} runs on the host engine, which "
                        "cannot preempt to a checkpoint")
                job.preempt_requested = True
                checker = job.checker
            # already-terminal: fall through to the status no-op
        if cancelled:
            self._queue.cancel(job_id)
        if tracer is not None:
            tracer.event("job_abort", job=job_id,
                         reason="cancelled" if cancelled
                         else "preempted", _flush=True)
            tracer.close()
        if checker is not None:
            checker.preempt()
        return self.status(job_id)

    def metrics_lines(self) -> List[str]:
        """The ``stpu_job_*`` Prometheus families for ``/.metrics``."""
        with self._lock:
            jobs = [self._jobs[j] for j in self._order]
            states: Dict[str, int] = {}
            for job in jobs:
                states[job.state] = states.get(job.state, 0) + 1
        # Jobs-by-state is a gauge (a job LEAVES "queued"/"running" —
        # the series decrease, which counter semantics forbid).
        lines = ["# TYPE stpu_jobs gauge"]
        lines += [f'stpu_jobs{{state="{s}"}} {c}'
                  for s, c in sorted(states.items())]
        lines += ["# TYPE stpu_job_queue_depth gauge",
                  f"stpu_job_queue_depth {self._queue.qsize()}"]
        cache = self.program_cache.stats()
        lines += [
            "# TYPE stpu_job_program_cache_hits_total counter",
            f"stpu_job_program_cache_hits_total {cache['hits']}",
            "# TYPE stpu_job_program_cache_misses_total counter",
            f"stpu_job_program_cache_misses_total {cache['misses']}",
            "# TYPE stpu_job_program_cache_programs gauge",
            f"stpu_job_program_cache_programs {cache['programs']}",
            # The cache's OWN counter families (round 16): the
            # stpu_job_* names above predate them and stay for
            # dashboard compatibility; these are the canonical ones,
            # including evictions.
            "# TYPE stpu_program_cache_hits_total counter",
            f"stpu_program_cache_hits_total {cache['hits']}",
            "# TYPE stpu_program_cache_misses_total counter",
            f"stpu_program_cache_misses_total {cache['misses']}",
            "# TYPE stpu_program_cache_evictions_total counter",
            f"stpu_program_cache_evictions_total {cache['evictions']}",
            "# TYPE stpu_program_cache_programs gauge",
            f"stpu_program_cache_programs {cache['programs']}",
        ]
        per_job: List[str] = []
        for job in jobs:
            status = self.status(job.id)
            if status.get("states") is not None:
                per_job.append((job.id, "states", status["states"]))
            if status.get("unique") is not None:
                per_job.append((job.id, "unique", status["unique"]))
            if status.get("runtime_s") is not None:
                per_job.append((job.id, "seconds",
                                status["runtime_s"]))
        for fam, mtype in (("states", "counter"), ("unique", "counter"),
                           ("seconds", "gauge")):
            rows = [(j, v) for j, f, v in per_job if f == fam]
            if not rows:
                continue
            # Round-18 naming audit: counters end in ``_total``; the
            # deprecated bare duals shipped one round and are gone.
            name = (f"stpu_job_{fam}_total" if mtype == "counter"
                    else f"stpu_job_{fam}")
            lines.append(f"# TYPE {name} {mtype}")
            lines += [f'{name}{{job="{j}"}} {v}' for j, v in rows]
        if self._control.armed:
            lines += self._control.metrics_lines()
        if self._obs.enabled and self._obs.hist is not None:
            # Live latency histograms (_bucket/_sum/_count) — same
            # emission helper trace_export uses offline.
            lines += prometheus_hist_lines(self._obs.hist.snapshot())
        slo = self._obs.slo_status()
        if slo is not None:
            from ..obs.slo import prometheus_slo_lines

            lines += prometheus_slo_lines(slo)
        return lines

    def close(self, preempt_running: bool = True) -> None:
        """Stops the worker pool. Running device jobs are preempted
        (their checkpoints stay resumable); queued jobs are dropped."""
        # Controller first: its tick thread calls back into submit/
        # preempt, and its shutdown terminally acknowledges parks
        # (the trace's park-pairing invariant) before workers drain.
        self._control.close()
        if preempt_running:
            with self._lock:
                jobs = list(self._jobs.values())
            for job in jobs:
                try:
                    self.preempt(job.id)
                except (JobConflict, KeyError):
                    pass
        self._queue.close()
        for t in self._workers:
            t.join(timeout=30)
        with self._mux_lock:
            groups = list(self._mux_all)
        for group in groups:
            group.close()
        # Close any still-open submit tracers (queued jobs dropped
        # without ever running).
        with self._lock:
            tracers = [j.tracer for j in self._jobs.values()
                       if j.tracer is not None]
            for j in self._jobs.values():
                j.tracer = None
        for tracer in tracers:
            tracer.close()

"""Closed-loop overload control for the checking service (round 21).

Rounds 14/16/18 built the sensors — per-job latency histograms
(``obs/hist.py``), rolling error-budget SLOs (``obs/slo.py``),
``/.healthz``, the anomaly detector — but nothing acted on them: the
queue admitted until a fixed depth 429'd, mux floor shares were static,
and a long exhaustive check could starve interactive jobs until a human
DELETEd it. This module is the actuator side: a controller that turns
the SLO surface into admission, preemption, batch-sizing, and
degradation decisions, and recovers automatically when pressure clears.

Four control loops share one policy core (:class:`ControlPolicy` — pure
and deterministic: every input, including time, is an explicit
argument, so the fast tier drives it on synthetic SLO streams with no
device in sight):

- **SLO-driven admission.** When the error budget burns
  (``burn >= burn_high`` on any objective), the admission gate engages:
  lowest-priority submissions are shed first (HTTP 429 + ``Retry-After``
  computed from the observed drain rate), and per-tenant token buckets
  bound how fast a retrying client can re-enter — a tight retry loop
  cannot amplify the overload it is reacting to. The gate disengages
  with hysteresis (burn must stay under ``burn_low`` for ``recover_s``
  seconds), so admission does not flap on a noisy boundary.
- **Deadline-aware preemption.** Jobs may declare ``deadline_s``; when
  a queued interactive job's deadline is at risk, the controller parks
  the longest-running exhaustive check through the existing cooperative
  ``preempt()`` → checkpoint path and auto-resumes it from its own
  generation when pressure clears. Work is parked, never lost: the
  resumed run's counters are bit-identical to an unpreempted run (the
  round-14 preempt→resume pin, now exercised by a machine policy).
- **Adaptive mux sizing.** :class:`~stateright_tpu.service.mux.MuxGroup`
  waves consult :meth:`ControlPolicy.mux_budget`: the batch budget is
  stepped down the group's bucket ladder while the observed per-wave
  latency quantile (per program key, from a live histogram) exceeds
  ``wave_target_s`` — bounded below by the fairness floor (every tenant
  keeps at least its floor share of the kept bucket).
- **Brownout ladder.** Under sustained pressure the controller steps
  down a declared degrade ladder — shed the top batch bucket rung
  (reusing the round-10 grow-OOM degrade semantics at the mux level),
  then widen checkpoint cadence, then pause background soak jobs
  (priority < 0 held in queue, not dropped) — one edge-triggered
  schema-v14 ``controller`` event per transition with
  ``requested``/``kept`` honesty, stepping back up hysteretically one
  rung per ``recover_rung_s``.

Armed via ``STpu_CONTROL`` (``1`` or comma-separated ``k=v`` knob
overrides, the ``STpu_SLO`` grammar). Disarmed, every call site holds
the shared :data:`NULL_CONTROL` and pays one ``.armed`` attribute check
— the house poisoned-null contract: the null object has NO decision
methods, so an unguarded hot-path call is an ``AttributeError`` in the
fast tier, not a silent policy evaluation.

The armed controller writes its own trace stream
(``<data_dir>/control.trace.jsonl``): ``admit``/``shed``/``park``/
``resume``/``controller`` events (schema v14) that
``tools/trace_lint.py`` checks end to end — every shed carries a
reason, every park is eventually resumed or terminally aborted, and
consecutive ``controller`` events must change rung.

Single-host honesty: the controller observes and actuates ONE process'
job service. It is the control loop a fleet scheduler would run per
replica; cross-replica coordination is not claimed.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs.hist import HistogramSet
#: Shed-reason vocabulary — canonical home is the schema module (so
#: jax-free consumers like tools/trace_lint.py validate it without
#: pulling this package); re-exported here for call sites.
from ..obs.schema import SHED_REASONS
from ..obs.tracer import RunTracer
from ..resilience.faults import fault_plan_from_env

__all__ = [
    "CONTROL_ENV", "RUNG_ACTIONS", "SHED_REASONS", "ControlPolicy",
    "OverloadController", "NullControl", "NULL_CONTROL",
    "control_from_env",
]

#: Environment knob: ``STpu_CONTROL=1`` arms the defaults; ``k=v``
#: pairs override policy knobs (see :func:`control_from_env`). Unset
#: means the shared :data:`NULL_CONTROL`.
CONTROL_ENV = "STpu_CONTROL"

#: The brownout ladder, rung by rung. Rung 0 is normal service; each
#: deeper rung ADDS its degradation to the previous ones. Recovery
#: transitions (stepping back up) carry action ``restore``.
RUNG_ACTIONS = ("normal", "shed_batch_rung", "widen_ckpt", "pause_soak")


#: Waves observed per program key before the adaptive mux budget
#: trusts the latency quantile (a single slow outlier must not halve
#: the ladder).
_MUX_MIN_WAVES = 8


class ControlPolicy:
    """The deterministic decision core. All state transitions are
    driven by explicit ``now`` arguments — wall clock in the live
    service, simulated time in ``tools/traffic_gen.py`` and the unit
    tests — so the same input stream always yields the same shed set,
    the same rung walk, and the same budgets.

    Not thread-safe by itself; :class:`OverloadController` serializes
    access (the simulator and the tests are single-threaded)."""

    def __init__(self, *, burn_high: float = 1.0, burn_low: float = 0.5,
                 recover_s: float = 2.0, shed_below: int = 1,
                 tenant_rate: float = 4.0, tenant_burst: float = 8.0,
                 retry_min_s: float = 0.1, retry_max_s: float = 30.0,
                 deadline_margin_s: float = 0.5,
                 min_park_run_s: float = 0.05,
                 rung_dwell_s: float = 2.0, recover_rung_s: float = 2.0,
                 max_rung: int = 3, wave_target_s: float = 0.5,
                 ckpt_widen: int = 4):
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.recover_s = float(recover_s)
        self.shed_below = int(shed_below)
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = float(tenant_burst)
        self.retry_min_s = float(retry_min_s)
        self.retry_max_s = float(retry_max_s)
        self.deadline_margin_s = float(deadline_margin_s)
        self.min_park_run_s = float(min_park_run_s)
        self.rung_dwell_s = float(rung_dwell_s)
        self.recover_rung_s = float(recover_rung_s)
        self.max_rung = max(0, min(int(max_rung), len(RUNG_ACTIONS) - 1))
        self.wave_target_s = float(wave_target_s)
        self.ckpt_widen = max(1, int(ckpt_widen))

        self.engaged = False
        self.rung = 0
        self._rung_t: Optional[float] = None
        self._cool_since: Optional[float] = None
        #: tenant label -> [tokens, refill timestamp]
        self._buckets: Dict[str, list] = {}
        #: observed completions/s (EWMA over inter-completion gaps);
        #: the Retry-After denominator. Starts at 1 job/s — a cold
        #: service quotes conservative but bounded retry times.
        self._drain = 1.0
        self._last_done: Optional[float] = None
        #: per-program-key wave-latency histograms feeding the adaptive
        #: mux budget (fixed power-of-two buckets — deterministic).
        self._wave_hist = HistogramSet()
        self._wave_counts: Dict[str, int] = {}

    # -- Engagement + brownout ladder --------------------------------------

    def observe(self, now: float, burn: float,
                queue_depth: int) -> List[dict]:
        """One control tick: updates the admission gate (hysteretic)
        and the brownout rung; returns the rung transitions to emit
        (edge-triggered — empty list means no change)."""
        if burn >= self.burn_high:
            self._cool_since = None
            if not self.engaged:
                self.engaged = True
                self._rung_t = now
        elif self.engaged:
            if burn <= self.burn_low:
                if self._cool_since is None:
                    self._cool_since = now
                elif now - self._cool_since >= self.recover_s:
                    self.engaged = False
                    self._cool_since = None
                    self._rung_t = now
            else:
                self._cool_since = None

        transitions: List[dict] = []
        if self._rung_t is None:
            self._rung_t = now
        if self.engaged:
            steps = int((now - self._rung_t) // self.rung_dwell_s)
            if steps > 0:
                requested = self.rung + steps
                kept = min(requested, self.max_rung)
                self._rung_t = now
                if kept != self.rung:
                    self.rung = kept
                    transitions.append({
                        "rung": kept, "action": RUNG_ACTIONS[kept],
                        "requested": requested, "kept": kept})
        elif self.rung > 0:
            steps = int((now - self._rung_t) // self.recover_rung_s)
            if steps > 0:
                requested = max(0, self.rung - steps)
                self._rung_t = now
                if requested != self.rung:
                    self.rung = requested
                    transitions.append({
                        "rung": requested, "action": "restore",
                        "requested": requested, "kept": requested})
        return transitions

    # -- Admission ---------------------------------------------------------

    def admission(self, now: float, tenant: Optional[str],
                  priority: int,
                  queue_depth: int) -> Optional[Tuple[str, float]]:
        """One admission decision: ``None`` admits; otherwise a
        ``(reason, retry_after_s)`` shed. Only consulted while work can
        still be shed cheaply — the caller rejects BEFORE allocating a
        job record. The engaged gate sheds below ``shed_below``; the
        brownout ladder raises the floor by exactly ONE class (rung
        1's shed action) — deeper rungs degrade via cadence and the
        soak hold, so high-priority interactive traffic is never shed
        by the ladder, only bounded by its tenant's retry budget."""
        if not self.engaged:
            return None
        floor = self.shed_below + (1 if self.rung >= 1 else 0)
        if priority < floor:
            reason = "slo_burn" if priority < self.shed_below \
                else "brownout"
            return reason, self.retry_after(queue_depth)
        if not self._take_token(tenant or "", now):
            return "retry_budget", self.retry_after(queue_depth)
        return None

    def _take_token(self, tenant: str, now: float) -> bool:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = self._buckets[tenant] = [self.tenant_burst, now]
        tokens, t0 = bucket
        tokens = min(self.tenant_burst,
                     tokens + (now - t0) * self.tenant_rate)
        if tokens < 1.0:
            bucket[0], bucket[1] = tokens, now
            return False
        bucket[0], bucket[1] = tokens - 1.0, now
        return True

    def retry_after(self, queue_depth: int) -> float:
        """Seconds until the queue's current depth drains at the
        observed completion rate — what the 429's ``Retry-After``
        carries, clamped to keep a cold drain estimate honest."""
        est = (queue_depth + 1) / max(self._drain, 1e-3)
        return round(min(self.retry_max_s, max(self.retry_min_s, est)),
                     3)

    def note_done(self, now: float) -> None:
        """Feeds the drain-rate EWMA one job completion."""
        if self._last_done is not None:
            gap = now - self._last_done
            if gap > 0:
                self._drain = 0.7 * self._drain + 0.3 * (1.0 / gap)
        self._last_done = now

    # -- Deadline risk -----------------------------------------------------

    def deadline_at_risk(self, now: float, submitted_t: float,
                         deadline_s: float, queued: bool) -> bool:
        """Whether a deadline job needs intervention: its remaining
        slack is within the safety margin plus (for a still-queued job)
        one expected drain interval — the soonest a worker could
        plausibly reach it."""
        left = submitted_t + deadline_s - now
        need = self.deadline_margin_s
        if queued:
            need += 1.0 / max(self._drain, 1e-3)
        return left <= need

    # -- Adaptive mux sizing -----------------------------------------------

    def note_wave(self, key, dur_s: float,
                  compiled: bool = False) -> None:
        """Feeds one mux group wave's latency. Compile waves are
        excluded — a lazy XLA build would read as a latency regression
        and halve the ladder for nothing."""
        if compiled:
            return
        label = repr(key)
        self._wave_hist.observe("control_wave_s", dur_s, key=label)
        self._wave_counts[label] = self._wave_counts.get(label, 0) + 1

    def mux_budget(self, key, buckets, n_tenants: int) -> int:
        """The adapted per-wave batch budget for a mux group with the
        given bucket ladder: steps down the ladder while the observed
        p90 wave latency for this program key exceeds the target
        (halving the batch is modeled as halving the wave), plus one
        rung while the brownout ladder is at ``shed_batch_rung`` or
        deeper. Bounded below by the smallest bucket and by one row per
        tenant — the existing fairness floor survives adaptation."""
        label = repr(key)
        shift = 0
        if self._wave_counts.get(label, 0) >= _MUX_MIN_WAVES:
            p90 = self._wave_hist.quantile("control_wave_s", 0.9,
                                           key=label)
            if p90 is not None:
                while (p90 > self.wave_target_s
                       and shift < len(buckets) - 1):
                    p90 /= 2.0
                    shift += 1
        if self.rung >= 1:
            shift += 1
        shift = min(shift, len(buckets) - 1)
        return max(int(buckets[len(buckets) - 1 - shift]),
                   int(n_tenants))

    # -- Brownout actuation knobs -----------------------------------------

    def ckpt_every(self, base: int) -> int:
        """Checkpoint cadence under the ladder: rung 2+ widens it by
        ``ckpt_widen`` (fewer safe-point stalls while overloaded;
        counters are cadence-independent, so bit-identity holds)."""
        if self.rung >= 2:
            return max(1, int(base)) * self.ckpt_widen
        return int(base)

    def hold_below(self) -> Optional[int]:
        """Queue-hold priority floor: at rung 3 background soak jobs
        (priority < 0 by service convention) are HELD in the queue —
        paused, not dropped — until the ladder steps back up."""
        return 0 if self.rung >= 3 else None


class OverloadController:
    """The armed controller: wraps one :class:`ControlPolicy` with a
    tick thread, the service actuators (park / auto-resume / queue
    hold), the v14 event stream, and the two fault points
    (``admit_fault`` / ``preempt_wedge``) that drill its own
    crash-safety. One instance serves one :class:`JobService`."""

    armed = True

    def __init__(self, policy: Optional[ControlPolicy] = None,
                 tick_s: float = 0.05):
        self.policy = policy or ControlPolicy()
        self._tick_s = max(0.005, float(tick_s))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._service = None
        self._tracer: Optional[RunTracer] = None
        self.trace_path: Optional[str] = None
        self.shed_total = 0
        self.admitted_under_pressure = 0
        self.park_total = 0
        self.resume_total = 0
        #: tick-thread exceptions survived (fault drills land here —
        #: the controller must crash safely, not wedge the service).
        self.fault_count = 0
        #: victim -> reason: preempt requested, park not yet observed.
        self._park_pending: Dict[str, str] = {}
        #: victim -> reason: ``park`` emitted, awaiting auto-resume.
        self._parked: Dict[str, str] = {}
        #: victim -> continuation job id.
        self._resumed: Dict[str, str] = {}

    # -- Lifecycle ---------------------------------------------------------

    def bind(self, service, trace_path: Optional[str] = None) -> None:
        """Attaches to a service and starts the tick loop. Called once
        by ``JobService.__init__``."""
        self._service = service
        self.trace_path = trace_path or os.path.join(
            service.data_dir, "control.trace.jsonl")
        self._tracer = RunTracer(self.trace_path, "service",
                                 meta={"control": True})
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="stpu-control")
        self._thread.start()

    def close(self) -> None:
        """Stops the loop; parks still outstanding are terminally
        acknowledged (``job_abort``) so the control stream's
        park-pairing invariant holds across a shutdown."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        with self._lock:
            parked = dict(self._parked)
            self._parked.clear()
            self._park_pending.clear()
        for jid, reason in sorted(parked.items()):
            self._event("job_abort", job=jid,
                        reason=f"parked at shutdown ({reason})")
        if self._tracer is not None:
            self._tracer.close()
            self._tracer = None

    def _event(self, etype: str, **fields) -> None:
        tracer = self._tracer
        if tracer is not None and tracer.enabled:
            tracer.event(etype, _flush=True, **fields)

    # -- Admission-path hooks (called from JobService.submit) --------------

    def admission(self, tenant: Optional[str], priority: int,
                  queue_depth: int,
                  now: Optional[float] = None
                  ) -> Optional[Tuple[str, float]]:
        """The submit-time gate: ``None`` admits, else the shed
        ``(reason, retry_after_s)`` (the service maps it to 429 +
        ``Retry-After``). The ``admit_fault`` injection fires here —
        BEFORE any state mutates, so a crashed decision fails exactly
        one request and leaks nothing."""
        plan = fault_plan_from_env()
        if plan.active:
            plan.crash("admit_fault", self._tracer)
        now = time.monotonic() if now is None else now
        with self._lock:
            decision = self.policy.admission(now, tenant, int(priority),
                                             int(queue_depth))
            if decision is not None:
                self.shed_total += 1
        if decision is not None:
            reason, retry_after = decision
            self._event("shed", tenant=tenant or "",
                        priority=int(priority), reason=reason,
                        retry_after_s=float(retry_after))
        return decision

    def note_admitted(self, job_id: str, tenant: Optional[str],
                      priority: int, queue_depth: int) -> None:
        """Records a submission that cleared an ENGAGED gate (quiet
        admissions are not events — the stream records decisions made
        under pressure, not every arrival)."""
        with self._lock:
            engaged = self.policy.engaged
            if engaged:
                self.admitted_under_pressure += 1
        if engaged:
            self._event("admit", job=job_id, tenant=tenant or "",
                        priority=int(priority),
                        queue_depth=int(queue_depth))

    def note_queue_full(self, tenant: Optional[str],
                        priority: int, queue_depth: int) -> float:
        """A bounded-queue overflow under an armed controller: counted
        and evented as a shed (reason ``queue_full``), returns the
        drain-derived Retry-After for the 429."""
        with self._lock:
            self.shed_total += 1
            retry_after = self.policy.retry_after(int(queue_depth))
        self._event("shed", tenant=tenant or "", priority=int(priority),
                    reason="queue_full",
                    retry_after_s=float(retry_after))
        return retry_after

    def retry_after(self) -> float:
        svc = self._service
        depth = svc._queue.qsize() if svc is not None else 0
        with self._lock:
            return self.policy.retry_after(depth)

    def note_done(self, ok: bool = True,
                  now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self.policy.note_done(now)

    # -- Engine-side hooks -------------------------------------------------

    def note_wave(self, key, dur_s: float,
                  compiled: bool = False) -> None:
        with self._lock:
            self.policy.note_wave(key, dur_s, compiled=compiled)

    def mux_budget(self, key, buckets, n_tenants: int) -> int:
        with self._lock:
            return self.policy.mux_budget(key, buckets, n_tenants)

    def ckpt_every(self, base: int) -> int:
        with self._lock:
            return self.policy.ckpt_every(base)

    # -- The control loop --------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self._tick_s):
            try:
                self._tick(time.monotonic())
            except Exception:  # noqa: BLE001 — the controller must
                # survive its own crashes (admit_fault/preempt_wedge
                # drills); a wedged tick must not take the loop down.
                with self._lock:
                    self.fault_count += 1

    def _tick(self, now: float) -> None:
        svc = self._service
        if svc is None:
            return
        slo = svc._obs.slo_status()
        burn = 0.0
        if slo is not None:
            burn = max((obj.get("burn", 0.0) or 0.0
                        for obj in slo["objectives"].values()),
                       default=0.0)
        depth = svc._queue.qsize()
        with self._lock:
            transitions = self.policy.observe(now, burn, depth)
            hold = self.policy.hold_below()
        for tr in transitions:
            self._event("controller", rung=tr["rung"],
                        action=tr["action"],
                        requested=tr["requested"], kept=tr["kept"])
        if transitions:
            svc._queue.set_hold(hold)

        self._settle_parks(svc)
        at_risk = self._scan_deadlines(svc, now)
        self._maybe_resume(svc, at_risk)

    def _settle_parks(self, svc) -> None:
        """Moves requested parks to parked once the victim's drain
        lands (state ``preempted``); a victim that raced to completion
        is simply dropped — nothing was parked, so no event."""
        with self._lock:
            pending = list(self._park_pending.items())
        for jid, reason in pending:
            try:
                state = svc._job(jid).state
            except KeyError:
                state = "failed"
            if state == "preempted":
                with self._lock:
                    self._park_pending.pop(jid, None)
                    self._parked[jid] = reason
                    self.park_total += 1
                self._event("park", job=jid, reason=reason)
            elif state not in ("running", "queued"):
                with self._lock:
                    self._park_pending.pop(jid, None)

    def _scan_deadlines(self, svc, now: float) -> bool:
        """Parks the longest-running preemptible check when a queued
        deadline job is at risk; returns whether any deadline is still
        at risk (suppresses auto-resume)."""
        with svc._lock:
            records = [(j.id, j.state, j.spec, j.submitted_t,
                        j.started_t) for j in svc._jobs.values()]
        with self._lock:
            at_risk = [
                jid for jid, state, spec, sub_t, _ in records
                if state in ("queued", "running")
                and spec.get("deadline_s") is not None
                and self.policy.deadline_at_risk(
                    now, sub_t, float(spec["deadline_s"]),
                    queued=(state == "queued"))]
            queued_risk = [
                jid for jid, state, spec, sub_t, _ in records
                if state == "queued" and jid in at_risk]
            busy = bool(self._park_pending)
            excluded = (set(self._park_pending) | set(self._parked)
                        | set(self._resumed))
        if not queued_risk or busy:
            return bool(at_risk)
        victims = [
            (now - started_t, jid)
            for jid, state, spec, _, started_t in records
            if state == "running" and started_t is not None
            and spec.get("engine") != "host"
            and spec.get("deadline_s") is None
            and jid not in excluded
            and now - started_t >= self.policy.min_park_run_s]
        if not victims:
            return bool(at_risk)
        _, victim = max(victims)
        # preempt_wedge: the park actuation dies mid-flight (models a
        # wedged checkpoint write at the drain rest point). The raise
        # lands in _loop's survival handler: the victim keeps running
        # under its Supervisor, nothing is half-parked, and a later
        # tick retries.
        plan = fault_plan_from_env()
        if plan.active:
            plan.crash("preempt_wedge", self._tracer)
        svc.preempt(victim)
        with self._lock:
            self._park_pending[victim] = "deadline"
        return bool(at_risk)

    def _maybe_resume(self, svc, at_risk: bool) -> None:
        """Auto-resumes the oldest parked job once pressure is off:
        gate disengaged, no deadline currently at risk, and nothing
        mid-park."""
        with self._lock:
            if (self.policy.engaged or at_risk or self._park_pending
                    or not self._parked):
                return
            jid = sorted(self._parked)[0]
        from .jobs import JobConflict

        try:
            payload = svc.submit({"resume": jid})
            rid = payload["id"]
        except JobConflict:
            # Resumed externally while parked: the continuation id on
            # the record keeps the park/resume pairing honest.
            try:
                rid = svc._job(jid).resumed_by
            except KeyError:
                rid = None
            if rid is None:
                return
        except KeyError:
            return
        with self._lock:
            self._parked.pop(jid, None)
            self._resumed[jid] = rid
            self.resume_total += 1
        self._event("resume", job=jid, resumed_as=rid)

    # -- Introspection -----------------------------------------------------

    def status(self) -> dict:
        """The controller block ``/.healthz`` and ``/.ops`` embed."""
        svc = self._service
        with self._lock:
            return {
                "armed": True,
                "engaged": self.policy.engaged,
                "rung": self.policy.rung,
                "rung_action": RUNG_ACTIONS[self.policy.rung],
                "queue_depth": (svc._queue.qsize()
                                if svc is not None else 0),
                "shed_total": self.shed_total,
                "admitted_under_pressure": self.admitted_under_pressure,
                "parked": sorted(set(self._park_pending)
                                 | set(self._parked)),
                "park_total": self.park_total,
                "resume_total": self.resume_total,
                "faults_survived": self.fault_count,
            }

    def metrics_lines(self) -> List[str]:
        st = self.status()
        return [
            "# TYPE stpu_control_shed_total counter",
            f"stpu_control_shed_total {st['shed_total']}",
            "# TYPE stpu_control_park_total counter",
            f"stpu_control_park_total {st['park_total']}",
            "# TYPE stpu_control_resume_total counter",
            f"stpu_control_resume_total {st['resume_total']}",
            "# TYPE stpu_control_rung gauge",
            f"stpu_control_rung {st['rung']}",
            "# TYPE stpu_control_engaged gauge",
            f"stpu_control_engaged {1 if st['engaged'] else 0}",
            "# TYPE stpu_control_parked gauge",
            f"stpu_control_parked {len(st['parked'])}",
        ]


class NullControl:
    """The disarmed controller: ``armed`` is False and ONLY the
    lifecycle no-ops exist. Decision methods are deliberately absent —
    a hot path that forgets its ``if control.armed:`` guard fails loud
    (poisoned null), instead of silently evaluating policy on every
    submission."""

    __slots__ = ()
    armed = False

    def bind(self, service, trace_path=None) -> None:
        pass

    def close(self) -> None:
        pass


#: The shared disarmed controller (identity-testable, like
#: ``NULL_TRACER`` / ``NULL_PLAN`` / ``NULL_OBS``).
NULL_CONTROL = NullControl()

#: ``k=v`` keys ``control_from_env`` forwards to ControlPolicy.
_POLICY_KEYS = {
    "burn_high": float, "burn_low": float, "recover_s": float,
    "shed_below": int, "tenant_rate": float, "tenant_burst": float,
    "retry_min_s": float, "retry_max_s": float,
    "deadline_margin_s": float, "min_park_run_s": float,
    "rung_dwell_s": float, "recover_rung_s": float, "max_rung": int,
    "wave_target_s": float, "ckpt_widen": int,
}


def control_from_env(spec: Optional[str] = None):
    """The factory every service uses: ``STpu_CONTROL`` unset (or
    ``0``) returns the shared :data:`NULL_CONTROL`; ``1`` arms the
    default policy; comma-separated ``k=v`` pairs override policy
    knobs plus ``tick`` (the loop cadence, seconds). Unknown keys are
    ignored — forward compatibility beats a crashed service (the
    ``STpu_SLO`` contract)."""
    spec = os.environ.get(CONTROL_ENV, "") if spec is None else spec
    spec = (spec or "").strip()
    if spec in ("", "0"):
        return NULL_CONTROL
    kwargs: Dict[str, object] = {}
    tick_s = 0.05
    if spec != "1":
        for part in spec.split(","):
            key, sep, value = part.partition("=")
            key = key.strip()
            if not sep:
                continue
            if key == "tick":
                try:
                    tick_s = float(value)
                except ValueError:
                    pass
                continue
            want = _POLICY_KEYS.get(key)
            if want is None:
                continue
            try:
                kwargs[key] = want(value)
            except ValueError:
                pass
    return OverloadController(ControlPolicy(**kwargs), tick_s=tick_s)

"""Differential fuzz harness: cross-validates a model's device form
against the host semantics — the cheap gate every corpus addition runs
through before the service will serve it (ROADMAP item 5).

Two complementary checks:

- :func:`diff_walk` replays **random seeded schedules**: starting from
  a random init state, it repeatedly (a) enumerates the host model's
  actions and applies ``next_state`` (dropping ignored actions and
  boundary-pruned successors), (b) runs the device ``step`` on the
  encoded state and keeps the valid, in-boundary rows, (c) asserts the
  two successor multisets agree *as encoded vectors* (catching both a
  wrong transition and a non-injective codec), and (d) asserts every
  property predicate agrees on the state — then follows one random
  successor. Because the walk compares per-state, a disagreement
  pinpoints the exact state and the exact successor set, which a
  whole-run count mismatch cannot.
- :func:`diff_check` runs the real engines end to end — host BFS vs
  the device engine — and asserts state/unique counts and the
  discovered-property sets agree (the BASELINE-style parity gate).

:func:`fuzz_gate` composes both over a registry entry; the service's
tests run it for every corpus model, and
``tools/diff_check.py`` exposes it as a CLI.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

__all__ = ["DiffMismatch", "diff_walk", "diff_check", "fuzz_gate"]


class DiffMismatch(AssertionError):
    """The device form disagreed with the host semantics."""


def _encode(dm, state) -> np.ndarray:
    return np.asarray(dm.encode(state), np.uint32)


def _host_successors(model, dm, state) -> List[bytes]:
    """The host model's boundary-filtered successor set, as encoded
    device vectors (bytes, for multiset comparison)."""
    actions: List = []
    model.actions(state, actions)
    out: List[bytes] = []
    for action in actions:
        succ = model.next_state(state, action)
        if succ is None:
            continue
        if not model.within_boundary(succ):
            continue
        out.append(_encode(dm, succ).tobytes())
    return out


def _device_successors(dm, step_fn, boundary_fn, vec) -> List[bytes]:
    succ, valid = step_fn(vec)
    succ = np.asarray(succ, np.uint32)
    valid = np.asarray(valid, bool)
    out: List[bytes] = []
    for row, ok in zip(succ, valid):
        if not ok:
            continue
        if boundary_fn is not None and not bool(boundary_fn(row)):
            continue
        if dm.error_lane is not None and int(row[dm.error_lane]) != 0:
            raise DiffMismatch(
                f"device successor set the error lane "
                f"({dm.error_lane}): encoding capacity exceeded — "
                "raise the bound (e.g. net_slots) before registering")
        out.append(row.tobytes())
    return out


def diff_walk(model, dm, *, seed: int, steps: int = 50) -> Dict:
    """One seeded random schedule; raises :class:`DiffMismatch` on the
    first disagreement. Returns ``{"steps", "transitions"}``."""
    import jax
    import jax.numpy as jnp

    rng = random.Random(seed)
    # The jitted programs are stashed on the device-model instance so
    # consecutive walks (fuzz_gate runs several seeds) compile once.
    step_fn = getattr(dm, "_diff_step_fn", None)
    if step_fn is None:
        step_fn = dm._diff_step_fn = jax.jit(dm.step)
    boundary_fn = getattr(dm, "_diff_boundary_fn", None)
    if boundary_fn is None:
        bnd = dm.boundary(jnp.zeros((dm.state_width,), jnp.uint32))
        boundary_fn = jax.jit(dm.boundary) if bnd is not None else None
        dm._diff_boundary_fn = boundary_fn

    prop_fns = dm.device_properties()
    properties = model.properties()

    inits = [s for s in model.init_states() if model.within_boundary(s)]
    state = rng.choice(inits)
    transitions = 0
    for step_no in range(steps):
        vec = _encode(dm, state)
        # Codec round trip: decode(encode(s)) must re-encode identically
        # (injectivity's observable half).
        if _encode(dm, dm.decode(vec)).tobytes() != vec.tobytes():
            raise DiffMismatch(
                f"seed {seed} step {step_no}: encode/decode round trip "
                f"diverged for state {state!r}")
        # Property agreement on the CURRENT state.
        for prop in properties:
            fn = prop_fns.get(prop.name)
            if fn is None:
                continue
            host_v = bool(prop.condition(model, state))
            dev_v = bool(fn(jnp.asarray(vec)))
            if host_v != dev_v:
                raise DiffMismatch(
                    f"seed {seed} step {step_no}: property "
                    f"{prop.name!r} disagrees (host={host_v} "
                    f"device={dev_v}) on state {state!r}")
        host = _host_successors(model, dm, state)
        dev = _device_successors(dm, step_fn, boundary_fn,
                                 jnp.asarray(vec))
        if sorted(host) != sorted(dev):
            host_set, dev_set = set(host), set(dev)
            missing = [np.frombuffer(b, np.uint32)
                       for b in host_set - dev_set]
            extra = [np.frombuffer(b, np.uint32)
                     for b in dev_set - host_set]
            raise DiffMismatch(
                f"seed {seed} step {step_no}: successor sets disagree "
                f"on state {state!r} (host {len(host)} rows, device "
                f"{len(dev)}): device missing {missing[:3]!r}, device "
                f"extra {extra[:3]!r}")
        transitions += len(host)
        if not host:
            # Terminal: restart the schedule from a random init.
            state = rng.choice(inits)
            continue
        state = model.next_state(
            state, _pick_action(model, state, rng, host))
    return {"steps": steps, "transitions": transitions}


def _pick_action(model, state, rng: random.Random, host: List[bytes]):
    """A random action whose successor survives the boundary (so the
    walk follows exactly the transitions it just compared)."""
    actions: List = []
    model.actions(state, actions)
    viable = [a for a in actions
              if (s := model.next_state(state, a)) is not None
              and model.within_boundary(s)]
    return rng.choice(viable)


def diff_check(model, *, batch_size: int = 64, fused: bool = False,
               target_state_count: Optional[int] = None) -> Dict:
    """Engine-level parity: host BFS vs the device engine on the same
    model. With ``target_state_count`` both runs are capped (the device
    wave overshoots a cap, so capped runs compare verdict SUBSETS only;
    uncapped runs compare exact counts)."""
    host_b = model.checker()
    dev_b = model.checker()
    if target_state_count:
        host_b.target_state_count(target_state_count)
        dev_b.target_state_count(target_state_count)
    host = host_b.spawn_bfs().join()
    dev = dev_b.spawn_tpu_bfs(batch_size=batch_size, fused=fused).join()
    result = {
        "host_unique": host.unique_state_count(),
        "host_states": host.state_count(),
        "device_unique": dev.unique_state_count(),
        "device_states": dev.state_count(),
        "host_discoveries": sorted(host.discoveries()),
        "device_discoveries": sorted(dev.discoveries()),
    }
    if not target_state_count:
        if (result["host_unique"] != result["device_unique"]
                or result["host_states"] != result["device_states"]):
            raise DiffMismatch(f"count mismatch: {result}")
        if result["host_discoveries"] != result["device_discoveries"]:
            raise DiffMismatch(f"verdict mismatch: {result}")
    return result


def fuzz_gate(name: str, *, registry=None, params: Optional[dict] = None,
              seeds=(0, 1, 2, 3), steps: int = 40,
              full: bool = True, batch_size: int = 64) -> Dict:
    """The corpus admission gate for one registered model: seeded
    random-schedule walks plus (optionally) the end-to-end engine
    parity check. Raises :class:`DiffMismatch` on any disagreement."""
    from .registry import default_registry

    registry = registry or default_registry()
    model, resolved = registry.build(name, params)
    factory = getattr(model, "device_model", None)
    if factory is None:
        raise DiffMismatch(
            f"model {name!r} has no device form — nothing to "
            "cross-validate (host-only corpus entries are not servable "
            "on the device engines)")
    dm = factory()
    result: Dict = {"model": name, "params": resolved, "walks": []}
    for seed in seeds:
        result["walks"].append(dict(
            diff_walk(model, dm, seed=seed, steps=steps), seed=seed))
    if full:
        result["engine_parity"] = diff_check(model, batch_size=batch_size)
    return result

"""``ModelRegistry``: the checking service's protocol corpus.

The service checks models *by name*: a job names a corpus entry plus
parameters, and the registry builds the host model (with its device
form attached where one exists) and produces the **canonical parameter
key** that scopes cross-job compiled-program sharing — two jobs may
share wave programs exactly when their ``(name, canonical params)``
agree, because the registry guarantees that key builds a semantically
identical model every time (``jit_cache.WaveProgramCache``'s safety
condition).

The default corpus names the repo's eight existing models — the raw
models (2pc, increment, increment-lock, sliding-puzzle) and the actor
systems (paxos, ABD, single-copy, ping-pong) — plus the round-14
addition: ``vsr``, a viewstamped-replication-style primary/backup
protocol with view change (``actor/viewstamped.py``), the corpus's
actor-path workout. Every entry is expected to pass the differential
fuzz gate (``service/diff.py``) — the cheap cross-validation every
future corpus addition runs through before it is servable.

Example model modules live under ``examples/`` as plain scripts (not a
package), so the registry extends ``sys.path`` the same way the test
suite does.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

__all__ = ["CorpusEntry", "ModelRegistry", "default_registry"]

_EXAMPLES_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "examples")


def _examples():
    """Makes the ``examples/`` scripts importable (idempotent)."""
    if _EXAMPLES_DIR not in sys.path:
        sys.path.insert(0, _EXAMPLES_DIR)


@dataclass(frozen=True)
class CorpusEntry:
    """One registered model: ``build(**params)`` returns a host model
    ready for ``checker()`` (device form attached where available);
    ``defaults`` double as the parameter schema — unknown keys are
    rejected and values are coerced to the default's type."""
    name: str
    build: Callable
    defaults: Dict[str, object]
    doc: str


class ModelRegistry:
    def __init__(self):
        self._entries: Dict[str, CorpusEntry] = {}
        self._lock = threading.Lock()

    def register(self, name: str, build: Callable,
                 defaults: Optional[Dict[str, object]] = None,
                 doc: str = "") -> None:
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            self._entries[name] = CorpusEntry(
                name, build, dict(defaults or {}), doc)

    def names(self):
        with self._lock:
            return sorted(self._entries)

    def entry(self, name: str) -> CorpusEntry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(
                f"unknown model {name!r}; registered: {self.names()}")
        return entry

    def describe(self):
        """The corpus listing (``GET /.corpus``): name, docstring, and
        the parameter schema with defaults."""
        return [{"name": e.name, "doc": e.doc, "params": dict(e.defaults)}
                for _, e in sorted(self._entries.items())]

    def resolve_params(self, name: str,
                       params: Optional[dict]) -> Dict[str, object]:
        """Defaults merged with ``params``; unknown keys rejected,
        values coerced to the default's type (an HTTP submission
        arrives as JSON — "3" and 3.0 both mean the int 3)."""
        entry = self.entry(name)
        resolved = dict(entry.defaults)
        for key, value in (params or {}).items():
            if key not in resolved:
                raise ValueError(
                    f"model {name!r} has no parameter {key!r}; "
                    f"accepts {sorted(resolved)}")
            want = type(resolved[key])
            try:
                resolved[key] = (bool(value) if want is bool
                                 else want(value))
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"parameter {key!r} of model {name!r}: {e}") from e
        return resolved

    def build(self, name: str, params: Optional[dict] = None):
        """Builds the host model; returns ``(model, canonical_params)``."""
        entry = self.entry(name)
        resolved = self.resolve_params(name, params)
        return entry.build(**resolved), resolved

    def program_key(self, name: str, params: Optional[dict] = None
                    ) -> Tuple:
        """The shared-program-cache key prefix certifying model
        identity: the corpus name plus the canonical parameter items."""
        resolved = self.resolve_params(name, params)
        return (name, tuple(sorted(resolved.items())))


# -- The default corpus ----------------------------------------------------


def _twopc(rm_count):
    _examples()
    from two_phase_commit import TwoPhaseSys

    return TwoPhaseSys(rm_count)


def _paxos(client_count, server_count):
    _examples()
    from paxos import PaxosModelCfg

    return PaxosModelCfg(client_count=client_count,
                         server_count=server_count).into_model()


def _increment(thread_count):
    _examples()
    from increment import IncrementModel

    return IncrementModel(thread_count)


def _increment_lock(thread_count):
    _examples()
    from increment_lock import IncrementLockModel

    return IncrementLockModel(thread_count)


def _single_copy(client_count, server_count):
    _examples()
    from single_copy_register import SingleCopyModelCfg

    return SingleCopyModelCfg(client_count=client_count,
                              server_count=server_count).into_model()


def _abd(client_count, server_count):
    _examples()
    from linearizable_register import AbdModelCfg

    return AbdModelCfg(client_count=client_count,
                       server_count=server_count).into_model()


def _pingpong(max_nat, maintains_history, lossy, duplicating):
    from ..actor.actor_test_util import PingPongCfg

    cfg = PingPongCfg(maintains_history=maintains_history,
                      max_nat=max_nat)
    model = (cfg.into_model()
             .with_lossy_network(lossy)
             .with_duplicating_network(duplicating))

    def device_model():
        import stateright_tpu.actor.actor_test_util as ppmod

        from ..tpu.models.pingpong import PingPongDevice

        return PingPongDevice(cfg, ppmod, lossy=lossy,
                              duplicating=duplicating)

    model.device_model = device_model
    return model


def _sliding_puzzle(rows, cols):
    _examples()
    from sliding_puzzle import SlidingPuzzle

    return SlidingPuzzle(rows, cols)


def _vsr(n, max_view, lossy, duplicating):
    from ..actor.viewstamped import VsrCfg

    return VsrCfg(n=n, max_view=max_view, lossy=lossy,
                  duplicating=duplicating).into_model()


_DEFAULT: Optional[ModelRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> ModelRegistry:
    """The process-wide default corpus (built once)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is not None:
            return _DEFAULT
        r = ModelRegistry()
        r.register("twopc", _twopc, {"rm_count": 3},
                   "two-phase commit (Gray & Lamport TLA+ subset)")
        r.register("paxos", _paxos,
                   {"client_count": 2, "server_count": 3},
                   "single-decree Paxos with linearizability history")
        r.register("increment", _increment, {"thread_count": 3},
                   "racy read-inc-write counter (finds the lost update)")
        r.register("increment_lock", _increment_lock,
                   {"thread_count": 3},
                   "spinlock-guarded counter (race eliminated)")
        r.register("single_copy", _single_copy,
                   {"client_count": 2, "server_count": 1},
                   "single-copy register (linearizable by construction)")
        r.register("abd", _abd, {"client_count": 2, "server_count": 2},
                   "ABD quorum register (linearizable reads/writes)")
        r.register("pingpong", _pingpong,
                   {"max_nat": 3, "maintains_history": False,
                    "lossy": False, "duplicating": True},
                   "ping-pong counter pair (actor-layer workout)")
        r.register("sliding_puzzle", _sliding_puzzle,
                   {"rows": 2, "cols": 3},
                   "sliding tile puzzle (search workload)")
        r.register("vsr", _vsr,
                   {"n": 3, "max_view": 1, "lossy": False,
                    "duplicating": True},
                   "viewstamped-replication primary/backup with view "
                   "change (round-14 corpus addition)")
        _DEFAULT = r
        return r

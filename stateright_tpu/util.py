"""Modeling utility collections: ``VectorClock``, ``DenseNatMap``,
``HashableHashSet``, ``HashableHashMap``.

Counterparts of the reference's `src/util/vector_clock.rs:11-106`,
`src/util/densenatmap.rs:75-216`, and `src/util.rs:72-300`. The Hashable
collections matter less here than in Rust — builtin ``set``/``frozenset``/
``dict`` already fingerprint order-insensitively via
``stateright_tpu.fingerprint`` — but ``set`` and ``dict`` are not
*hashable*, so states built on frozen dataclasses can't hold them when
user code also wants ``hash()``/dict-key semantics; these wrappers are
mutable collections with stable order-insensitive hashes.

Design notes (deliberately not a translation):

- ``VectorClock`` is immutable (`incremented` returns a new clock), which
  fits frozen-dataclass model states; the reference mutates in place.
- ``DenseNatMap`` stores a typed key constructor (e.g. ``Id``) instead of
  a phantom type parameter; iteration yields properly-typed keys.
- Both integrate with the framework protocols: ``__fingerprint__`` for
  stable state identity (padding-insensitive for clocks, exactly like
  the reference's trailing-zero-cutoff ``Hash``) and ``__rewrite__`` for
  symmetry reduction.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Tuple

__all__ = ["VectorClock", "DenseNatMap", "HashableHashSet",
           "HashableHashMap"]


class VectorClock:
    """A vector clock: a partial causal order on events
    (`vector_clock.rs:11-106`). Components beyond the stored length are
    implicitly zero, and all comparisons/identity ignore trailing zeros.

    >>> a = VectorClock().incremented(0)        # process 0 acts
    >>> b = VectorClock().incremented(1)        # process 1 acts
    >>> a.partial_cmp(b) is None                # concurrent
    True
    >>> merged = VectorClock.merge_max(a, b).incremented(1)
    >>> a < merged and b < merged
    True
    >>> VectorClock([1, 0, 0]) == VectorClock([1])  # padding-insensitive
    True
    """

    __slots__ = ("_v",)

    def __init__(self, components: Iterable[int] = ()):
        self._v: Tuple[int, ...] = tuple(int(x) for x in components)
        if any(x < 0 for x in self._v):
            raise ValueError("vector clock components are nonnegative")

    # -- Accessors --------------------------------------------------------

    def get(self, index: int) -> int:
        """The component at ``index`` (0 beyond the stored length)."""
        return self._v[index] if index < len(self._v) else 0

    def components(self) -> Tuple[int, ...]:
        return self._v

    # -- Operations (vector_clock.rs:21-40) -------------------------------

    @staticmethod
    def merge_max(c1: "VectorClock", c2: "VectorClock") -> "VectorClock":
        """Elementwise maximum of two clocks."""
        n = max(len(c1._v), len(c2._v))
        return VectorClock(max(c1.get(i), c2.get(i)) for i in range(n))

    def incremented(self, index: int) -> "VectorClock":
        """A new clock with component ``index`` incremented (padding with
        zeros as needed)."""
        v = list(self._v) + [0] * (index + 1 - len(self._v))
        v[index] += 1
        return VectorClock(v)

    # -- Identity: trailing zeros are insignificant -----------------------

    def _trimmed(self) -> Tuple[int, ...]:
        v = self._v
        n = len(v)
        while n and v[n - 1] == 0:
            n -= 1
        return v[:n]

    def __eq__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self._trimmed() == other._trimmed()

    def __hash__(self) -> int:
        return hash(self._trimmed())

    def __fingerprint__(self):
        return self._trimmed()

    # -- Partial order (vector_clock.rs:83-106) ---------------------------

    def partial_cmp(self, other: "VectorClock") -> Optional[int]:
        """-1 / 0 / +1 when comparable, ``None`` for concurrent clocks."""
        expected = 0
        for i in range(max(len(self._v), len(other._v))):
            a, b = self.get(i), other.get(i)
            ordering = (a > b) - (a < b)
            if expected == 0:
                expected = ordering
            elif ordering not in (0, expected):
                return None
        return expected

    def __lt__(self, other) -> bool:
        return self.partial_cmp(other) == -1

    def __le__(self, other) -> bool:
        return self.partial_cmp(other) in (-1, 0)

    def __gt__(self, other) -> bool:
        return self.partial_cmp(other) == 1

    def __ge__(self, other) -> bool:
        return self.partial_cmp(other) in (0, 1)

    def __repr__(self) -> str:
        return f"VectorClock({list(self._v)!r})"

    def __str__(self) -> str:
        # Display parity with the reference: "<1, 2, ...>"; equal clocks
        # need not display identically (trailing zeros show).
        return "<" + "".join(f"{c}, " for c in self._v) + "...>"


class DenseNatMap:
    """A map whose keys densely cover ``0..len``, stored as a flat list
    (`densenatmap.rs:75-216`). Safer than a bare list in model state:
    lookups are by *typed* key (e.g. actor ``Id``), inserts must stay
    dense, and symmetry rewrites reindex keys while rewriting values.
    """

    __slots__ = ("_values", "_key")

    def __init__(self, values: Iterable = (), key: Callable[[int], object] = int):
        self._values = list(values)
        self._key = key

    @classmethod
    def from_pairs(cls, pairs: Iterable[Tuple[object, object]],
                   key: Callable[[int], object] = int) -> "DenseNatMap":
        """Builds from (key, value) pairs in any order; raises ``ValueError``
        if the keys do not densely cover ``0..n`` (densenatmap.rs
        ``FromIterator``)."""
        indexed = sorted(((int(k), v) for k, v in pairs), key=lambda kv: kv[0])
        for expected, (i, _) in enumerate(indexed):
            if i != expected:
                raise ValueError(
                    f"invalid key at index: index={i}, "
                    f"expected_index={expected}")
        return cls((v for _, v in indexed), key=key)

    # -- Map surface (densenatmap.rs:84-130) ------------------------------

    def get(self, key) -> Optional[object]:
        """The value for ``key``, or ``None`` if out of range."""
        index = int(key)
        return self._values[index] if 0 <= index < len(self._values) else None

    def insert(self, key, value) -> Optional[object]:
        """Overwrites an existing key (returning the previous value) or
        appends at exactly ``len`` (returning ``None``); anything sparser
        raises ``IndexError`` (densenatmap.rs:95-109)."""
        index = int(key)
        if index > len(self._values):
            raise IndexError(
                f"out of bounds: index={index}, len={len(self._values)}")
        if index == len(self._values):
            self._values.append(value)
            return None
        previous = self._values[index]
        self._values[index] = value
        return previous

    def __getitem__(self, key):
        return self._values[int(key)]

    def __setitem__(self, key, value):
        self.insert(key, value)

    def __len__(self) -> int:
        return len(self._values)

    def __iter__(self):
        return iter(self.items())

    def items(self):
        return [(self._key(i), v) for i, v in enumerate(self._values)]

    def values(self):
        return list(self._values)

    # -- Identity / symmetry ----------------------------------------------

    def __eq__(self, other) -> bool:
        if not isinstance(other, DenseNatMap):
            return NotImplemented
        return self._values == other._values

    def __hash__(self) -> int:
        return hash(tuple(self._values))

    def __fingerprint__(self):
        return tuple(self._values)

    def __rewrite__(self, plan) -> "DenseNatMap":
        """Symmetry rewrite: keys reindex through the plan, values rewrite
        structurally (the reference's ``Rewrite`` impl,
        densenatmap.rs:202-216)."""
        from .symmetry import rewrite_value

        return DenseNatMap.from_pairs(
            ((plan.rewrite_mapping[i], rewrite_value(v, plan))
             for i, v in enumerate(self._values)),
            key=self._key)

    def __repr__(self) -> str:
        return f"DenseNatMap({self._values!r})"


class HashableHashSet:
    """A mutable hash set with a stable, order-insensitive ``hash()``
    (`util.rs:72-208`): same elements => same hash regardless of
    insertion order, computed from sorted element digests."""

    __slots__ = ("_items",)

    def __init__(self, items: Iterable = ()):
        self._items = set(items)

    def add(self, item) -> None:
        self._items.add(item)

    def discard(self, item) -> None:
        self._items.discard(item)

    def remove(self, item) -> None:
        self._items.remove(item)

    def __contains__(self, item) -> bool:
        return item in self._items

    def __iter__(self):
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __eq__(self, other) -> bool:
        if isinstance(other, HashableHashSet):
            return self._items == other._items
        if isinstance(other, (set, frozenset)):
            return self._items == other
        return NotImplemented

    def __hash__(self) -> int:
        # frozenset hashing is already order-insensitive, cheap, and —
        # because __eq__ equates us with set/frozenset — the only hash
        # that keeps the eq/hash contract across those types. (Stable
        # cross-process identity is the fingerprint layer's job, via
        # __fingerprint__.)
        return hash(frozenset(self._items))

    def __fingerprint__(self):
        return frozenset(self._items)

    def __rewrite__(self, plan) -> "HashableHashSet":
        from .symmetry import rewrite_value

        return HashableHashSet(
            rewrite_value(x, plan) for x in self._items)

    def __repr__(self) -> str:
        inner = ", ".join(sorted(map(repr, self._items)))
        return f"HashableHashSet({{{inner}}})"


class HashableHashMap:
    """A mutable hash map with a stable, order-insensitive ``hash()``
    (`util.rs:226-327`), hashing sorted (key, value) entry digests."""

    __slots__ = ("_map",)

    def __init__(self, items=()):
        self._map = dict(items)

    def __getitem__(self, key):
        return self._map[key]

    def __setitem__(self, key, value) -> None:
        self._map[key] = value

    def __delitem__(self, key) -> None:
        del self._map[key]

    def get(self, key, default=None):
        return self._map.get(key, default)

    def __contains__(self, key) -> bool:
        return key in self._map

    def __iter__(self):
        return iter(self._map)

    def keys(self):
        return self._map.keys()

    def values(self):
        return self._map.values()

    def items(self):
        return self._map.items()

    def __len__(self) -> int:
        return len(self._map)

    def __eq__(self, other) -> bool:
        if isinstance(other, HashableHashMap):
            return self._map == other._map
        if isinstance(other, dict):
            return self._map == other
        return NotImplemented

    def __hash__(self) -> int:
        # Order-insensitive by construction; values must be hashable
        # (the reference requires V: Hash likewise, util.rs:278-300).
        return hash(frozenset(self._map.items()))

    def __fingerprint__(self):
        return dict(self._map)

    def __rewrite__(self, plan) -> "HashableHashMap":
        from .symmetry import rewrite_value

        return HashableHashMap(
            (rewrite_value(k, plan), rewrite_value(v, plan))
            for k, v in self._map.items())

    def __repr__(self) -> str:
        return f"HashableHashMap({self._map!r})"

"""Flattens tester histories for the C++ search (``stateright_tpu.native``).

Only register histories qualify (the reference object is a `Register`,
ops are Write/Read, returns WriteOk/ReadOk) — that covers every storage
workload in the reference's examples (paxos, ABD, single-copy). Values
are interned to int64 ids because register semantics only ever compare
them for equality. Anything else returns None → the Python search runs.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["native_register_verdict"]


def native_register_verdict(tester, realtime: bool) -> Optional[bool]:
    from .. import native

    if native.register_check is None:
        return None
    from .register import Read, ReadOk, Register, Write, WriteOk

    ref = tester.init_ref_obj
    if type(ref) is not Register:
        return None

    threads = sorted(tester.history_by_thread)
    tindex = {t: i for i, t in enumerate(threads)}
    intern: dict = {}

    def vid(v) -> int:
        i = intern.get(v)
        if i is None:
            i = intern[v] = len(intern)
        return i

    try:
        init_val = vid(ref.value)
        t_off, kind, val = [0], [], []
        cs_off, cs_peer, cs_time = [0], [], []
        for t in threads:
            for entry in tester.history_by_thread[t]:
                if realtime:
                    cs, op, ret = entry
                else:
                    (op, ret), cs = entry, ()
                if type(op) is Write:
                    if type(ret) is not WriteOk:
                        return None
                    kind.append(0)
                    val.append(vid(op.value))
                elif type(op) is Read:
                    if type(ret) is not ReadOk:
                        return None
                    kind.append(1)
                    val.append(vid(ret.value))
                else:
                    return None
                for peer, min_time in cs:
                    cs_peer.append(tindex[peer])
                    cs_time.append(min_time)
                cs_off.append(len(cs_peer))
            t_off.append(len(kind))

        has_if, if_kind, if_val = [], [], []
        if_cs_off, if_cs_peer, if_cs_time = [0], [], []
        for t in threads:
            entry = tester.in_flight_by_thread.get(t)
            if entry is None:
                has_if.append(0)
                if_kind.append(0)
                if_val.append(0)
            else:
                if realtime:
                    cs, op = entry
                else:
                    op, cs = entry, ()
                if type(op) is Write:
                    if_kind.append(0)
                    if_val.append(vid(op.value))
                elif type(op) is Read:
                    if_kind.append(1)
                    if_val.append(0)
                else:
                    return None
                has_if.append(1)
                for peer, min_time in cs:
                    if_cs_peer.append(tindex[peer])
                    if_cs_time.append(min_time)
            if_cs_off.append(len(if_cs_peer))
    except TypeError:  # unhashable value — let Python handle it
        return None

    return native.register_check(
        len(threads), init_val, realtime,
        t_off, kind, val, cs_off, cs_peer, cs_time,
        has_if, if_kind, if_val, if_cs_off, if_cs_peer, if_cs_time)

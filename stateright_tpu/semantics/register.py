"""``Register`` reference object (`src/semantics/register.rs`)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from .base import SequentialSpec

__all__ = ["Register", "RegisterOp", "RegisterRet",
           "Read", "Write", "ReadOk", "WriteOk"]


@dataclass(frozen=True)
class Write:
    value: Any

    def __repr__(self):
        return f"Write({self.value!r})"


@dataclass(frozen=True)
class Read:
    def __repr__(self):
        return "Read"


@dataclass(frozen=True)
class WriteOk:
    def __repr__(self):
        return "WriteOk"


@dataclass(frozen=True)
class ReadOk:
    value: Any

    def __repr__(self):
        return f"ReadOk({self.value!r})"


RegisterOp = (Write, Read)
RegisterRet = (WriteOk, ReadOk)


class Register(SequentialSpec):
    """A simple read/write register."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def invoke(self, op):
        if type(op) is Write:
            self.value = op.value
            return WriteOk()
        return ReadOk(self.value)

    def is_valid_step(self, op, ret) -> bool:
        if type(op) is Write and type(ret) is WriteOk:
            self.value = op.value
            return True
        if type(op) is Read and type(ret) is ReadOk:
            return self.value == ret.value
        return False

    def clone(self) -> "Register":
        return Register(self.value)

    def __eq__(self, other):
        return isinstance(other, Register) and self.value == other.value

    def __hash__(self):
        return hash(("Register", self.value))

    def __fingerprint__(self):
        return ("Register", self.value)

    def __repr__(self):
        return f"Register({self.value!r})"

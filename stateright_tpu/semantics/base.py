"""``SequentialSpec`` and ``ConsistencyTester`` interfaces.

Counterpart of `src/semantics.rs:72-98` and
`src/semantics/consistency_tester.rs:15-38`.
"""

from __future__ import annotations

import copy
from typing import Any, Iterable, Tuple

__all__ = ["SequentialSpec", "ConsistencyTester"]


class SequentialSpec:
    """A sequential "reference object" defining operational semantics
    (e.g. "this system should behave like a register"). ``invoke`` mutates
    the object and returns the operation's return value."""

    def invoke(self, op) -> Any:
        raise NotImplementedError

    def is_valid_step(self, op, ret) -> bool:
        """Whether invoking ``op`` may return ``ret``. Default calls
        ``invoke``; override to avoid needless work."""
        return self.invoke(op) == ret

    def is_valid_history(self, ops: Iterable[Tuple[Any, Any]]) -> bool:
        """Whether a sequential (op, ret) history is valid."""
        return all(self.is_valid_step(op, ret) for op, ret in ops)

    def clone(self) -> "SequentialSpec":
        return copy.deepcopy(self)


class ConsistencyTester:
    """Records operation invocations/returns per abstract thread and tests
    the history against a consistency model. ``on_invoke``/``on_return``
    raise ``ValueError`` on *invalid* histories (double in-flight ops,
    returns with no invocation) — distinct from merely *inconsistent*
    histories, which simply make ``is_consistent`` false."""

    def on_invoke(self, thread_id, op) -> "ConsistencyTester":
        raise NotImplementedError

    def on_return(self, thread_id, ret) -> "ConsistencyTester":
        raise NotImplementedError

    def is_consistent(self) -> bool:
        raise NotImplementedError

    def on_invret(self, thread_id, op, ret) -> "ConsistencyTester":
        """Records an operation and its return together."""
        self.on_invoke(thread_id, op)
        return self.on_return(thread_id, ret)


class RecordingTester(ConsistencyTester):
    """Shared machinery for the two history-recording testers: per-thread
    histories and in-flight maps, cloning, and the identity protocol that
    lets a tester live inside model state. Subclasses define what an
    in-flight entry carries (``_invoke_entry``) and how it completes
    (``_complete_entry``), plus their ``serialized_history``."""

    __slots__ = ("init_ref_obj", "history_by_thread",
                 "in_flight_by_thread", "is_valid_history", "_fp")

    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        self.history_by_thread: dict = {}
        self.in_flight_by_thread: dict = {}
        self.is_valid_history = True
        self._fp = None

    # -- Subclass hooks --------------------------------------------------

    def _invoke_entry(self, thread_id, op):
        """The value stored while the op is in flight."""
        raise NotImplementedError

    def _complete_entry(self, in_flight_entry, ret):
        """The per-thread history entry once the op returns."""
        raise NotImplementedError

    def _in_flight_op(self, in_flight_entry):
        """The op inside an in-flight entry (for error messages)."""
        raise NotImplementedError

    def serialized_history(self):
        raise NotImplementedError

    # -- Recording -------------------------------------------------------

    def on_invoke(self, thread_id, op):
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id in self.in_flight_by_thread:
            self.is_valid_history = False
            self._fp = None
            raise ValueError(
                f"Thread already has an operation in flight. "
                f"thread_id={thread_id!r}, "
                f"op={self._in_flight_op(self.in_flight_by_thread[thread_id])!r}, "
                f"history_by_thread={self.history_by_thread!r}")
        self.in_flight_by_thread[thread_id] = self._invoke_entry(
            thread_id, op)
        self.history_by_thread.setdefault(thread_id, ())
        self._fp = None
        return self

    def on_return(self, thread_id, ret):
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id not in self.in_flight_by_thread:
            self.is_valid_history = False
            self._fp = None
            raise ValueError(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}, "
                f"history={self.history_by_thread.get(thread_id, ())!r}")
        entry = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread[thread_id] = (
            self.history_by_thread.get(thread_id, ())
            + (self._complete_entry(entry, ret),))
        self._fp = None
        return self

    # Verdict memo, keyed by (tester class, history fingerprint). Histories
    # repeat massively across model states (`ActorModel` explores every
    # interleaving, but many reach the same history), and the reference
    # re-runs its exponential `serialized_history()` search once per
    # evaluated state (`linearizability.rs:178-240` via an `always`
    # property). Keying by fingerprint is sound under this framework's
    # identity model: states themselves dedup by fingerprint, so two
    # histories with equal fingerprints are already "the same" to the
    # checker. One bool per unique history keeps the memo small.
    _verdict_memo: dict = {}

    def is_consistent(self) -> bool:
        key = (type(self), hash(self))
        memo = RecordingTester._verdict_memo
        verdict = memo.get(key)
        if verdict is None:
            native = self._native_is_consistent()
            verdict = (self.serialized_history() is not None
                       if native is None else native)
            if len(memo) >= 1 << 22:  # bound worst-case footprint
                memo.clear()
            memo[key] = verdict
        return verdict

    def _native_is_consistent(self):
        """Subclass hook: return a bool verdict from the C++ fast path
        (``stateright_tpu.native``), or None to use the Python search."""
        return None

    def __len__(self) -> int:
        return (len(self.in_flight_by_thread)
                + sum(len(h) for h in self.history_by_thread.values()))

    # -- Identity / cloning ----------------------------------------------

    def clone(self):
        t = type(self).__new__(type(self))
        t.init_ref_obj = self.init_ref_obj
        t.history_by_thread = dict(self.history_by_thread)
        t.in_flight_by_thread = dict(self.in_flight_by_thread)
        t.is_valid_history = self.is_valid_history
        t._fp = None
        return t

    def __rewrite__(self, plan):
        """Symmetry support: remap thread ids (actor ``Id``s when wired in
        as ActorModel history)."""
        from ..symmetry import rewrite_value

        t = type(self).__new__(type(self))
        t.init_ref_obj = self.init_ref_obj
        t.history_by_thread = {
            rewrite_value(tid, plan): rewrite_value(h, plan)
            for tid, h in self.history_by_thread.items()}
        t.in_flight_by_thread = {
            rewrite_value(tid, plan): rewrite_value(v, plan)
            for tid, v in self.in_flight_by_thread.items()}
        t.is_valid_history = self.is_valid_history
        t._fp = None
        return t

    def __eq__(self, other):
        return (type(other) is type(self)
                and self.init_ref_obj == other.init_ref_obj
                and self.history_by_thread == other.history_by_thread
                and self.in_flight_by_thread == other.in_flight_by_thread
                and self.is_valid_history == other.is_valid_history)

    def __hash__(self):
        if self._fp is None:
            from ..fingerprint import fingerprint

            self._fp = fingerprint(self)
        return self._fp

    def __fingerprint__(self):
        return (type(self).__name__, self.init_ref_obj,
                self.history_by_thread, self.in_flight_by_thread,
                self.is_valid_history)

    def __repr__(self):
        return (f"{type(self).__name__}(init={self.init_ref_obj!r}, "
                f"history={self.history_by_thread!r}, "
                f"in_flight={self.in_flight_by_thread!r}, "
                f"valid={self.is_valid_history})")

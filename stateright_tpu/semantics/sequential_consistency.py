"""Sequential-consistency tester (`src/semantics/sequential_consistency.rs`).

Validates that a concurrent history can be interleaved into a total order
that (a) preserves each thread's program order and (b) is valid per the
sequential reference object. The search is recursive backtracking over all
interleavings — worst-case exponential — and runs once per evaluated state
when wired in as an ``ActorModel`` history, so the C++ fast path
(``stateright_tpu.native``) takes over when available.
"""

from __future__ import annotations

from typing import Optional

from .base import RecordingTester

__all__ = ["SequentialConsistencyTester"]


class SequentialConsistencyTester(RecordingTester):
    """History entries are plain ``(op, ret)`` pairs; program order per
    thread is the only cross-op constraint."""

    __slots__ = ()

    def _invoke_entry(self, thread_id, op):
        return op

    def _complete_entry(self, op, ret):
        return (op, ret)

    def _in_flight_op(self, entry):
        return entry

    def _native_is_consistent(self):
        from ._native_dispatch import native_register_verdict

        if not self.is_valid_history:
            return False
        return native_register_verdict(self, realtime=False)

    def serialized_history(self) -> Optional[list]:
        """Attempts to serialize the partial order into a valid total order
        (`sequential_consistency.rs:151-213`)."""
        if not self.is_valid_history:
            return None
        remaining = {t: self.history_by_thread[t]
                     for t in sorted(self.history_by_thread)}
        return _serialize([], self.init_ref_obj, remaining,
                          dict(self.in_flight_by_thread))


def _serialize(valid_history, ref_obj, remaining, in_flight):
    """Backtracking over interleavings preserving per-thread order. In-flight
    ops are optional extensions (they may not have taken effect yet)."""
    if all(not h for h in remaining.values()):
        return valid_history
    for thread_id in remaining:
        history = remaining[thread_id]
        if not history:
            # Case 1: only a possible in-flight op for this thread.
            if thread_id not in in_flight:
                continue
            op = in_flight[thread_id]
            next_ref = ref_obj.clone()
            ret = next_ref.invoke(op)
            next_in_flight = dict(in_flight)
            del next_in_flight[thread_id]
            result = _serialize(valid_history + [(op, ret)], next_ref,
                                remaining, next_in_flight)
            if result is not None:
                return result
        else:
            # Case 2: the thread's next completed op.
            op, ret = history[0]
            next_ref = ref_obj.clone()
            if not next_ref.is_valid_step(op, ret):
                continue
            next_remaining = dict(remaining)
            next_remaining[thread_id] = history[1:]
            result = _serialize(valid_history + [(op, ret)], next_ref,
                                next_remaining, in_flight)
            if result is not None:
                return result
    return None

"""Linearizability tester (`src/semantics/linearizability.rs`).

Structurally the sequential-consistency tester plus real-time ordering:
when an operation starts, the tester records the index of the last
completed operation of every *other* thread (`linearizability.rs:114-122`);
during serialization a candidate op is rejected while any such peer op is
still unserialized (`linearizability.rs:198-227`). This enforces that
sequenced (non-concurrent) operations across threads respect their
happened-before order.
"""

from __future__ import annotations

from typing import Optional

from .base import RecordingTester

__all__ = ["LinearizabilityTester"]


class LinearizabilityTester(RecordingTester):
    """History entries are ``(cs, op, ret)``; in-flight entries ``(cs,
    op)`` — ``cs`` is a tuple of ``(peer_thread, last_completed_index)``
    happened-before edges recorded at invoke time."""

    __slots__ = ()

    def _invoke_entry(self, thread_id, op):
        cs = tuple(sorted(
            (tid, len(h) - 1)
            for tid, h in self.history_by_thread.items()
            if tid != thread_id and h))
        return (cs, op)

    def _complete_entry(self, entry, ret):
        cs, op = entry
        return (cs, op, ret)

    def _in_flight_op(self, entry):
        return entry[1]

    def _native_is_consistent(self):
        from ._native_dispatch import native_register_verdict

        if not self.is_valid_history:
            return False
        return native_register_verdict(self, realtime=True)

    def serialized_history(self) -> Optional[list]:
        """Attempts to serialize the partial order into a valid total order
        respecting real-time edges (`linearizability.rs:165-240`)."""
        if not self.is_valid_history:
            return None
        remaining = {
            t: tuple(enumerate(self.history_by_thread[t]))
            for t in sorted(self.history_by_thread)}
        return _serialize([], self.init_ref_obj, remaining,
                          dict(self.in_flight_by_thread))


def _violates_realtime(cs, remaining):
    """True when a peer still has an unserialized op at or before the
    recorded happened-before index (`linearizability.rs:198-206`)."""
    for peer_id, min_peer_time in cs:
        ops = remaining.get(peer_id)
        if ops and ops[0][0] <= min_peer_time:
            return True
    return False


def _serialize(valid_history, ref_obj, remaining, in_flight):
    if all(not h for h in remaining.values()):
        return valid_history
    for thread_id in remaining:
        history = remaining[thread_id]
        if not history:
            # Case 1: only a possible in-flight op for this thread.
            if thread_id not in in_flight:
                continue
            cs, op = in_flight[thread_id]
            if _violates_realtime(cs, remaining):
                continue
            next_ref = ref_obj.clone()
            ret = next_ref.invoke(op)
            next_in_flight = dict(in_flight)
            del next_in_flight[thread_id]
            result = _serialize(valid_history + [(op, ret)], next_ref,
                                remaining, next_in_flight)
            if result is not None:
                return result
        else:
            # Case 2: the thread's next completed op.
            idx, (cs, op, ret) = history[0]
            next_remaining = dict(remaining)
            next_remaining[thread_id] = history[1:]
            if _violates_realtime(cs, next_remaining):
                continue
            next_ref = ref_obj.clone()
            if not next_ref.is_valid_step(op, ret):
                continue
            result = _serialize(valid_history + [(op, ret)], next_ref,
                                next_remaining, in_flight)
            if result is not None:
                return result
    return None

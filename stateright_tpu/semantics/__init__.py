"""Consistency semantics: reference objects and history testers.

Counterpart of the reference's `src/semantics.rs` and `src/semantics/`:
correctness of a concurrent system is defined against a *sequential
reference object* (``SequentialSpec``); a ``ConsistencyTester`` records a
potentially concurrent operation history and decides whether it can be
serialized per a consistency model (linearizability or sequential
consistency). Testers are cloneable/hashable so they can live inside model
state as the auxiliary history ``H`` of an ``ActorModel``.
"""

from .base import ConsistencyTester, SequentialSpec
from .linearizability import LinearizabilityTester
from .sequential_consistency import SequentialConsistencyTester
from .register import Register, ReadOk, RegisterOp, RegisterRet, Read, Write, WriteOk
from .vec import VecSpec, VecOp, VecRet, Push, Pop, Len, PushOk, PopOk, LenOk

__all__ = [
    "ConsistencyTester",
    "SequentialSpec",
    "LinearizabilityTester",
    "SequentialConsistencyTester",
    "Register",
    "RegisterOp",
    "RegisterRet",
    "Read",
    "Write",
    "ReadOk",
    "WriteOk",
    "VecSpec",
    "VecOp",
    "VecRet",
    "Push",
    "Pop",
    "Len",
    "PushOk",
    "PopOk",
    "LenOk",
]

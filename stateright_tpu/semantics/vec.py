"""Stack-like ``Vec`` reference object (`src/semantics/vec.rs`)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional

from .base import SequentialSpec

__all__ = ["VecSpec", "VecOp", "VecRet",
           "Push", "Pop", "Len", "PushOk", "PopOk", "LenOk"]


@dataclass(frozen=True)
class Push:
    value: Any

    def __repr__(self):
        return f"Push({self.value!r})"


@dataclass(frozen=True)
class Pop:
    def __repr__(self):
        return "Pop"


@dataclass(frozen=True)
class Len:
    def __repr__(self):
        return "Len"


@dataclass(frozen=True)
class PushOk:
    def __repr__(self):
        return "PushOk"


@dataclass(frozen=True)
class PopOk:
    value: Optional[Any]

    def __repr__(self):
        return f"PopOk({self.value!r})"


@dataclass(frozen=True)
class LenOk:
    len: int

    def __repr__(self):
        return f"LenOk({self.len})"


VecOp = (Push, Pop, Len)
VecRet = (PushOk, PopOk, LenOk)


class VecSpec(SequentialSpec):
    """Stack semantics over a list."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[List] = None):
        self.items = list(items) if items else []

    def invoke(self, op):
        if type(op) is Push:
            self.items.append(op.value)
            return PushOk()
        if type(op) is Pop:
            return PopOk(self.items.pop() if self.items else None)
        return LenOk(len(self.items))

    def is_valid_step(self, op, ret) -> bool:
        if type(op) is Push and type(ret) is PushOk:
            self.items.append(op.value)
            return True
        if type(op) is Pop and type(ret) is PopOk:
            popped = self.items.pop() if self.items else None
            return popped == ret.value
        if type(op) is Len and type(ret) is LenOk:
            return len(self.items) == ret.len
        return False

    def clone(self) -> "VecSpec":
        return VecSpec(self.items)

    def __eq__(self, other):
        return isinstance(other, VecSpec) and self.items == other.items

    def __hash__(self):
        return hash(("VecSpec", tuple(self.items)))

    def __fingerprint__(self):
        return ("VecSpec", self.items)

    def __repr__(self):
        return f"VecSpec({self.items!r})"

"""Transition-structure compiler: matmul-form frontier expansion (ISSUE 15).

The round-15 megakernel fused the successor path into one kernel, but
the work inside it is still gather/scatter on the vector unit — the MXU
sits idle. BLEST (arXiv:2512.21967) reformulates BFS frontier expansion
as matmul-friendly products; this module applies the idea to the wave
pipeline's ``expand_frontier`` stage for *regular* models.

A model is **regular** when, for every action ``a`` and every output
position ``o`` (each successor lane plus the action's validity bit),
the next-value function depends only on a small *key tuple* of input
lanes whose joint domain — the product of the declared ``lane_bits()``
widths — is enumerable. The compiler discovers the key tuples by
probing the model's own jitted ``step`` (sweep each lane over its full
declared domain at several random baseline contexts), tabulates each
key group by enumerating its joint domain, and verifies every table
row at independent random contexts; a verification miss refines the
key set with the offending lane and retries. Everything the compiler
knows comes from evaluating ``step`` itself, so the emitted tables are
exact by construction wherever the key-dependence inference is right,
and the independent-context verification plus the differential fuzz
suite (tests/test_matmul_wave.py) guard the inference.

At runtime (:func:`matmul_expand`) each key group advances the whole
batch with ONE dense product: the joint key index is one-hot encoded
``[B, D]`` and multiplied against the group's transition table
``[D, 2*n_cols]`` — exactly the shape Mosaic puts on the MXU. Bit
exactness on a float unit comes from a 16-bit lo/hi split: every table
entry is < 2^16, the one-hot selects exactly one row, and f32
represents integers below 2^24 exactly, so the uint32 reconstruction
``lo | (hi << 16)`` reproduces ``step``'s output bit for bit.

Irregular models (undeclared ``lane_bits``, sentinel lanes, key
domains past the cap, unstable inference) keep the vmapped ``step``
path via the capability gate: :func:`classify` always returns a stable
human-readable ``reason`` naming the first failed gate, which the
engines surface through ``scheduler_stats()["wave_matmul"]``.
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "KEY_DOMAIN_CAP", "LANE_DOMAIN_CAP", "MatmulClassification",
    "MatmulPlan", "classify", "matmul_expand", "plan_bytes",
]

#: Joint key-domain cap per output group: ∏ 2^bits over the key lanes.
#: Past this the transition table stops being a small VMEM-resident
#: constant and the one-hot matmul stops being a win.
KEY_DOMAIN_CAP = 4096
#: Single-lane domain cap for the probing sweep (a lane wider than this
#: cannot be swept exhaustively, and could never be a key lane anyway).
LANE_DOMAIN_CAP = 1 << 12
#: Baseline contexts for the dependence sweep / verification contexts
#: for the table build (independent draws, deterministic seed).
_N_BASELINES = 3
_N_VERIFY = 3
#: Key-set refinement rounds per output column before declaring the
#: inference unstable (each round adds one key lane, so a column can
#: never need more rounds than there are lanes).
_MAX_REFINE_PER_COL = 8
#: Per-group probe-row budget for the closure verification (the joint
#: domain times the non-key sweep width); past this the classification
#: itself would cost more than it buys.
_GROUP_PROBE_CAP = 1 << 19
#: Total row budget for the pairwise dependence sweep.
_PAIR_PROBE_CAP = 1 << 19
#: Fixed probe-batch shape: one jitted ``vmap(step)`` compile serves
#: every probe, padded to this many rows.
_CHUNK = 512


class _Group:
    """One key tuple and every (action, output) column it drives.

    ``table`` is float32 ``[domain, 2*len(cols)]`` — interleaved
    (lo, hi) 16-bit halves of the uint32 output value per column
    (validity columns carry 0/1 in the lo half). ``strides`` maps a key
    assignment to its table row: ``row = Σ lane_value[k] * stride[k]``,
    matching the enumeration order the table was built in."""

    __slots__ = ("keys", "strides", "domain", "cols", "table")

    def __init__(self, keys: Tuple[int, ...], strides: Tuple[int, ...],
                 domain: int, cols: List[Tuple[int, int]],
                 table: np.ndarray):
        self.keys = keys
        self.strides = strides
        self.domain = domain
        self.cols = cols
        self.table = table


class MatmulPlan:
    """A compiled matmul-form expansion for one regular model.

    ``groups`` carry the transition tables; ``consts`` are outputs with
    an empty key set (written as broadcast scalars, no matmul);
    ``copies`` (passthrough columns — the table turned out to be the
    identity on the output's own lane) are implicit: the runtime starts
    from a broadcast copy of the input registers, so they cost nothing.
    ``matmul_ops`` is the per-frontier-row MAC count, Σ_g D_g·2·n_g —
    the static gauge the wave events and bench record."""

    __slots__ = ("width", "fanout", "groups", "consts", "copies",
                 "matmul_ops", "table_bytes")

    def __init__(self, width: int, fanout: int, groups: List[_Group],
                 consts: List[Tuple[int, int, int]], copies: int):
        self.width = width
        self.fanout = fanout
        self.groups = groups
        self.consts = consts
        self.copies = copies
        self.matmul_ops = sum(g.domain * g.table.shape[1]
                              for g in groups)
        self.table_bytes = sum(g.table.nbytes for g in groups)


class MatmulClassification:
    """The capability-gate verdict: ``regular`` + a stable ``reason``
    string (pinned by tests), and the :class:`MatmulPlan` when
    regular."""

    __slots__ = ("regular", "reason", "plan")

    def __init__(self, regular: bool, reason: str,
                 plan: Optional[MatmulPlan]):
        self.regular = regular
        self.reason = reason
        self.plan = plan


def plan_bytes(plan: Optional[MatmulPlan], batch: int) -> int:
    """The matmul path's extra VMEM working set at ``batch`` rows: the
    widest one-hot block plus every resident transition table — the
    term the megakernel's VMEM gate adds when the plan rides
    in-kernel."""
    if plan is None:
        return 0
    widest = max((g.domain for g in plan.groups), default=0)
    return 4 * batch * widest + plan.table_bytes


def _irregular(reason: str) -> MatmulClassification:
    return MatmulClassification(False, reason, None)


class _StepProbe:
    """Batched host-side evaluator over the model's own ``step``: one
    fixed-shape jitted vmap, every probe padded to ``_CHUNK`` rows."""

    def __init__(self, dm):
        self._fn = jax.jit(jax.vmap(dm.step))

    def __call__(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """``rows`` uint32 [N, W] → (succ uint32 [N, F, W],
        valid bool [N, F])."""
        succ_parts, val_parts = [], []
        for i in range(0, rows.shape[0], _CHUNK):
            chunk = rows[i:i + _CHUNK]
            n = chunk.shape[0]
            if n < _CHUNK:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], _CHUNK - n, axis=0)])
            s, v = self._fn(jnp.asarray(chunk, jnp.uint32))
            succ_parts.append(np.asarray(s)[:n])
            val_parts.append(np.asarray(v)[:n])
        return (np.concatenate(succ_parts, axis=0),
                np.concatenate(val_parts, axis=0))


def _outputs(succ: np.ndarray, valid: np.ndarray) -> np.ndarray:
    """Stacks successor lanes and the validity bit into one uint32
    output cube ``[N, F, W+1]`` — column ``W`` is the action's validity
    (0/1), so key inference and tabulation treat it like any lane."""
    return np.concatenate(
        [succ, valid[..., None].astype(np.uint32)], axis=2)


def _random_contexts(rng, bits: Sequence[int], n: int) -> np.ndarray:
    """``n`` uniform in-domain probe rows (uint32 [n, W])."""
    cols = [rng.integers(0, 1 << b, size=n, dtype=np.uint32)
            for b in bits]
    return np.stack(cols, axis=1)


def _spread_contexts(rng, bits: Sequence[int], n: int) -> np.ndarray:
    """``n`` in-domain rows where every row past the first differs
    from row 0 in EVERY lane (a nonzero per-lane offset mod the lane
    domain) — so an output that secretly reads a lane outside its
    inferred key set sees that lane move in every verification
    context, not only with 1 - 1/D probability."""
    rows = _random_contexts(rng, bits, n)
    for j in range(1, n):
        for lane, b in enumerate(bits):
            d = 1 << b
            off = rng.integers(1, d) if d > 1 else 0
            rows[j, lane] = (rows[0, lane] + off) % d
    return rows


def _find_offender(probe: _StepProbe, vec_a: np.ndarray,
                   vec_b: np.ndarray, keys: Tuple[int, ...],
                   a: int, o: int) -> Optional[int]:
    """Two contexts that disagree on output ``(a, o)`` at identical key
    values: morph ``vec_a`` into ``vec_b`` one non-key lane at a time
    and return the first lane whose flip moves the output — the lane
    the key set is missing."""
    lanes = [l for l in range(vec_a.shape[0]) if l not in keys]
    rows = np.empty((len(lanes) + 1, vec_a.shape[0]), np.uint32)
    rows[0] = vec_a
    cur = vec_a.copy()
    for j, lane in enumerate(lanes):
        cur[lane] = vec_b[lane]
        rows[j + 1] = cur
    succ, valid = probe(rows)
    out = _outputs(succ, valid)[:, a, o]
    for j, lane in enumerate(lanes):
        if out[j + 1] != out[j]:
            return lane
    return None


#: Classification memo: probing a model costs thousands of step
#: evaluations plus one vmap compile, and engines classify at spawn
#: time. Keyed on the model's canonical form (``native_form()`` —
#: the same identity the cross-engine program cache trusts); ad-hoc
#: models without one re-classify every time.
_CLASSIFY_CACHE: dict = {}


def classify(dm) -> MatmulClassification:
    """Classifies ``dm`` and compiles its :class:`MatmulPlan` when
    regular. Deterministic: fixed probe seed, stable reason strings."""
    key = None
    try:
        nf = getattr(dm, "native_form", lambda: None)()
    except Exception:
        nf = None
    if nf is not None:
        model_id, params = nf
        key = (type(dm).__name__, model_id, tuple(params))
        hit = _CLASSIFY_CACHE.get(key)
        if hit is not None:
            return hit
    res = _classify(dm)
    if key is not None:
        _CLASSIFY_CACHE[key] = res
    return res


def _classify(dm) -> MatmulClassification:
    from .packing import compile_layout

    W, F = dm.state_width, dm.max_fanout
    lane_bits = getattr(dm, "lane_bits", lambda: None)()
    if lane_bits is None:
        return _irregular("undeclared lane_bits")
    layout = compile_layout(lane_bits, W)
    if any(lane.sentinel is not None for lane in layout.lanes):
        return _irregular("sentinel lane domains")
    bits = [lane.bits for lane in layout.lanes]
    for i, b in enumerate(bits):
        if (1 << b) > LANE_DOMAIN_CAP:
            return _irregular(
                f"lane domain too large (lane {i}: {b} bits)")

    probe = _StepProbe(dm)
    rng = np.random.default_rng(0)
    baselines = _spread_contexts(rng, bits, _N_BASELINES)
    base_out = _outputs(*probe(baselines))  # [R, F, W+1]

    # Dependence sweep: every lane over its full declared domain at
    # every baseline — one probe pass serves all F*(W+1) outputs.
    sweep_rows = []
    for lane in range(W):
        d = 1 << bits[lane]
        block = np.repeat(baselines, d, axis=0)  # [R*d, W]
        block[:, lane] = np.tile(
            np.arange(d, dtype=np.uint32), _N_BASELINES)
        sweep_rows.append(block)
    sweep_out = _outputs(*probe(np.concatenate(sweep_rows, axis=0)))

    deps: List[List[set]] = [[set() for _ in range(W + 1)]
                             for _ in range(F)]
    offset = 0
    for lane in range(W):
        d = 1 << bits[lane]
        block = sweep_out[offset:offset + _N_BASELINES * d]
        block = block.reshape(_N_BASELINES, d, F, W + 1)
        # Lane `lane` drives output (a, o) iff sweeping it moved the
        # output away from the baseline value anywhere.
        moved = (block != base_out[:, None]).any(axis=(0, 1))  # [F, W+1]
        for a, o in zip(*np.nonzero(moved)):
            deps[int(a)][int(o)].add(lane)
        offset += _N_BASELINES * d

    # Pairwise joint sweep (2-deviation probes): a conjunctive
    # dependence — e.g. 2pc's TmCommit validity, (tm == 0) &
    # (prepared == full) — is invisible to every single-lane sweep
    # from a context where the other conjunct is false. Sweeping each
    # lane PAIR over its joint domain at one baseline closes that gap
    # (the regularity criterion this compiler implements: dependence
    # must be revealable by at most two simultaneous lane deviations;
    # the closure verification below then grows key sets one lane at
    # a time from there).
    pair_total = sum((1 << bits[l1]) * (1 << bits[l2])
                     for l1 in range(W) for l2 in range(l1 + 1, W))
    if pair_total > _PAIR_PROBE_CAP:
        return _irregular("probe budget exceeded (pair sweep)")
    base = baselines[0]
    for l1 in range(W):
        d1 = 1 << bits[l1]
        for l2 in range(l1 + 1, W):
            d2 = 1 << bits[l2]
            blk = np.tile(base, (d1 * d2, 1))
            v1 = np.repeat(np.arange(d1, dtype=np.uint32), d2)
            v2 = np.tile(np.arange(d2, dtype=np.uint32), d1)
            blk[:, l1] = v1
            blk[:, l2] = v2
            grid = _outputs(*probe(blk)).reshape(d1, d2, F, W + 1)
            # Exact conditional dependence on this grid: l1 drives an
            # output iff the output varies along the l1 axis at some
            # fixed l2 value (and vice versa) — attributing by "some
            # both-deviated row moved" would smear every dependence
            # onto its sweep partner.
            hit1 = (grid != grid[:1]).any(axis=(0, 1))  # [F, W+1]
            hit2 = (grid != grid[:, :1]).any(axis=(0, 1))
            for lane, hit in ((l1, hit1), (l2, hit2)):
                for a, o in zip(*np.nonzero(hit)):
                    deps[int(a)][int(o)].add(lane)

    # Tabulate by key set: enumerate each group's joint domain at
    # independent verification contexts; a context disagreement means
    # the sweep missed a key lane — refine and retry.
    worklist = {}
    for a in range(F):
        for o in range(W + 1):
            worklist.setdefault(tuple(sorted(deps[a][o])),
                                []).append((a, o))
    groups: List[_Group] = []
    consts: List[Tuple[int, int, int]] = []
    copies = 0
    refines: dict = {}
    pending = sorted(worklist.items())
    while pending:
        keys, cols = pending.pop(0)
        domain = 1
        for k in keys:
            domain *= 1 << bits[k]
        if domain > KEY_DOMAIN_CAP:
            a, o = cols[0]
            what = "valid" if o == W else f"lane {o}"
            return _irregular(
                f"key domain too large (action {a}, {what}: "
                f"{domain} > {KEY_DOMAIN_CAP})")
        nonkey = [l for l in range(W) if l not in keys]
        sweep_n = sum(1 << bits[l] for l in nonkey)
        if domain * (_N_VERIFY + sweep_n) > _GROUP_PROBE_CAP:
            a, o = cols[0]
            what = "valid" if o == W else f"lane {o}"
            return _irregular(
                f"probe budget exceeded (action {a}, {what})")
        ctxs = _spread_contexts(rng, bits, _N_VERIFY)
        assigns = np.array(
            list(itertools.product(*((range(1 << bits[k]))
                                     for k in keys))),
            dtype=np.uint32).reshape(domain, len(keys))
        # Block A: the full joint key domain at every spread context
        # (cross-context agreement = "nothing outside the keys moved
        # the output" at those points). Block B, the closure sweep:
        # every non-key lane over its FULL domain at context 0, at
        # every key assignment — a residual single-lane dependence is
        # caught deterministically, not with 1 - 1/D probability.
        rows_a = np.repeat(ctxs, domain, axis=0)  # [R*D, W]
        for j, k in enumerate(keys):
            rows_a[:, k] = np.tile(assigns[:, j], _N_VERIFY)
        blocks, bmeta = [rows_a], []
        for lane in nonkey:
            d = 1 << bits[lane]
            blk = np.tile(ctxs[0], (d * domain, 1))
            blk[:, lane] = np.repeat(
                np.arange(d, dtype=np.uint32), domain)
            for j, k in enumerate(keys):
                blk[:, k] = np.tile(assigns[:, j], d)
            blocks.append(blk)
            bmeta.append((lane, d))
        out = _outputs(*probe(np.concatenate(blocks, axis=0)))
        out_a = out[:_N_VERIFY * domain].reshape(
            _N_VERIFY, domain, F, W + 1)
        vals = np.stack([out_a[:, :, a, o] for (a, o) in cols],
                        axis=2)  # [R, D, n_cols]
        agree = (vals == vals[:1]).all(axis=0)  # [D, n_cols]
        bad = None  # (column index, offending lane or None)
        if not agree.all():
            d_bad, c_bad = map(int, np.argwhere(~agree)[0])
            a, o = cols[c_bad]
            r_bad = int(np.nonzero(
                vals[:, d_bad, c_bad] != vals[0, d_bad, c_bad])[0][0])
            bad = (c_bad, _find_offender(
                probe, rows_a[d_bad].copy(),
                rows_a[r_bad * domain + d_bad].copy(), keys, a, o))
        else:
            off = _N_VERIFY * domain
            for lane, d in bmeta:
                blk = out[off:off + d * domain].reshape(
                    d, domain, F, W + 1)
                off += d * domain
                for ci, (a, o) in enumerate(cols):
                    if (blk[:, :, a, o]
                            != vals[0][:, ci][None, :]).any():
                        bad = (ci, lane)
                        break
                if bad is not None:
                    break
        if bad is not None:
            c_bad, offender = bad
            a, o = cols[c_bad]
            refines[(a, o)] = refines.get((a, o), 0) + 1
            if offender is None or \
                    refines[(a, o)] > _MAX_REFINE_PER_COL:
                what = "valid" if o == W else f"lane {o}"
                return _irregular(
                    f"inference unstable (action {a}, {what})")
            new_keys = tuple(sorted(keys + (offender,)))
            rest = [c for c in cols if c != (a, o)]
            if rest:
                pending.insert(0, (keys, rest))
            pending.insert(0, (new_keys, [(a, o)]))
            continue
        table_u32 = vals[0]  # [D, n_cols], exact step outputs
        if not keys:
            consts.extend((a, o, int(table_u32[0, j]))
                          for j, (a, o) in enumerate(cols))
            continue
        # Passthrough columns — the table is the identity on the
        # output's own single key lane — ride the broadcast base frame.
        live = []
        if len(keys) == 1:
            ident = assigns[:, 0]
            for j, (a, o) in enumerate(cols):
                if o == keys[0] and o < W and \
                        (table_u32[:, j] == ident).all():
                    copies += 1
                else:
                    live.append(j)
        else:
            live = list(range(len(cols)))
        if not live:
            continue
        cols = [cols[j] for j in live]
        table_u32 = table_u32[:, live]
        strides = []
        s = 1
        for k in reversed(keys):
            strides.append(s)
            s *= 1 << bits[k]
        strides = tuple(reversed(strides))
        table = np.empty((domain, 2 * len(cols)), np.float32)
        table[:, 0::2] = (table_u32 & 0xFFFF).astype(np.float32)
        table[:, 1::2] = (table_u32 >> 16).astype(np.float32)
        groups.append(_Group(tuple(keys), strides, domain, cols, table))

    plan = MatmulPlan(W, F, groups, consts, copies)
    return MatmulClassification(
        True,
        f"regular ({len(groups)} key groups, "
        f"{plan.matmul_ops} macs/row)", plan)


def matmul_expand(dm, plan: MatmulPlan, vecs, valid, tables=None):
    """Drop-in replacement for ``engine.expand_frontier`` on a regular
    model: same signature, same returns (``succ_flat [B*F, W]``,
    ``valid_flat [B*F]``, ``succ_count``, ``terminal [B]``), same bits
    — successor generation runs as one dense product per key group
    instead of the per-row vmapped ``step``. ``tables`` optionally
    supplies the per-group transition tables as live arrays (one per
    ``plan.groups`` entry, in order) — the megakernels pass them as
    ``pallas_call`` operands, since a kernel may not close over array
    constants; the default materializes each group's host table
    in-trace."""
    if tables is None:
        tables = [jnp.asarray(g.table) for g in plan.groups]
    B = vecs.shape[0]
    F, W = plan.fanout, plan.width
    has_boundary = dm.boundary(
        jnp.zeros((W,), jnp.uint32)) is not None
    # Base frame: every successor starts as a copy of its source row —
    # passthrough lanes are done already; tabulated outputs overwrite.
    succ = jnp.broadcast_to(vecs[:, None, :], (B, F, W))
    sv = jnp.zeros((B, F), jnp.bool_)
    for a, o, val in plan.consts:
        if o == W:
            sv = sv.at[:, a].set(bool(val))
        else:
            succ = succ.at[:, a, o].set(jnp.uint32(val))
    for g, table in zip(plan.groups, tables):
        kidx = jnp.zeros((B,), jnp.int32)
        for k, stride in zip(g.keys, g.strides):
            kidx = kidx + vecs[:, k].astype(jnp.int32) * stride
        # ≥2D iota (Mosaic requires it); one-hot [B, D] × table
        # [D, 2n] is the MXU-shaped product (exact: the one-hot picks
        # one row, every entry < 2^16 is an exact f32 integer).
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, g.domain), 1)
        onehot = (kidx[:, None] == iota).astype(jnp.float32)
        prod = jnp.dot(onehot, table,
                       preferred_element_type=jnp.float32)
        cols = (prod[:, 0::2].astype(jnp.uint32)
                | (prod[:, 1::2].astype(jnp.uint32) << 16))
        for j, (a, o) in enumerate(g.cols):
            if o == W:
                sv = sv.at[:, a].set(cols[:, j] != 0)
            else:
                succ = succ.at[:, a, o].set(cols[:, j])
    sv = sv & valid[:, None]
    if has_boundary:
        sv = sv & jax.vmap(jax.vmap(dm.boundary))(succ)
    succ_count = jnp.sum(sv, dtype=jnp.int64)
    terminal = valid & ~sv.any(axis=1)
    s = sv.size
    return succ.reshape(s, W), sv.reshape(s), succ_count, terminal

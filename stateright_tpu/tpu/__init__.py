"""The TPU engine: whole-frontier breadth-first checking on device.

This package is the BASELINE.json north star — a `tpu_bfs` strategy
alongside the host `spawn_bfs`/`spawn_dfs`. Where the reference's BFS
(`src/checker/bfs.rs`) has worker threads pulling one state at a time
through a job market, the TPU engine inverts the loop: each *wave* advances
the entire frontier as a batch under one jitted program —

    encode states -> vmap(step) -> fingerprint -> dedup against a
    device-resident sorted fingerprint table -> evaluate properties ->
    compact the next frontier

Models opt in by providing a :class:`DeviceModel` (see ``device_model.py``):
a fixed-width ``uint32`` state encoding plus a jittable per-state successor
function. Multi-chip runs shard the fingerprint space across a
``jax.sharding.Mesh`` (see ``sharded.py``).

Fingerprints are 64-bit; this module enables ``jax_enable_x64`` so the
visited table can live in a single sorted ``uint64`` array (TPUs emulate
64-bit integer compares — measured fast enough to sort 1M fingerprints in
well under a millisecond on a v5e).
"""

import jax

jax.config.update("jax_enable_x64", True)

from .device_model import DeviceModel  # noqa: E402
from .hashing import SENTINEL, device_fp64, host_fp64  # noqa: E402
from .engine import TpuBfsChecker  # noqa: E402

__all__ = [
    "DeviceModel",
    "TpuBfsChecker",
    "device_fp64",
    "host_fp64",
    "SENTINEL",
]

"""The TPU engine: whole-frontier breadth-first checking on device.

This package is the BASELINE.json north star — a `tpu_bfs` strategy
alongside the host `spawn_bfs`/`spawn_dfs`. Where the reference's BFS
(`src/checker/bfs.rs`) has worker threads pulling one state at a time
through a job market, the TPU engine inverts the loop: each *wave* advances
the entire frontier as a batch under one jitted program —

    encode states -> vmap(step) -> fingerprint -> dedup against a
    device-resident open-addressing fingerprint hash table -> evaluate
    properties -> compact the next frontier

Models opt in by providing a :class:`DeviceModel` (see ``device_model.py``):
a fixed-width ``uint32`` state encoding plus a jittable per-state successor
function. Multi-chip runs shard the fingerprint space across a
``jax.sharding.Mesh`` (see ``sharded.py``).

Fingerprints are 64-bit; importing this module enables ``jax_enable_x64``
so the visited table can live in a single ``uint64`` array (TPUs emulate
64-bit integer compares; the open-addressing probe loop does a handful per
candidate). The flip is process-wide — it changes jax's *default* dtypes
for all code in the process — which is why it happens here, on first use
of the TPU engine (``spawn_tpu_bfs`` / an explicit ``stateright_tpu.tpu``
import), and not when the top-level package is imported: host-only users
never see it. An explicit ``JAX_ENABLE_X64=0`` in the environment is
treated as an opt-out and makes this import fail loudly instead of
silently overriding the user's setting.
"""

import os

import jax

# jax's own false spellings (config.bool_env): match them all so no
# explicit opt-out is silently overridden.
_explicit = os.environ.get("JAX_ENABLE_X64", "")
if _explicit.lower() in ("n", "no", "f", "false", "off", "0"):
    raise ImportError(
        "the stateright_tpu TPU engine needs 64-bit array support for its "
        "uint64 fingerprint table, but JAX_ENABLE_X64 is explicitly "
        "disabled in the environment; unset it (or use the host engines "
        "spawn_bfs/spawn_dfs, which do not require jax at all)")
jax.config.update("jax_enable_x64", True)

from .device_model import DeviceModel  # noqa: E402
from .hashing import SENTINEL, device_fp64, host_fp64  # noqa: E402
from .engine import TpuBfsChecker  # noqa: E402

__all__ = [
    "DeviceModel",
    "TpuBfsChecker",
    "device_fp64",
    "host_fp64",
    "SENTINEL",
]

"""Model-derived bit-packed row format for device state storage.

Device states are *computed* as ``uint32[state_width]`` registers (the
``DeviceModel`` contract), but most models declare lanes far narrower
than 32 bits — a 2pc RM state is 2 bits, a paxos ballot index 4 — so
storing, probing, exchanging, and checkpointing full-width rows moves
3-4x the bytes the encoding needs. Explicit-state checking on
accelerators is bandwidth-bound (GPUexplore, arXiv:1801.05857; ScalaBFS,
arXiv:2105.11754: HBM traffic, not FLOPs, is the currency), so the
engines keep rows *packed* at rest and unpack to registers only inside
the wave.

This module is the layout compiler: :func:`compile_layout` turns a
model's :meth:`DeviceModel.lane_bits` declaration into a static
word-aligned bitfield plan and emits matching jittable
``pack(uint32[..., W]) -> uint32[..., Wp]`` / ``unpack`` programs
(``Wp = ceil(sum(bits) / 32)``) plus numpy twins for the host-side cold
paths (seeding, checkpoint conversion). Compute is untouched: ``step``,
properties, fingerprints, and symmetry rewrites always see the exact
unpacked lanes, so counts, discoveries, and parent maps are
bit-identical with packing on or off (the pack-matrix suite pins this).

Lane specs (one per lane, in lane order):

- ``b`` (int, 1..32): a plain lane whose values fit ``b`` bits. The
  declared width is part of the encoding contract, like injectivity —
  packing truncates silently beyond it (``pack_np_checked`` exists for
  cold-path validation).
- ``(b, sentinel)``: a lane over ``[0, 2^b - 1)`` plus one out-of-band
  sentinel value (e.g. an actor network slot's ``EMPTY_ENV`` =
  ``0xFFFFFFFF``). The sentinel packs as the field's all-ones pattern
  and unpacks back exactly; real values must stay strictly below
  ``2^b - 1``.

Invalid specs (bits out of range, wrong lane count, a sentinel that
collides with the value range) are rejected here, at build time — never
as silent corruption mid-run.

**Tenant lane (round 16).** The wave multiplexer stores rows from many
co-scheduled jobs in one frontier, so a packed row must say which job it
belongs to. :meth:`PackedLayout.with_tenant_lane` derives a layout whose
rows carry one extra *word-aligned* trailing lane holding a small tenant
slot index. The model lanes' placement, widths, and sentinel rules are
byte-for-byte unchanged (the tenant lane starts on its own fresh word),
so stripping the trailing word recovers exactly the solo storage row —
which is how multiplexed checkpoints stay bit-identical to solo ones.

**Matmul wave (round 19).** The same ``lane_bits`` declaration this
module compiles is the lane-domain source for the matmul-wave
transition compiler (``tpu/matmul_wave.py``): ``classify`` runs
:func:`compile_layout` first and reads each lane's declared ``bits``
(and sentinel status) off the resulting plan, so spec validation,
domain sizing, and the regularity gate all share one parse — a model
whose declaration is wrong fails here, at build time, for both
consumers.

**In-kernel use (round 15).** The jittable ``pack``/``unpack`` codecs
are pure ``jnp`` shift/mask pipelines with every constant created
in-trace, so they trace directly inside a Pallas kernel body: the wave
megakernel (``pallas_table.build_wave_megakernel``) reads PACKED rows
from HBM and unpacks the lanes the step function consumes entirely in
VMEM, then re-packs the successor window before it leaves the kernel —
registers never touch HBM. The ``packed_row_bytes`` /
``unpacked_row_bytes`` attributes are the per-row figures the kernel's
VMEM working-set gate (``pallas_table.wave_kernel_ok``) budgets.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PackedLayout", "compile_layout"]


class _Lane:
    __slots__ = ("bits", "word", "offset", "sentinel", "spill")

    def __init__(self, bits: int, word: int, offset: int,
                 sentinel: Optional[int]):
        self.bits = bits
        self.word = word          # first packed word holding this lane
        self.offset = offset      # bit offset within that word
        self.sentinel = sentinel  # unpacked value of the all-ones field
        self.spill = offset + bits > 32  # straddles into word+1


def _parse_spec(spec, i: int) -> Tuple[int, Optional[int]]:
    if isinstance(spec, (tuple, list)):
        if len(spec) != 2:
            raise ValueError(
                f"lane {i}: spec {spec!r} must be `bits` or "
                "`(bits, sentinel)`")
        bits, sentinel = int(spec[0]), int(spec[1])
    else:
        bits, sentinel = int(spec), None
    if not 1 <= bits <= 32:
        raise ValueError(
            f"lane {i}: declared width {bits} outside 1..32")
    if sentinel is not None:
        if not 0 <= sentinel < (1 << 32):
            raise ValueError(
                f"lane {i}: sentinel {sentinel} is not a uint32")
        if bits == 32:
            # A 32-bit field represents everything; a sentinel adds
            # nothing and the all-ones reservation would be a lie.
            sentinel = None
        elif sentinel < (1 << bits) - 1:
            raise ValueError(
                f"lane {i}: sentinel {sentinel} collides with the "
                f"{bits}-bit value range (must be >= {(1 << bits) - 1})")
    return bits, sentinel


class PackedLayout:
    """A compiled word-aligned bitfield plan for one model's rows.

    ``packs`` is False when the plan saves nothing (every lane 32 bits,
    or ``Wp == W``); the engines then skip packing entirely and this
    object degrades to an identity codec.
    """

    def __init__(self, specs: Sequence, state_width: int):
        specs = list(specs)
        if len(specs) != state_width:
            raise ValueError(
                f"lane_bits declares {len(specs)} lanes; the model's "
                f"state_width is {state_width}")
        self.width = state_width
        self.lanes: List[_Lane] = []
        cursor = 0
        for i, spec in enumerate(specs):
            bits, sentinel = _parse_spec(spec, i)
            self.lanes.append(
                _Lane(bits, cursor // 32, cursor % 32, sentinel))
            cursor += bits
        self.total_bits = cursor
        self.packed_width = max(1, -(-cursor // 32))
        self.packs = self.packed_width < self.width
        #: bytes per row in each form — the per-row figures the
        #: megakernel's VMEM working-set accounting
        #: (``pallas_table.wave_kernel_bytes``) is expressed in: packed
        #: rows ride HBM, registers exist only in VMEM.
        self.packed_row_bytes = 4 * self.packed_width
        self.unpacked_row_bytes = 4 * self.width
        #: JSON-serializable form (checkpoint headers self-describe
        #: their layout with this).
        self.specs = [(l.bits if l.sentinel is None
                       else [l.bits, l.sentinel]) for l in self.lanes]
        #: set by :meth:`with_tenant_lane` on derived layouts; the base
        #: layout compiled from a model never has one.
        self.tenant_lane: Optional[_Lane] = None
        self._jit_pack = None
        self._jit_unpack = None

    def with_tenant_lane(self, bits: int = 16) -> "PackedLayout":
        """Derives a layout whose packed rows grow one trailing
        word-aligned lane carrying a tenant (job) slot index.

        The model lanes are re-laid out identically — same words, same
        offsets, same sentinels — and the tenant lane occupies its own
        fresh word after them, so ``packed[..., :-1]`` of a tenant row
        is exactly the row the base layout would have produced."""
        if self.tenant_lane is not None:
            raise ValueError("layout already carries a tenant lane")
        if not 1 <= int(bits) <= 32:
            raise ValueError(
                f"tenant lane width {bits} outside 1..32")
        out = PackedLayout(self.specs, self.width)
        out.tenant_lane = _Lane(int(bits), out.packed_width, 0, None)
        out.packed_width += 1
        out.packed_row_bytes = 4 * out.packed_width
        return out

    # -- numpy codec (host cold paths) -----------------------------------

    def pack_np(self, rows: np.ndarray) -> np.ndarray:
        """``uint32[..., W] -> uint32[..., Wp]`` (vectorized numpy)."""
        rows = np.asarray(rows, np.uint32)
        out = np.zeros(rows.shape[:-1] + (self.packed_width,), np.uint32)
        for i, l in enumerate(self.lanes):
            mask = np.uint32((1 << l.bits) - 1) if l.bits < 32 \
                else np.uint32(0xFFFFFFFF)
            v = rows[..., i]
            f = (np.minimum(v, mask) if l.sentinel is not None
                 else v & mask)
            out[..., l.word] |= (f << np.uint32(l.offset)).astype(
                np.uint32)
            if l.spill:
                out[..., l.word + 1] |= (
                    f >> np.uint32(32 - l.offset)).astype(np.uint32)
        return out

    def unpack_np(self, packed: np.ndarray) -> np.ndarray:
        """``uint32[..., Wp] -> uint32[..., W]`` (vectorized numpy)."""
        packed = np.asarray(packed, np.uint32)
        out = np.zeros(packed.shape[:-1] + (self.width,), np.uint32)
        for i, l in enumerate(self.lanes):
            out[..., i] = self._lane_np(packed, l)
        return out

    def _lane_np(self, packed: np.ndarray, l: _Lane) -> np.ndarray:
        mask = np.uint32((1 << l.bits) - 1) if l.bits < 32 \
            else np.uint32(0xFFFFFFFF)
        f = packed[..., l.word] >> np.uint32(l.offset)
        if l.spill:
            f = f | (packed[..., l.word + 1]
                     << np.uint32(32 - l.offset)).astype(np.uint32)
        f = f & mask
        if l.sentinel is not None:
            f = np.where(f == mask, np.uint32(l.sentinel), f)
        return f.astype(np.uint32)

    def lane_np(self, packed: np.ndarray, lane: int) -> np.ndarray:
        """One unpacked lane column from packed rows (e.g. the engine's
        error-lane check) without materializing the full unpack."""
        return self._lane_np(packed, self.lanes[lane])

    def tenant_np(self, packed: np.ndarray) -> np.ndarray:
        """The tenant slot column of tenant-lane rows (numpy)."""
        if self.tenant_lane is None:
            raise ValueError("layout has no tenant lane")
        return self._lane_np(np.asarray(packed, np.uint32),
                             self.tenant_lane)

    def pack_tenant_np(self, rows: np.ndarray,
                       tags: np.ndarray) -> np.ndarray:
        """``(uint32[..., W], tag[...]) -> uint32[..., Wp+1]``: packs
        model lanes exactly as the base layout would, then writes the
        tenant slot into the trailing word (numpy)."""
        if self.tenant_lane is None:
            raise ValueError("layout has no tenant lane")
        out = self.pack_np(rows)
        l = self.tenant_lane
        mask = np.uint32((1 << l.bits) - 1) if l.bits < 32 \
            else np.uint32(0xFFFFFFFF)
        out[..., l.word] = np.asarray(tags, np.uint32) & mask
        return out

    def check_fits(self, rows: np.ndarray) -> None:
        """Raises if any lane value exceeds its declared width — the
        cold-path guard (seeding, checkpoint conversion) for a model
        whose ``lane_bits`` contract is wrong."""
        rows = np.asarray(rows, np.uint32)
        for i, l in enumerate(self.lanes):
            if l.bits == 32:
                continue
            mask = np.uint32((1 << l.bits) - 1)
            v = rows[..., i]
            bad = (v > mask) if l.sentinel is None else \
                ((v >= mask) & (v != np.uint32(l.sentinel)))
            if bad.any():
                raise ValueError(
                    f"lane {i} holds value {int(v[bad.nonzero()][0])}, "
                    f"outside its declared {l.bits}-bit width — the "
                    "model's lane_bits() contract is wrong")

    # -- jittable codec (wave programs) ----------------------------------

    def pack(self, rows):
        """``uint32[..., W] -> uint32[..., Wp]`` (traceable jnp)."""
        import jax.numpy as jnp

        words = [jnp.zeros(rows.shape[:-1], jnp.uint32)
                 for _ in range(self.packed_width)]
        for i, l in enumerate(self.lanes):
            mask = jnp.uint32((1 << l.bits) - 1) if l.bits < 32 \
                else jnp.uint32(0xFFFFFFFF)
            v = rows[..., i]
            f = (jnp.minimum(v, mask) if l.sentinel is not None
                 else v & mask)
            words[l.word] = words[l.word] | (f << l.offset)
            if l.spill:
                words[l.word + 1] = words[l.word + 1] \
                    | (f >> (32 - l.offset))
        return jnp.stack(words, axis=-1)

    def unpack(self, packed):
        """``uint32[..., Wp] -> uint32[..., W]`` (traceable jnp)."""
        import jax.numpy as jnp

        return jnp.stack(
            [self._lane(packed, l) for l in self.lanes], axis=-1)

    def _lane(self, packed, l: _Lane):
        import jax.numpy as jnp

        mask = jnp.uint32((1 << l.bits) - 1) if l.bits < 32 \
            else jnp.uint32(0xFFFFFFFF)
        f = packed[..., l.word] >> l.offset
        if l.spill:
            f = f | (packed[..., l.word + 1] << (32 - l.offset))
        f = f & mask
        if l.sentinel is not None:
            f = jnp.where(f == mask, jnp.uint32(l.sentinel), f)
        return f

    def lane(self, packed, lane: int):
        """One unpacked lane from packed rows (traceable jnp)."""
        return self._lane(packed, self.lanes[lane])

    def tenant(self, packed):
        """The tenant slot column of tenant-lane rows (traceable jnp)."""
        if self.tenant_lane is None:
            raise ValueError("layout has no tenant lane")
        return self._lane(packed, self.tenant_lane)

    def pack_tenant(self, rows, tags):
        """``(uint32[..., W], tag[...]) -> uint32[..., Wp+1]``: the
        traceable twin of :meth:`pack_tenant_np`."""
        import jax.numpy as jnp

        if self.tenant_lane is None:
            raise ValueError("layout has no tenant lane")
        l = self.tenant_lane
        mask = jnp.uint32((1 << l.bits) - 1) if l.bits < 32 \
            else jnp.uint32(0xFFFFFFFF)
        return self.pack(rows).at[..., l.word].set(
            tags.astype(jnp.uint32) & mask)

    def __repr__(self) -> str:
        return (f"PackedLayout(W={self.width}, Wp={self.packed_width}, "
                f"bits={self.total_bits}, packs={self.packs})")


def compile_layout(lane_bits, state_width: int) -> PackedLayout:
    """Compiles a model's ``lane_bits()`` declaration into a
    :class:`PackedLayout`. ``None`` (the conservative default: 32 bits
    per lane) yields the identity layout (``packs`` False)."""
    if lane_bits is None:
        lane_bits = [32] * state_width
    return PackedLayout(lane_bits, state_width)

"""Multi-chip BFS: fingerprint-sharded visited tables + ICI all-to-all.

The reference is a single-process checker; its only scale-out axis is a
work-stealing thread pool (`bfs.rs:29-30,70-74`). The TPU-native scale-out
replaces that with SPMD over a ``jax.sharding.Mesh``:

- **Ownership**: fingerprint space is hash-partitioned — device
  ``fp % n_shards`` owns a state. Each device holds the sorted visited
  table for *its* fingerprints only, so table capacity scales linearly
  with chips.
- **Wave shuffle**: every wave, each device expands its share of the
  frontier, fingerprints the successors, buckets them by owner, and a
  single ``lax.all_to_all`` (ICI when the mesh is a TPU slice, DCN across
  hosts) routes each successor to its owner, which dedups it against its
  local table. New states stay with their owner as its next-wave frontier
  share — ownership doubles as load balancing.
- **Parent pointers travel with the data**: each routed successor carries
  its parent's fingerprint and eventually-bits, so the host parent map
  (`bfs.rs:26`) needs no second exchange.

Everything inside the wave is one jitted ``shard_map`` program; the host
only feeds per-shard frontier batches and drains per-shard new-state
streams.

Like the reference's multithreaded BFS (`checker.rs:115-118`), discovery
paths are not guaranteed shortest when sharded: wave composition across
shard queues is not a global level order.
"""

from __future__ import annotations

import time
import warnings
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import shard_map

from ..resilience.faults import ExchangeIntegrityError
from ..resilience.membership import EpochOwnership, OwnerMap
from .device_model import DeviceModel
from .engine import (TpuBfsChecker, compaction_order, dedup_impl,
                     eval_properties, expand_frontier,
                     fingerprint_successors, first_occurrence_candidates,
                     host_table_insert, matmul_expand, pick_bucket,
                     sender_kernel_impl, succ_bucket_ladder)
from .hashing import SENTINEL

__all__ = ["ShardedTpuBfsChecker"]


class ShardedTpuBfsChecker(EpochOwnership, TpuBfsChecker):
    """The multi-device wave engine. ``batch_size`` is per shard.

    The ``_ENGINE_ID`` class attribute tags this engine's wave events
    in the obs stream.

    ``exchange_novel_only`` (default on) runs the intra-wave local dedup
    on the SENDER side, before the all-to-all: only each shard's
    locally-novel candidates (first occurrence of each distinct
    fingerprint among its B*F successors) enter the exchange, so
    duplicate successors die in their producer's local pass instead of
    riding the interconnect to be discarded by the owner (the
    shared-hash-table observation of arXiv:1004.2772: thin the traffic
    INTO the global structure). Bit-identical: a dropped row is a
    same-shard later duplicate, which the owner-side first-occurrence
    rule — applied to the shard-major receive order — could never have
    selected anyway."""

    _ENGINE_ID = "sharded"

    def __init__(self, builder, batch_size: int = 512,
                 device_model: Optional[DeviceModel] = None,
                 table_capacity: int = 1 << 16,
                 mesh: Optional[Mesh] = None,
                 exchange_novel_only: Optional[bool] = None, **kwargs):
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("shard",))
        self._mesh = mesh
        self._n_shards = mesh.devices.size
        # Epoch-versioned ownership (resilience.membership): partition
        # ``fp % n`` normally lives on shard ``fp % n`` (the identity
        # map — device routing stays the raw modulo, zero overhead),
        # but the assignment can be remapped at a rest point
        # (``set_owner_assignment``), bumping the epoch; compiled wave
        # programs are keyed by it, so stale routing can never run.
        self._owner_map = OwnerMap.identity(self._n_shards)
        self._exchange_novel = (True if exchange_novel_only is None
                                else bool(exchange_novel_only))
        if kwargs.pop("pipeline", None):
            raise NotImplementedError(
                "the sharded engine's wave loop is not software-pipelined "
                "yet; drop pipeline=True (the all-to-all already overlaps "
                "per-shard work)")
        if kwargs.get("table_impl") == "pallas":
            import warnings

            warnings.warn(
                "the sharded engines run the XLA visited table; "
                "table_impl='pallas' is single-device for now",
                RuntimeWarning, stacklevel=2)
            kwargs["table_impl"] = "xla"
        super().__init__(builder, batch_size=batch_size,
                         device_model=device_model,
                         table_capacity=table_capacity,
                         pipeline=False, **kwargs)

    def _pre_spawn_check(self) -> None:
        from ..model import Expectation

        for p, fn in zip(self._properties, self._prop_fns):
            if p.expectation is Expectation.EVENTUALLY and fn is None:
                raise NotImplementedError(
                    f"sharded engine requires a device predicate for "
                    f"eventually property {p.name!r} (per-path bits are "
                    "cleared on device before the all-to-all)")

    # -- Sharded state ----------------------------------------------------

    def _pending_blocks(self) -> list:
        """Frontier blocks across all shard queues (plus anything still
        in the pre-split queue, when the worker hasn't started);
        paged-out blocks materialize non-destructively."""
        from ..store.tiered import FrontierRef

        blocks = list(self._pending)
        for q in getattr(self, "_queues", []):
            blocks.extend(q)
        return [self._store.load_ref(b) if isinstance(b, FrontierRef)
                else b for b in blocks]

    def _new_table(self, fps) -> jax.Array:
        """Global [n_shards * capacity] table; each shard's slice is an
        open-addressing hash table over its owned fingerprints. Also
        (re)establishes ``_shard_counts`` — per-shard table occupancy,
        the quantity ``_needs_growth`` compares against capacity — so
        fresh runs, growth rehashes, and checkpoint resumes all account
        for every resident fingerprint."""
        n, cap = self._n_shards, self._capacity
        table = np.full((n, cap), SENTINEL, np.uint64)
        buckets: list = [[] for _ in range(n)]
        for fp in fps:
            buckets[self._owner(int(fp))].append(fp)
        for i, bucket in enumerate(buckets):
            host_table_insert(table[i], np.fromiter(
                (int(f) for f in bucket), np.uint64, len(bucket)))
        self._shard_counts = [len(b) for b in buckets]
        self._resident = sum(self._shard_counts)
        sharding = jax.sharding.NamedSharding(self._mesh, P("shard"))
        return jax.device_put(table.reshape(n * cap), sharding)

    def _grow_table_impl(self) -> None:
        # The base _grow_table wraps this with the OOM graceful
        # degradation (grow_oom fault hook + batch-bucket shedding).
        real = np.asarray(self._visited)
        real = real[real != SENTINEL]
        old = self._capacity
        while self._needs_growth():
            self._capacity *= 2
        if self._tracer.enabled:
            self._tracer.event("grow", kind="table", old=old,
                               new=self._capacity)
        try:
            self._visited = self._new_table(real)
        except BaseException:
            self._capacity = old
            raise

    def _reset_engine_state(self) -> None:
        # restart_from support: stale per-shard queues from the failed
        # run must not leak into _pending_blocks before the restarted
        # worker re-splits the reloaded frontier.
        self.__dict__.pop("_queues", None)

    def _needs_growth_at(self, capacity: int) -> bool:
        """Capacity is per shard and a single wave can add up to
        ``n_shards * B * F`` states to ONE shard (every device's full
        fan-out routed to the same owner), so headroom is reserved
        against the fullest shard — and the open-addressing table wants
        load factor <= 1/2 so probe chains stay O(1)."""
        worst = max(self._shard_counts) if getattr(
            self, "_shard_counts", None) else 0
        return (worst + self._n_shards * self._B_max * self._F
                > capacity // 2)

    def _table_bytes(self, capacity: int) -> int:
        # Capacity is PER SHARD; the device footprint is the mesh's.
        return self._n_shards * capacity * 8

    def _spill_enough(self, keep_fps: np.ndarray) -> bool:
        """Per-shard growth predicate over the survivors: the fullest
        shard's KEPT rows must leave wave headroom at the current
        capacity."""
        if not len(keep_fps):
            worst = 0
        else:
            assign = np.asarray(self._owner_map.assignment(), np.int64)
            owners = assign[(np.asarray(keep_fps, np.uint64)
                             % np.uint64(self._n_shards)).astype(
                                 np.int64)]
            worst = int(np.bincount(
                owners, minlength=self._n_shards).max())
        return (worst + self._n_shards * self._B_max * self._F
                <= self._capacity // 2)

    # -- Sharded wave program ---------------------------------------------

    def _succ_full_rows(self, B: int) -> int:
        # A shard can receive every other shard's full fan-out.
        return self._n_shards * B * self._F

    # The single-kernel wave here is the table-less per-shard sender
    # megakernel; the base _kernel_path gates on this.
    _SENDER_KERNEL = True

    def _route_fn(self, B: int):
        """Builds the sender side of the wave — expand, fingerprint,
        eventually-bit clearing, optional sender-side local dedup, and
        the all-to-all routing home. Shared by the wave program and the
        overflow regather (which re-runs it deterministically and lets
        XLA DCE the property/terminal outputs it does not use)."""
        dm = self._dm
        n = self._n_shards
        F, W = self._F, self._W
        Wr = self._Wrow
        layout = self._wave_layout()
        S = B * F          # successors per shard per wave
        CAP = S            # per-destination bucket capacity (worst case)
        R = n * CAP        # receive buffer rows per shard
        prop_fns = list(self._prop_fns)
        use_sym = self._use_symmetry
        exchange_novel = self._exchange_novel
        sentinel = jnp.uint64(SENTINEL)
        # Ownership assignment, baked into the compiled program (the
        # wave cache is epoch-keyed, so a remap recompiles). Identity
        # keeps the raw-modulo routing — the compiled HLO is unchanged
        # from the pre-epoch engine.
        assign = (None if self._owner_map.is_identity
                  else jnp.asarray(
                      np.asarray(self._owner_map.assignment(),
                                 np.int32)))
        from ..model import Expectation
        eventually_device = [
            i for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY]
        # Single-kernel wave (ISSUE 10): the sender megakernel runs the
        # per-shard front half (unpack → expand → fingerprint → local
        # dedup → re-pack) as one pallas_call; the partitioned table
        # keeps the probe owner-side after the all-to-all.
        sender = sender_kernel_impl(self._wave_kernel_on, dm, B,
                                    use_sym, layout, exchange_novel,
                                    matmul_plan=self._matmul_plan)

        def route(vecs, fps, valid, ebits):
            # Local views: vecs [B, Wr] (storage row format), fps [B],
            # valid [B], ebits [B]. Unpack to real lanes for compute.
            store = vecs
            if layout is not None:
                vecs = layout.unpack(store)
            conds = eval_properties(prop_fns, vecs)
            if sender is not None:
                (succ_store, dedup_fps, path_fps, sflat,
                 send_mask) = sender(store, valid)
                succ_count = jnp.sum(sflat, dtype=jnp.int64)
                terminal = valid & ~sflat.reshape(B, F).any(axis=1)
            else:
                succ_flat, sflat, succ_count, terminal = (
                    matmul_expand(dm, self._matmul_plan, vecs, valid)
                    if self._matmul_plan is not None
                    else expand_frontier(dm, vecs, valid))
                dedup_fps, path_fps = fingerprint_successors(
                    dm, succ_flat, sflat, use_sym)
            parent_fps = jnp.repeat(fps, F)
            # Children inherit the parent's ebits *after* clearing bits for
            # eventually properties satisfied at the parent (bfs.rs:212-222)
            # — cleared here because the parent row is gone post-shuffle.
            ebits_cleared = ebits
            for i in eventually_device:
                ebits_cleared = ebits_cleared & ~jnp.where(
                    conds[i], jnp.uint32(1 << i), jnp.uint32(0))
            child_ebits = jnp.repeat(ebits_cleared, F)

            if sender is None:
                if exchange_novel:
                    # Sender-side local dedup: only the first
                    # occurrence of each distinct fingerprint enters
                    # the exchange. A dropped row is a same-shard later
                    # duplicate the owner's first-occurrence rule (over
                    # the shard-major receive order) could never
                    # select, so the surviving rows — and their
                    # relative order — are unchanged.
                    send_mask = first_occurrence_candidates(dedup_fps)
                else:
                    send_mask = sflat

            # Bucket successors by owner shard and all-to-all them home.
            part = (dedup_fps % n).astype(jnp.int32)
            dest = part if assign is None else assign[part]
            owner = jnp.where(send_mask, dest, n)
            order = jnp.argsort(owner, stable=True)
            so = owner[order]
            starts = jnp.searchsorted(so, jnp.arange(n + 1))
            rank = jnp.arange(S) - starts[jnp.clip(so, 0, n)]
            slot = so * CAP + rank  # >= n*CAP for the invalid bucket -> drop

            def scatter(x, fill):
                out = jnp.full((n * CAP,) + x.shape[1:], fill, x.dtype)
                return out.at[slot].set(x[order], mode="drop")

            # Pack BEFORE the exchange: only packed rows ride the
            # all-to-all (stacking on the novelty routing above — the
            # interconnect now moves Wr words per state, not W), and the
            # owner side never unpacks: received rows flow packed
            # through dedup compaction into its queue/arena. (The
            # sender megakernel already emitted storage rows.)
            if sender is None:
                succ_store = (succ_flat if layout is None
                              else layout.pack(succ_flat))
            send_vecs = scatter(succ_store, 0).reshape(n, CAP, Wr)
            send_dedup = scatter(dedup_fps, sentinel).reshape(n, CAP)
            send_path = scatter(path_fps, sentinel).reshape(n, CAP)
            send_parent = scatter(parent_fps, sentinel).reshape(n, CAP)
            send_ebits = scatter(child_ebits, 0).reshape(n, CAP)

            a2a = partial(jax.lax.all_to_all, axis_name="shard",
                          split_axis=0, concat_axis=0, tiled=True)
            recv_vecs = a2a(send_vecs).reshape(R, Wr)
            recv_dedup = a2a(send_dedup).reshape(R)
            recv_path = a2a(send_path).reshape(R)
            recv_parent = a2a(send_parent).reshape(R)
            recv_ebits = a2a(send_ebits).reshape(R)
            return (conds, succ_count, terminal, recv_vecs, recv_dedup,
                    recv_path, recv_parent, recv_ebits)

        return route

    def _wave_fn(self, capacity: int, batch: Optional[int] = None,
                 out_rows: Optional[int] = None):
        B = self._B if batch is None else batch
        n = self._n_shards
        F, W = self._F, self._W
        R = n * B * F      # receive buffer rows per shard
        K = R if out_rows is None else min(max(1, int(out_rows)), R)
        key = (B, capacity, K, self._owner_map.epoch)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached
        mesh = self._mesh
        prop_fns = list(self._prop_fns)
        route = self._route_fn(B)
        dedup = dedup_impl(self._table_impl, capacity)

        def wave_local(vecs, fps, valid, ebits, visited):
            (conds, succ_count, terminal, recv_vecs, recv_dedup,
             recv_path, recv_parent, recv_ebits) = route(
                vecs, fps, valid, ebits)

            # Owner-side dedup (cross-sender duplicates + revisits) +
            # insert against this shard's table slice, then the ladder's
            # K-row compaction; the full novelty mask and the overflow
            # flag ship so a truncated wave regathers losslessly.
            new_mask, new_count, cand_count, merged = dedup(
                recv_dedup, visited)
            comp = compaction_order(new_mask)[:K]
            new_vecs = recv_vecs[comp]
            new_fps = recv_path[comp]
            new_parent = recv_parent[comp]
            new_ebits = recv_ebits[comp]
            overflow = new_count > K
            conds_out = [c for c in conds if c is not None]
            return (conds_out, succ_count[None], cand_count[None],
                    terminal, new_count[None], new_vecs, new_fps,
                    new_parent, new_ebits, new_mask, overflow[None],
                    merged)

        n_conds = sum(1 for fn in prop_fns if fn is not None)
        sharded = shard_map(
            wave_local, mesh=mesh,
            in_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                      P("shard")),
            out_specs=([P("shard")] * n_conds, P("shard"), P("shard"),
                       P("shard"), P("shard"), P("shard"), P("shard"),
                       P("shard"), P("shard"), P("shard"), P("shard"),
                       P("shard")),
            check_vma=False)
        # Donate the batch arrays too (0-3): they are rebuilt host-side
        # every wave, so the device copies are dead after the expand —
        # XLA can reuse their pages for the receive buffers.
        jitted = jax.jit(sharded, donate_argnums=(0, 1, 2, 3, 4))
        spec = jax.sharding.NamedSharding(mesh, P("shard"))

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=spec)

        jitted = self._aot(jitted, (
            sds((n * B, self._Wrow), jnp.uint32), sds((n * B,), jnp.uint64),
            sds((n * B,), jnp.bool_), sds((n * B,), jnp.uint32),
            sds((n * capacity,), jnp.uint64)))
        if self._prof.enabled:
            # Sharded wave programs bypass the shared program cache
            # (the ownership epoch keys them per instance), so static
            # cost capture (obs/prof.py) rides here instead of
            # _cached_program.
            self._prof.capture(self._prof_key(key), jitted)
        self._wave_cache[key] = jitted
        return jitted

    def _regather_fn(self, batch: int, out_rows: int):
        """Overflow recovery under ``shard_map``: re-runs the
        deterministic sender side (expand + fingerprint + exchange —
        the all-to-all routes the same rows to the same slots) and
        compacts with the wave's own per-shard novelty masks at a rung
        that fits. No table access; property outputs are DCE'd."""
        B = batch
        n = self._n_shards
        F, W = self._F, self._W
        R = n * B * F
        K = min(max(1, int(out_rows)), R)
        key = ("regather", B, K, self._owner_map.epoch)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached
        route = self._route_fn(B)

        def regather_local(vecs, fps, valid, ebits, new_mask):
            (_conds, _succ, _term, recv_vecs, _recv_dedup, recv_path,
             recv_parent, recv_ebits) = route(vecs, fps, valid, ebits)
            comp = compaction_order(new_mask)[:K]
            return (recv_vecs[comp], recv_path[comp], recv_parent[comp],
                    recv_ebits[comp])

        sharded = shard_map(
            regather_local, mesh=self._mesh,
            in_specs=(P("shard"),) * 5,
            out_specs=(P("shard"),) * 4,
            check_vma=False)
        jitted = jax.jit(sharded)
        spec = jax.sharding.NamedSharding(self._mesh, P("shard"))

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=spec)

        jitted = self._aot(jitted, (
            sds((n * B, self._Wrow), jnp.uint32), sds((n * B,), jnp.uint64),
            sds((n * B,), jnp.bool_), sds((n * B,), jnp.uint32),
            sds((n * R,), jnp.bool_)))
        self._wave_cache[key] = jitted
        return jitted

    def _inject_exchange_faults(self, shard_blocks: list) -> list:
        """Applies any armed all-to-all faults to the fetched shard
        blocks: ``a2a_short`` drops a block's tail row (a short
        delivery), ``a2a_corrupt`` overwrites a fingerprint with the
        sentinel (payload corruption). Both are then caught by the
        owner-side integrity check. A fault only fires when a nonempty
        block exists to damage, so every emitted ``fault`` event has an
        observable failure to pair with."""
        target = next((i for i, b in enumerate(shard_blocks)
                       if len(b[1])), None)
        if target is None:
            return shard_blocks
        if self._faults.fires("a2a_short", self._tracer, shard=target):
            vecs, fps, parents, ebits = shard_blocks[target]
            shard_blocks[target] = (vecs[:-1], fps[:-1], parents[:-1],
                                    ebits[:-1])
            # Re-pick: a one-row target is empty now, and the corrupt
            # fault below needs a row to damage.
            target = next((i for i, b in enumerate(shard_blocks)
                           if len(b[1])), None)
        if target is not None and self._faults.fires(
                "a2a_corrupt", self._tracer, shard=target):
            vecs, fps, parents, ebits = shard_blocks[target]
            fps = fps.copy()
            fps[-1] = np.uint64(SENTINEL)
            shard_blocks[target] = (vecs, fps, parents, ebits)
        return shard_blocks

    # -- Host orchestration -----------------------------------------------

    def _run_waves(self) -> None:
        from ..model import Expectation

        model = self._model
        n = self._n_shards
        F, W = self._F, self._W
        properties = self._properties
        eventually_idx = self._eventually_idx

        # Per-shard pending BLOCK queues, seeded by ownership.
        # (_shard_counts — table occupancy — was established by
        # _new_table; pending states are already resident there.)
        from collections import deque
        queues = [deque() for _ in range(n)]
        self._queues = queues
        assign_np = np.asarray(self._owner_map.assignment(), np.int64)
        while self._pending:
            vecs, fps, ebits = self._pending.popleft()
            owners = assign_np[(fps % np.uint64(n)).astype(np.int64)]
            for i in range(n):
                mask = owners == i
                k = int(mask.sum())
                if k:
                    queues[i].append((vecs[mask], fps[mask], ebits[mask]))

        self.wave_log.append((time.monotonic(), self._state_count))
        wave_index = 0
        while any(queues):
            wave_index += 1
            if (self._ckpt_path is not None
                    and wave_index % self._ckpt_every == 0):
                self._write_checkpoint(self._ckpt_path)  # safe point
            if self._faults.active:
                self._faults.crash("wave_crash", self._tracer,
                                   wave=wave_index)
            with self._lock:
                if len(self._discoveries) == len(properties):
                    return
                if (self._target_state_count is not None
                        and self._state_count >= self._target_state_count):
                    return
            if self._needs_growth():
                self._grow_table()

            # Adaptive width: the smallest ladder bucket covering the
            # fullest shard queue (results are bucket-independent; the
            # cross-B parity suite pins this).
            widest = 0
            for q in queues:
                rows = 0
                for blk in q:
                    rows += (blk.rows if hasattr(blk, "rows")
                             else len(blk[1]))
                    if rows >= self._B_max:
                        break
                widest = max(widest, rows)
            B = pick_bucket(self._buckets, widest)
            r_full = n * B * F   # receive rows per shard (worst case)
            K = self._pick_out_rows(B)

            batch_vecs = np.zeros((n * B, self._Wrow), np.uint32)
            batch_fps = np.zeros(n * B, np.uint64)
            batch_ebits = np.zeros(n * B, np.uint32)
            valid = np.zeros(n * B, bool)
            for i, q in enumerate(queues):
                parts, m = self._take_batch(q, B)
                row = i * B
                for vecs, fps, ebits in parts:
                    k = len(fps)
                    batch_vecs[row:row + k] = vecs
                    batch_fps[row:row + k] = fps
                    batch_ebits[row:row + k] = ebits
                    row += k
                valid[i * B:i * B + m] = True

            pkey = prof_s = t0 = None
            if self._prof.enabled:
                pkey = self._prof_key(
                    (B, self._capacity, K, self._owner_map.epoch))
                if self._prof.should_sample(pkey):
                    t0 = time.monotonic()
            with warnings.catch_warnings():
                # Batch-array donations that cannot alias an output are
                # still useful on HBM backends; the mismatch warning is
                # cosmetic.
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                (conds_out, succ_count, cand_count, terminal, new_count,
                 new_vecs, new_fps, new_parent, new_ebits, new_mask,
                 overflow, self._visited) = \
                    self._wave_fn(self._capacity, B, K)(
                        jnp.asarray(batch_vecs), jnp.asarray(batch_fps),
                        jnp.asarray(valid), jnp.asarray(batch_ebits),
                        self._visited)
            if t0 is not None:
                # Rest-point timing (obs/prof.py): the sharded loop is
                # synchronous, so the join costs only what the host
                # reads below would have paid anyway.
                jax.block_until_ready(self._visited)
                prof_s = time.monotonic() - t0

            new_count = np.asarray(new_count)
            r_out = K
            overflowed = bool(np.asarray(overflow).any())
            if overflowed:
                # Some shard's novel set outgrew the output rung: the
                # table insertions are complete and each shard's full
                # novelty mask is an output, so regather losslessly at
                # a rung that fits the worst shard (logged).
                r_out = pick_bucket(succ_bucket_ladder(r_full),
                                    int(new_count.max()))
                (new_vecs, new_fps, new_parent, new_ebits) = \
                    self._regather_fn(B, r_out)(
                        jnp.asarray(batch_vecs), jnp.asarray(batch_fps),
                        jnp.asarray(valid), jnp.asarray(batch_ebits),
                        new_mask)
                if self._tracer.enabled:
                    self._tracer.event("overflow_redispatch", bucket=B,
                                       out_rows=r_out,
                                       novel=int(new_count.max()))

            conds = self._eval_host_conds(
                conds_out, batch_vecs, np.flatnonzero(valid))

            if self._visitor is not None:
                for row in np.flatnonzero(valid):
                    self._visitor.visit(
                        model, self._reconstruct_path(int(batch_fps[row])))

            terminal = np.asarray(terminal)
            # Slice each shard's surviving rows on device; only those rows
            # cross to the host (each shard's output block is r_out rows).
            # Slice lengths round up to powers of two so the number of
            # shape-specialized dispatch entries stays O(log r_out).
            shard_blocks = []
            for i in range(n):
                k = int(new_count[i])
                base = i * r_out
                kb = min(max(1, 1 << (k - 1).bit_length()) if k else 0,
                         r_out)
                block_vecs = np.asarray(new_vecs[base:base + kb])[:k]
                self._check_error_lane(block_vecs)
                shard_blocks.append((
                    block_vecs,
                    np.asarray(new_fps[base:base + kb])[:k],
                    np.asarray(new_parent[base:base + kb])[:k],
                    np.asarray(new_ebits[base:base + kb])[:k]))

            if self._faults.active:
                shard_blocks = self._inject_exchange_faults(shard_blocks)
            # Owner-side exchange integrity check (always on — the cost
            # is one length compare and one O(novel) sentinel scan per
            # shard): a short or corrupted all-to-all delivery must die
            # HERE with a diagnosis, not as a poisoned queue entry
            # whose subtree silently vanishes. The wave's table
            # insertions are already applied, so the raise tears the
            # in-memory frontier — the supervisor resumes from the last
            # checkpoint.
            for i, (_, fps_i, _, _) in enumerate(shard_blocks):
                k = int(new_count[i])
                if len(fps_i) != k:
                    raise ExchangeIntegrityError(
                        f"all-to-all delivered {len(fps_i)} rows to "
                        f"shard {i} where its dedup reported {k} novel "
                        "states (short exchange); resume from the last "
                        "checkpoint")
                if k and (fps_i == np.uint64(SENTINEL)).any():
                    raise ExchangeIntegrityError(
                        f"all-to-all delivered a sentinel fingerprint "
                        f"inside shard {i}'s novel block (corrupt "
                        "exchange payload); resume from the last "
                        "checkpoint")

            # Tiered store: the device tables only know their RESIDENT
            # rows — re-generated spilled states look novel on device
            # (and were re-admitted to their owner's table slice). The
            # batched probe against the warm/cold partitions filters
            # them out of counts/queues/parents; the DEVICE novel
            # counts still feed shard occupancy (the rows ARE back in
            # the tables).
            dev_novel = [int(new_count[i]) for i in range(n)]
            if self._store.active and self._store.spilled_rows:
                filtered = []
                for vecs_i, fps_i, parents_i, ebits_i in shard_blocks:
                    if len(fps_i):
                        present = self._store.probe(
                            self._store_probe_fps(vecs_i, fps_i))
                        if present.any():
                            keep = ~present
                            vecs_i, fps_i, parents_i, ebits_i = (
                                vecs_i[keep], fps_i[keep],
                                parents_i[keep], ebits_i[keep])
                    filtered.append((vecs_i, fps_i, parents_i, ebits_i))
                shard_blocks = filtered

            with self._lock:
                succ_sum = int(np.asarray(succ_count).sum())
                cand_sum = int(np.asarray(cand_count).sum())
                self._state_count += succ_sum
                self._succ_hist.append((B, int(new_count.max())))
                self._resident += sum(dev_novel)
                # Stream each shard's new block into its queue + the
                # parent log FIRST so the wave event reports post-wave
                # occupancy (all array ops; bfs.rs:262 enqueue).
                novel_sum = 0
                for i, (vecs_i, fps_i, parents_i, ebits_i) \
                        in enumerate(shard_blocks):
                    self._shard_counts[i] += dev_novel[i]
                    k = len(fps_i)
                    if not k:
                        continue
                    self._unique_count += k
                    novel_sum += k
                    self._parent_log.append((fps_i, parents_i))
                    queues[i].append((vecs_i, fps_i, ebits_i))
                now = time.monotonic()
                self.wave_log.append((now, self._state_count))
                # Unified wave event (obs schema); load factor is the
                # FULLEST shard's slice — the quantity growth gates on.
                entry = {
                    "t": now, "states": self._state_count,
                    "unique": self._unique_count, "bucket": B,
                    "compiled": self._take_compile(), "waves": 1,
                    "inflight": 0, "out_rows": r_out,
                    # Valid frontier rows across all shard slots (the
                    # kernel-occupancy numerator; padded rows = n*B)
                    # and the successor-path implementation this
                    # dispatch ran.
                    "rows": int(valid.sum()),
                    "kernel_path": self._kernel_path(self._capacity, B),
                    "expand_impl": self._expand_impl(),
                    "successors": succ_sum, "candidates": cand_sum,
                    "novel": novel_sum, "capacity": self._capacity,
                    "load_factor": round(
                        max(self._shard_counts) / self._capacity, 4),
                    "overflow": overflowed,
                    # Bandwidth gauges (obs schema v2): capacity is per
                    # shard, so table bytes scale with the mesh; the
                    # unfused engine keeps its frontier host-side.
                    "bytes_per_state": 4 * self._Wrow,
                    "arena_bytes": None,
                    "table_bytes": n * self._capacity * 8,
                    # v10: wave-loop host-I/O stall since the last
                    # wave event (safe-point joins + inline writes).
                    "io_stall_s": self._take_io_stall(),
                    # v5 attribution: single-process sharded runs still
                    # record which ownership epoch the wave ran under
                    # (remaps bump it — resilience/membership.py).
                    "epoch": self._owner_map.epoch}
                if self._store.active:
                    # Tier occupancy gauges (obs schema v6).
                    entry.update(
                        self._store.gauges(),
                        tier_device_rows=self._resident,
                        tier_device_bytes=self._table_bytes(
                            self._capacity))
                if self._prof.enabled:
                    # v13 cost stamping + (on sampled dispatches) the
                    # profile_snapshot roofline event.
                    self._prof.wave(entry, pkey, prof_s, self._tracer,
                                    self._flight)
                self.dispatch_log.append(entry)
                if self._flight.armed:
                    self._flight.record(entry)
                for i, prop in enumerate(properties):
                    if prop.name in self._discoveries:
                        continue
                    if prop.expectation is Expectation.ALWAYS:
                        hits = valid & ~conds[i]
                    elif prop.expectation is Expectation.SOMETIMES:
                        hits = valid & conds[i]
                    else:
                        continue
                    rows = np.flatnonzero(hits)
                    if rows.size:
                        self._discoveries[prop.name] = int(batch_fps[rows[0]])
                ebits_after = batch_ebits.copy()
                for i in eventually_idx:
                    ebits_after &= ~np.where(
                        conds[i], np.uint32(1 << i), np.uint32(0))
                for row in np.flatnonzero(
                        terminal & valid & (ebits_after != 0)):
                    for i in eventually_idx:
                        prop = properties[i]
                        if (ebits_after[row] >> i) & 1 \
                                and prop.name not in self._discoveries:
                            self._discoveries[prop.name] = int(batch_fps[row])
            if self._store.active and novel_sum:
                # Host-tier frontier budget across every shard queue.
                self._store.balance_frontier(queues)
            if self._tracer.enabled:
                self._tracer.wave(entry)
            if self._wave_obs.enabled:
                self._wave_obs.wave(entry, self._tracer, self._flight)

"""64-bit fingerprints of encoded state vectors, on device and host.

Counterpart of the reference's stable keyed hashing (`src/lib.rs:302-344`):
state identity must be a pure function of the state, stable across runs and
across the host/device boundary. The device cannot run blake2b cheaply, so
the TPU engine defines its *own* fingerprint: two independent murmur3-style
32-bit hashes of the ``uint32`` state-encoding lanes (different seeds),
packed into one ``uint64``. The host re-implements the identical function
(`host_fp64`) so path reconstruction by replay (`path.rs:20-86`) and the
device visited-table agree on identity.

All-ones (``SENTINEL``) is reserved as the table's empty/padding marker and
zero is avoided to mirror the reference's nonzero ``Fingerprint``
(`lib.rs:303`); real fingerprints landing on either value are nudged.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["SENTINEL", "device_fp64", "host_fp64", "host_fp64_batch"]

SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_SEED_HI = 0x9747B28C
_SEED_LO = 0x2E1F36D9
_M32 = 0xFFFFFFFF


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mm3_fold(h, k):
    """One murmur3_32 round absorbing a uint32 word ``k`` into state ``h``."""
    k = k * _C1
    k = _rotl32(k, 15)
    k = k * _C2
    h = h ^ k
    h = _rotl32(h, 13)
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _mm3_final(h, nbytes):
    h = h ^ jnp.uint32(nbytes)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def device_fp64(vecs):
    """Fingerprints encoded states: ``uint32[..., W] -> uint64[...]``.

    Jittable; the fold over the W lanes is unrolled (W is static and
    small), keeping everything elementwise-fusible for XLA.
    """
    w = vecs.shape[-1]
    hi = jnp.full(vecs.shape[:-1], _SEED_HI, jnp.uint32)
    lo = jnp.full(vecs.shape[:-1], _SEED_LO, jnp.uint32)
    for i in range(w):
        lane = vecs[..., i]
        hi = _mm3_fold(hi, lane)
        lo = _mm3_fold(lo, lane)
    hi = _mm3_final(hi, 4 * w)
    lo = _mm3_final(lo, 4 * w)
    fp = (hi.astype(jnp.uint64) << 32) | lo.astype(jnp.uint64)
    # Reserve the sentinel and zero (nonzero convention, lib.rs:303).
    fp = jnp.where(fp == jnp.uint64(SENTINEL), fp - 1, fp)
    return jnp.where(fp == 0, jnp.uint64(1), fp)


def _host_mm3(words: np.ndarray, seed: int) -> int:
    h = seed
    for k in words:
        k = (int(k) * _C1) & _M32
        k = ((k << 15) | (k >> 17)) & _M32
        k = (k * _C2) & _M32
        h ^= k
        h = ((h << 13) | (h >> 19)) & _M32
        h = (h * 5 + 0xE6546B64) & _M32
    h ^= 4 * len(words)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def host_fp64(vec: np.ndarray) -> int:
    """The device fingerprint of one encoded state, computed on host."""
    fp = (_host_mm3(vec, _SEED_HI) << 32) | _host_mm3(vec, _SEED_LO)
    if fp == int(SENTINEL):
        fp -= 1
    return fp if fp != 0 else 1


def host_fp64_batch(vecs: np.ndarray) -> np.ndarray:
    """Vectorized ``host_fp64`` over ``uint32[N, W]`` (wrapping uint32 ops)."""
    with np.errstate(over="ignore"):
        n, w = vecs.shape
        hi = np.full(n, _SEED_HI, np.uint32)
        lo = np.full(n, _SEED_LO, np.uint32)
        c1 = np.uint32(_C1)
        c2 = np.uint32(_C2)
        for i in range(w):
            for name, h in (("hi", hi), ("lo", lo)):
                k = vecs[:, i] * c1
                k = (k << np.uint32(15)) | (k >> np.uint32(17))
                k = k * c2
                h ^= k
                h = ((h << np.uint32(13)) | (h >> np.uint32(19)))
                h = h * np.uint32(5) + np.uint32(0xE6546B64)
                if name == "hi":
                    hi = h
                else:
                    lo = h
        out = np.empty(n, np.uint64)
        for name, h in (("hi", hi), ("lo", lo)):
            h = h ^ np.uint32(4 * w)
            h ^= h >> np.uint32(16)
            h = h * np.uint32(0x85EBCA6B)
            h ^= h >> np.uint32(13)
            h = h * np.uint32(0xC2B2AE35)
            h ^= h >> np.uint32(16)
            if name == "hi":
                out = h.astype(np.uint64) << np.uint64(32)
            else:
                out |= h.astype(np.uint64)
        out[out == SENTINEL] -= np.uint64(1)
        out[out == 0] = np.uint64(1)
        return out

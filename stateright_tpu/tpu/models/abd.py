"""Device form of the ABD quorum register (`linearizable-register.rs`).

Attiya–Bar-Noy–Dolev: reads and writes both run a query phase (collect
(seq, value) from a quorum) then a record phase (install the chosen pair
at a quorum). Sequencers are ``(logical_clock, server_id)`` — encoded as
``clock * S + id`` so integer order == the host's lexicographic tuple
order, making the quorum max a plain integer max. Clock is bounded by the
number of writes (<= C). Built on :class:`RegisterWorkloadDevice`; parity
gate: 544 unique states @ 2 clients / 2 servers
(`linearizable-register.rs:256`).

Per-server lanes: ``seq``, ``val``, and the in-progress phase —
``ph_kind`` (0 none / 1 query / 2 record), ``ph_req`` (request field),
``ph_write`` (0 = read else value idx), ``ph_read`` (0 = write else
1 + value idx), ``ph_acks`` (server bitmask), and one response lane per
server (0 = absent else ``1 + seq_idx * (C+1) + val_idx``). Lanes unused
by the current phase are zeroed so the encoding stays injective.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...actor import Id
from ..actor_device import EMPTY_ENV, compact_envs
from ..register_workload import GET, GETOK, PUT, PUTOK, \
    RegisterWorkloadDevice

__all__ = ["AbdDevice"]

QUERY, ACKQUERY, RECORD, ACKRECORD = 4, 5, 6, 7


class AbdDevice(RegisterWorkloadDevice):
    INTERNAL_KINDS = ("Query", "AckQuery", "Record", "AckRecord")

    def __init__(self, client_count: int, server_count: int, host_cfg,
                 **kwargs):
        from ..device_model import DeviceFormUnavailable

        # ABD's internal messages carry BARE request ids (Query(4), ...)
        # with no requester in the message, so the envelope req field
        # (op-1)<<2|k can only be encoded when every product op*(S+k)
        # is unique over op in {1,2}, k < C. Paxos/single-copy are
        # immune (their encodings always have requester context); ABD
        # configs with colliding ids — e.g. 3 clients on 2 servers,
        # where 1*(2+2) == 2*(2+0) — fall back to the host engines.
        ids: dict = {}
        for k in range(client_count):
            for op in (1, 2):
                ids.setdefault(op * (server_count + k), []).append(k)
        if any(len(v) > 1 for v in ids.values()):
            raise DeviceFormUnavailable(
                f"ABD request ids collide at {client_count} clients / "
                f"{server_count} servers (op * actor products are not "
                "unique), and internal messages carry no requester to "
                "disambiguate; this configuration runs on the host "
                "engines")
        self.SERVER_LANES = (
            "seq", "val", "ph_kind", "ph_req", "ph_write", "ph_read",
            "ph_acks") + tuple(f"ph_resp{j}" for j in range(server_count))
        self.max_out = max(server_count - 1, 1)
        super().__init__(client_count, server_count, host_cfg, **kwargs)
        self._host = host_cfg.host_module if hasattr(
            host_cfg, "host_module") else None

    def native_form(self):
        """Compiled C++ counterpart (``native/host_bfs.cc`` model 4):
        same lanes, envelopes, and fingerprints as this device form."""
        return (4, [self.C, self.S])

    # -- Packed-row layout: sequencer/response universes as bit widths ----

    def _seq_max(self) -> int:
        # seq = clock * S + id, clock <= C (one Put per client), id < S.
        return self.C * self.S + self.S - 1

    def server_lane_bits(self) -> tuple:
        def bits(n):
            return max(1, int(n).bit_length())

        resp_max = 1 + self._seq_max() * (self.C + 1) + self.C
        return ((bits(self._seq_max()),     # seq
                 bits(self.C),              # val
                 2,                         # ph_kind 0..2
                 3,                         # ph_req (3-bit req field)
                 bits(self.C),              # ph_write 0..C
                 bits(self.C + 1),          # ph_read 0..1+C
                 self.S)                    # ph_acks bitmask
                + (bits(resp_max),) * self.S)

    def extra_bits(self) -> int:
        # AckQuery/Record carry a bare sequencer index in extra.
        return max(1, self._seq_max().bit_length())

    # -- Sequencer / response encodings -----------------------------------

    def _seq_idx(self, seq) -> int:
        clock, sid = seq
        return clock * self.S + int(sid)

    def _seq_tuple(self, idx: int):
        return (idx // self.S, Id(idx % self.S))

    def _resp_enc(self, seq, value) -> int:
        return 1 + self._seq_idx(seq) * (self.C + 1) + self.value_idx(value)

    def _resp_dec(self, code: int):
        code -= 1
        return (self._seq_tuple(code // (self.C + 1)),
                self.value_of(code % (self.C + 1)))

    # -- Internal message codec -------------------------------------------

    def encode_internal(self, inner) -> tuple:
        name = type(inner).__name__
        if name == "Query":
            return "Query", self._req_field(inner.request_id), 0, 0
        if name == "AckQuery":
            return ("AckQuery", self._req_field(inner.request_id),
                    self.value_idx(inner.value), self._seq_idx(inner.seq))
        if name == "Record":
            return ("Record", self._req_field(inner.request_id),
                    self.value_idx(inner.value), self._seq_idx(inner.seq))
        if name == "AckRecord":
            return "AckRecord", self._req_field(inner.request_id), 0, 0
        raise ValueError(f"unsupported internal message {inner!r}")

    def decode_internal(self, kind_name: str, req: int, value: int,
                        extra: int):
        h = self._host_module()
        req_id = self._req_id(req)
        if kind_name == "Query":
            return h.Query(req_id)
        if kind_name == "AckQuery":
            return h.AckQuery(req_id, self._seq_tuple(extra),
                              self.value_of(value))
        if kind_name == "Record":
            return h.Record(req_id, self._seq_tuple(extra),
                            self.value_of(value))
        return h.AckRecord(req_id)

    def _host_module(self):
        # The explicit override wins; otherwise the module that defined
        # the host cfg — NOT importlib by name: when the example runs as
        # a script its classes live in ``__main__``, and a fresh import
        # would create a second module whose classes fail
        # ``type(x) is h.Phase1`` identity checks.
        import sys

        if self._host is not None:
            return self._host
        return sys.modules[type(self.host_cfg).__module__]

    # -- Client symmetry: no rewrite hooks needed. A nontrivial group
    # requires two clients in one residue class mod S, which forces
    # S < C and therefore clients 0 and S to coexist — whose request
    # ids collide (client 0's op 2 and client S's op 1 are both 2S), so
    # the constructor guard above already rejects every such config.
    # Within the encodable configs the group is always trivial:
    # ``representative`` is the identity and check-sym works hook-free.

    # -- Server delivery (`linearizable-register.rs:68-186`) -------------

    def server_deliver(self, lanes, f):
        s, c = self.S, self.C
        u = jnp.uint32
        seq = self.lane(lanes, "seq")
        val = self.lane(lanes, "val")
        ph_kind = self.lane(lanes, "ph_kind")
        ph_req = self.lane(lanes, "ph_req")
        ph_write = self.lane(lanes, "ph_write")
        ph_read = self.lane(lanes, "ph_read")
        ph_acks = self.lane(lanes, "ph_acks")
        resp = jnp.stack([self.lane(lanes, f"ph_resp{j}")
                          for j in range(s)])
        no_env = u(EMPTY_ENV)
        maj = s // 2 + 1

        # --- Put/Get with no phase in flight: start the query phase.
        start_case = ((f.kind == PUT) | (f.kind == GET)) & (ph_kind == 0)
        self_resp = 1 + seq * (c + 1) + val
        start_lanes = lanes
        start_lanes = self.with_lane(start_lanes, "ph_kind", 1)
        start_lanes = self.with_lane(start_lanes, "ph_req", f.req)
        start_lanes = self.with_lane(
            start_lanes, "ph_write",
            jnp.where(f.kind == PUT, f.value, u(0)))
        start_lanes = self.with_lane(start_lanes, "ph_read", 0)
        start_lanes = self.with_lane(start_lanes, "ph_acks", 0)
        for j in range(s):
            start_lanes = self.with_lane(
                start_lanes, f"ph_resp{j}",
                jnp.where(f.dst == j, self_resp, u(0)))
        query_env = lambda p: self.build_env(  # noqa: E731
            dst=p, src=f.dst, kind=QUERY, req=f.req)

        # --- Query: reply with our (seq, val); no state change.
        query_case = f.kind == QUERY
        ackquery_out = self.build_env(dst=f.src, src=f.dst, kind=ACKQUERY,
                                      req=f.req, value=val, extra=seq)

        # --- AckQuery during our query phase for this request.
        ackq_case = (f.kind == ACKQUERY) & (ph_kind == 1) \
            & (ph_req == f.req)
        m_resp = 1 + f.extra * (c + 1) + f.value
        resp2 = jnp.stack([
            jnp.where(f.src == j, m_resp, resp[j]) for j in range(s)])
        quorum_q = jnp.sum((resp2 != 0).astype(u)) == maj
        best = jnp.max(resp2) - 1  # distinct seqs: max enc == max seq
        best_seq = best // (c + 1)
        best_val = best % (c + 1)
        is_write = ph_write != 0
        new_seq = jnp.where(is_write, (best_seq // s + 1) * s + f.dst,
                            best_seq)
        new_val = jnp.where(is_write, ph_write, best_val)
        adopt = new_seq > seq  # self-Record effect
        ackq_lanes = lanes
        ackq_lanes = self.with_lane(
            ackq_lanes, "seq",
            jnp.where(quorum_q & adopt, new_seq, seq))
        ackq_lanes = self.with_lane(
            ackq_lanes, "val",
            jnp.where(quorum_q & adopt, new_val, val))
        ackq_lanes = self.with_lane(
            ackq_lanes, "ph_kind", jnp.where(quorum_q, u(2), u(1)))
        ackq_lanes = self.with_lane(
            ackq_lanes, "ph_write", jnp.where(quorum_q, u(0), ph_write))
        ackq_lanes = self.with_lane(
            ackq_lanes, "ph_read",
            jnp.where(quorum_q & ~is_write, 1 + best_val, u(0)))
        ackq_lanes = self.with_lane(
            ackq_lanes, "ph_acks",
            jnp.where(quorum_q, u(1) << f.dst, u(0)))
        for j in range(s):
            ackq_lanes = self.with_lane(
                ackq_lanes, f"ph_resp{j}",
                jnp.where(quorum_q, u(0), resp2[j]))
        record_env = lambda p: self.build_env(  # noqa: E731
            dst=p, src=f.dst, kind=RECORD, req=ph_req, value=new_val,
            extra=new_seq)

        # --- Record: ack; adopt the pair if newer.
        record_case = f.kind == RECORD
        rec_adopt = f.extra > seq
        record_lanes = lanes
        record_lanes = self.with_lane(
            record_lanes, "seq", jnp.where(rec_adopt, f.extra, seq))
        record_lanes = self.with_lane(
            record_lanes, "val", jnp.where(rec_adopt, f.value, val))
        ackrecord_out = self.build_env(dst=f.src, src=f.dst,
                                       kind=ACKRECORD, req=f.req)

        # --- AckRecord during our record phase, new acker.
        ackr_case = (f.kind == ACKRECORD) & (ph_kind == 2) \
            & (ph_req == f.req) & (((ph_acks >> f.src) & 1) == 0)
        acks2 = ph_acks | (u(1) << f.src)
        quorum_r = sum(((acks2 >> j) & 1) for j in range(s)) == maj
        ackr_lanes = lanes
        ackr_lanes = self.with_lane(
            ackr_lanes, "ph_kind", jnp.where(quorum_r, u(0), u(2)))
        ackr_lanes = self.with_lane(
            ackr_lanes, "ph_req", jnp.where(quorum_r, u(0), ph_req))
        ackr_lanes = self.with_lane(
            ackr_lanes, "ph_read", jnp.where(quorum_r, u(0), ph_read))
        ackr_lanes = self.with_lane(
            ackr_lanes, "ph_acks", jnp.where(quorum_r, u(0), acks2))
        requester = s + (ph_req & 3)
        reply_out = jnp.where(
            ph_read != 0,
            self.build_env(dst=requester, src=f.dst, kind=GETOK,
                           req=ph_req, value=ph_read - 1),
            self.build_env(dst=requester, src=f.dst, kind=PUTOK,
                           req=ph_req))

        # --- Select.
        handled = (start_case | query_case | ackq_case | record_case
                   | ackr_case)
        new_lanes = lanes
        new_lanes = jnp.where(start_case, start_lanes, new_lanes)
        new_lanes = jnp.where(ackq_case, ackq_lanes, new_lanes)
        new_lanes = jnp.where(record_case, record_lanes, new_lanes)
        new_lanes = jnp.where(ackr_case, ackr_lanes, new_lanes)

        # Broadcast slots: Query on start, Record on query quorum — to
        # the S-1 peers (self excluded), compacted into max_out slots.
        bcast = jnp.stack([
            jnp.where(f.dst == p, no_env,
                      jnp.where(start_case, query_env(p),
                                jnp.where(ackq_case & quorum_q,
                                          record_env(p), no_env)))
            for p in range(s)])
        outs = compact_envs(bcast, self.max_out)
        # Reply slot (never used together with a broadcast).
        reply = jnp.where(query_case, ackquery_out,
                          jnp.where(record_case, ackrecord_out,
                                    jnp.where(ackr_case & quorum_r,
                                              reply_out, no_env)))
        outs = outs.at[0].set(jnp.where(reply != no_env, reply, outs[0]))
        return new_lanes, handled, outs

    # -- Host codec -------------------------------------------------------

    def encode_server(self, ss, vec: np.ndarray, base: int) -> None:
        h = self._host_module()
        li = self._lane_idx
        vec[base + li["seq"]] = self._seq_idx(ss.seq)
        vec[base + li["val"]] = self.value_idx(ss.val)
        ph = ss.phase
        if ph is None:
            return
        vec[base + li["ph_req"]] = self._req_field(ph.request_id)
        assert int(ph.requester_id) == self.S + (
            self._req_field(ph.request_id) & 3), "requester outside universe"
        if type(ph) is h.Phase1:
            vec[base + li["ph_kind"]] = 1
            vec[base + li["ph_write"]] = (
                0 if ph.write is None else self.value_idx(ph.write))
            for sid, (seq, value) in ph.responses:
                vec[base + li[f"ph_resp{int(sid)}"]] = \
                    self._resp_enc(seq, value)
        else:
            vec[base + li["ph_kind"]] = 2
            vec[base + li["ph_read"]] = (
                0 if ph.read is None else 1 + self.value_idx(ph.read))
            vec[base + li["ph_acks"]] = sum(1 << int(a) for a in ph.acks)

    def decode_server(self, vec: np.ndarray, base: int, server_index: int):
        h = self._host_module()
        li = self._lane_idx
        seq = self._seq_tuple(int(vec[base + li["seq"]]))
        val = self.value_of(int(vec[base + li["val"]]))
        kind = int(vec[base + li["ph_kind"]])
        if kind == 0:
            phase = None
        else:
            req_id = self._req_id(int(vec[base + li["ph_req"]]))
            requester = Id(self.S + (int(vec[base + li["ph_req"]]) & 3))
            if kind == 1:
                write_idx = int(vec[base + li["ph_write"]])
                responses = tuple(sorted(
                    (Id(j), self._resp_dec(int(vec[base + li[f"ph_resp{j}"]])))
                    for j in range(self.S)
                    if vec[base + li[f"ph_resp{j}"]]))
                phase = h.Phase1(
                    request_id=req_id, requester_id=requester,
                    write=None if write_idx == 0
                    else self.value_of(write_idx),
                    responses=responses)
            else:
                read_code = int(vec[base + li["ph_read"]])
                acks = tuple(Id(j) for j in range(self.S)
                             if (int(vec[base + li["ph_acks"]]) >> j) & 1)
                phase = h.Phase2(
                    request_id=req_id, requester_id=requester,
                    read=None if read_code == 0
                    else self.value_of(read_code - 1),
                    acks=acks)
        return h.AbdState(seq=seq, val=val, phase=phase)

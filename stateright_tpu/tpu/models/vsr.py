"""Device encoding of the viewstamped-replication model
(``stateright_tpu/actor/viewstamped.py``) — the round-14 corpus
addition's accelerator form, validated against the host semantics by
the service's differential fuzz gate (``stateright_tpu/service/diff.py``).

Lanes (``W = 8*n + 1 + net_slots + 1``):

- ``[8*i .. 8*i+8)`` — replica ``i``'s eight state fields, in the
  exact :class:`ReplicaState` field order: view, status, op_val,
  committed, oks, svc, dvc, dvc_best (the host state is deliberately
  flat integers so this is a direct transcription);
- ``[8*n]`` — the timer bitmask (constant all-ones: VR timers re-arm
  on every timeout, the ``max_view`` boundary is what bounds the run);
- ``[8*n+1 ..]`` — network slots + overflow flag (``ActorDeviceModel``).

Envelope code (src/dst get 2 bits — at most 4 replicas; view and the
operation value get 4 bits each — ``max_view <= 14``)::

    ((((view << 4) | val) << 3 | kind) << 2 | src) << 2 | dst

with kinds Prepare=0, PrepareOk=1, Commit=2, StartViewChange=3,
DoViewChange=4, StartView=5.

Every handler mirrors its host twin branch for branch, including the
*no-op* conditions (a duplicate ack, a stale view) — the ``handled``
flag is what keeps the checker action sets identical, and the diff-fuzz
walk compares them state by state.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...actor.core import majority
from ..actor_device import EMPTY_ENV, ActorDeviceModel

__all__ = ["VsrDevice"]

_PREPARE, _PREPARE_OK, _COMMIT = 0, 1, 2
_START_VC, _DO_VC, _START_VIEW = 3, 4, 5

#: ReplicaState field order — lane offsets within a replica's 8 lanes.
_F_VIEW, _F_STATUS, _F_OP, _F_COMMITTED = 0, 1, 2, 3
_F_OKS, _F_SVC, _F_DVC, _F_BEST = 4, 5, 6, 7


class VsrDevice(ActorDeviceModel):
    duplicating = True
    lossy = False

    def __init__(self, cfg, net_slots: int | None = None):
        from ...actor.viewstamped import VsrCfg

        if not isinstance(cfg, VsrCfg):
            raise TypeError(f"expected VsrCfg, got {type(cfg).__name__}")
        if cfg.n > 4:
            raise ValueError("envelope codec supports at most 4 replicas")
        if cfg.max_view > 14:
            raise ValueError("envelope codec supports max_view <= 14")
        self.cfg = cfg
        n = cfg.n
        self.n = n
        self.maj = majority(n)
        # Measured peaks: 9 in-flight at n=2/max_view=1, 20 at n=3 —
        # 8 per replica leaves slack; overflow is a hard error anyway.
        self.net_slots = 8 * n if net_slots is None else net_slots
        self.n_timers = n
        self.timer_offset = 8 * n
        self.net_offset = 8 * n + 1
        self.state_width = self.net_offset + self.net_slots + 1
        self.error_lane = self.net_offset + self.net_slots
        self.max_out = n
        self.lossy = cfg.lossy
        self.duplicating = cfg.duplicating

    # -- Envelope codec ---------------------------------------------------

    def env_encode(self, envelope) -> int:
        from ...actor import viewstamped as vs

        msg = envelope.msg
        kind = {vs.Prepare: _PREPARE, vs.PrepareOk: _PREPARE_OK,
                vs.Commit: _COMMIT, vs.StartViewChange: _START_VC,
                vs.DoViewChange: _DO_VC, vs.StartView: _START_VIEW}[
                    type(msg)]
        val = getattr(msg, "val", getattr(msg, "op_val", 0)) or 0
        code = (msg.view << 4) | val
        return (((code << 3) | kind) << 2 | int(envelope.src)) << 2 \
            | int(envelope.dst)

    def env_decode(self, code: int):
        from ...actor import viewstamped as vs
        from ...actor.core import Id
        from ...actor.model_state import Envelope

        dst = code & 3
        src = (code >> 2) & 3
        kind = (code >> 4) & 7
        val = (code >> 7) & 15
        view = (code >> 11) & 15
        msg = {_PREPARE: lambda: vs.Prepare(view, val),
               _PREPARE_OK: lambda: vs.PrepareOk(view),
               _COMMIT: lambda: vs.Commit(view, val),
               _START_VC: lambda: vs.StartViewChange(view),
               _DO_VC: lambda: vs.DoViewChange(view, val),
               _START_VIEW: lambda: vs.StartView(view, val)}[kind]()
        return Envelope(Id(src), Id(dst), msg)

    # -- State codec ------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        n = self.n
        vec = np.zeros(self.state_width, np.uint32)
        for i, s in enumerate(state.actor_states):
            vec[8 * i:8 * i + 8] = (s.view, s.status, s.op_val,
                                    s.committed, s.oks, s.svc, s.dvc,
                                    s.dvc_best)
        vec[self.timer_offset] = sum(
            1 << i for i, armed in enumerate(state.is_timer_set)
            if armed)
        vec[self.net_offset:] = self.encode_network(state.network)
        return vec

    def decode(self, vec: np.ndarray):
        from ...actor.model_state import ActorModelState, Network
        from ...actor.viewstamped import ReplicaState

        n = self.n
        states = [ReplicaState(*(int(v) for v in vec[8 * i:8 * i + 8]))
                  for i in range(n)]
        timers = [bool((int(vec[self.timer_offset]) >> i) & 1)
                  for i in range(n)]
        return ActorModelState(
            actor_states=states,
            network=Network(self.decode_network(vec[self.net_offset:])),
            is_timer_set=timers,
            history=None,
        )

    # -- jax helpers ------------------------------------------------------

    def _enc(self, view, val, kind: int, src, dst):
        code = (view.astype(jnp.uint32) << 4) | val.astype(jnp.uint32)
        return ((((code << 3) | jnp.uint32(kind)) << 2
                 | src.astype(jnp.uint32)) << 2) | dst.astype(jnp.uint32)

    def _popcount(self, mask):
        total = jnp.zeros((), jnp.uint32)
        for b in range(self.n):
            total = total + ((mask >> b) & 1)
        return total

    @staticmethod
    def _sel(cond, then, other):
        return jnp.where(cond, then, other).astype(jnp.uint32)

    # -- Delivery ---------------------------------------------------------

    def deliver(self, body, env):
        n, maj = self.n, jnp.uint32(self.maj)
        dst = env & 3
        src = (env >> 2) & 3
        kind = (env >> 4) & 7
        val = (env >> 7) & 15
        view = (env >> 11) & 15
        sel = self._sel

        rows = body[:8 * n].reshape(n, 8)
        row = rows[dst]  # dynamic gather of the receiver's 8 lanes
        s_view, s_status, s_op, s_com, s_oks, s_svc, s_dvc, s_best = (
            row[k] for k in range(8))
        i_bit = (jnp.uint32(1) << dst)
        j_bit = (jnp.uint32(1) << src)
        is_primary = (view % n) == dst

        # -- Prepare (view, x): accept + ack, or catch up -----------------
        p_catch = kind == _PREPARE
        p_catch = p_catch & (view > s_view)
        p_same = ((kind == _PREPARE) & (view == s_view)
                  & (s_status == 0) & ~is_primary & (s_op == 0))
        prep_handled = p_catch | p_same

        # -- PrepareOk (view): quorum counting at the primary -------------
        ok_valid = ((kind == _PREPARE_OK) & (view == s_view)
                    & (s_status == 0) & ((s_view % n) == dst)
                    & (s_op != 0) & (s_com == 0))
        oks2 = s_oks | j_bit | i_bit
        ok_changed = ok_valid & (oks2 != s_oks)
        ok_quorum = ok_changed & (self._popcount(oks2) >= maj)

        # -- Commit (view, x): adopt the committed fact -------------------
        c_fresh = (kind == _COMMIT) & (s_com == 0)
        c_newer = c_fresh & (view > s_view)

        # -- StartViewChange (view): gossip + quorum ----------------------
        svc_enter = (kind == _START_VC) & (view > s_view)
        svc_same = ((kind == _START_VC) & (view == s_view)
                    & (s_status == 1))
        svc_mask_enter = i_bit | j_bit
        svc_mask_same = s_svc | j_bit
        svc_changed = svc_same & (svc_mask_same != s_svc)
        svc_handled = svc_enter | svc_changed
        svc_send_dvc = (
            (svc_enter & (self._popcount(svc_mask_enter) >= maj))
            | (svc_changed & (self._popcount(svc_mask_same) >= maj)
               & (self._popcount(s_svc) < maj)))

        # -- DoViewChange (view, o): the new primary collects -------------
        dvc_newer = (kind == _DO_VC) & is_primary & (view > s_view)
        dvc_same = ((kind == _DO_VC) & is_primary & (view == s_view)
                    & (s_status == 1))
        dvc_mask_newer = i_bit | j_bit
        best_newer = jnp.maximum(s_op, val)
        dvc_mask_same = s_dvc | j_bit | i_bit
        best_same = jnp.maximum(jnp.maximum(s_best, s_op), val)
        dvc_changed = dvc_same & ((dvc_mask_same != s_dvc)
                                  | (best_same != s_best))
        dvc_handled = dvc_newer | dvc_changed
        dvc_complete = (
            (dvc_newer & (self._popcount(dvc_mask_newer) >= maj))
            | (dvc_changed & (self._popcount(dvc_mask_same) >= maj)
               & (self._popcount(s_dvc) < maj)))
        dvc_mask = sel(dvc_newer, dvc_mask_newer, dvc_mask_same)
        dvc_best = sel(dvc_newer, best_newer, best_same)

        # -- StartView (view, o): adopt the announced op ------------------
        sv_adopt = ((kind == _START_VIEW)
                    & ((view > s_view)
                       | ((view == s_view) & (s_status == 1))))
        sv_ack = sv_adopt & (val != 0) & (s_com == 0)

        handled = (prep_handled | ok_changed | c_fresh | svc_handled
                   | dvc_handled | sv_adopt)

        # -- New replica row (one where-cascade per field; branches are
        # mutually exclusive because `kind` selects them) -----------------
        zero = jnp.uint32(0)
        new_view = s_view
        new_view = sel(p_catch | c_newer | svc_enter | dvc_newer
                       | sv_adopt, view, new_view)
        new_status = s_status
        new_status = sel(p_catch | c_newer | sv_adopt, zero, new_status)
        new_status = sel(svc_enter, jnp.uint32(1), new_status)
        new_status = sel(dvc_newer, jnp.uint32(1), new_status)
        new_status = sel(dvc_complete, zero, new_status)
        new_op = s_op
        new_op = sel(prep_handled | c_newer, val, new_op)
        new_op = sel(c_fresh & ~c_newer,
                     sel(s_op == 0, val, s_op), new_op)
        new_op = sel(sv_adopt, val, new_op)
        new_op = sel(dvc_complete, dvc_best, new_op)
        new_com = s_com
        new_com = sel(c_fresh, val, new_com)
        new_com = sel(ok_quorum, s_op, new_com)
        new_oks = s_oks
        new_oks = sel(p_catch | c_newer | svc_enter | dvc_newer
                      | sv_adopt, zero, new_oks)
        new_oks = sel(ok_changed, oks2, new_oks)
        new_oks = sel(dvc_complete,
                      sel(dvc_best != 0, i_bit, zero), new_oks)
        new_svc = s_svc
        new_svc = sel(p_catch | c_newer | dvc_newer | sv_adopt, zero,
                      new_svc)
        new_svc = sel(svc_enter, svc_mask_enter, new_svc)
        new_svc = sel(svc_changed, svc_mask_same, new_svc)
        new_svc = sel(dvc_complete, zero, new_svc)
        new_dvc = s_dvc
        new_dvc = sel(p_catch | c_newer | svc_enter | sv_adopt, zero,
                      new_dvc)
        new_dvc = sel(dvc_handled, dvc_mask, new_dvc)
        new_dvc = sel(dvc_complete, zero, new_dvc)
        new_best = s_best
        new_best = sel(p_catch | c_newer | svc_enter | sv_adopt, zero,
                       new_best)
        new_best = sel(dvc_handled, dvc_best, new_best)
        new_best = sel(dvc_complete, zero, new_best)

        new_row = jnp.stack([new_view, new_status, new_op, new_com,
                             new_oks, new_svc, new_dvc, new_best])
        new_rows = rows.at[dst].set(
            jnp.where(handled, new_row, row).astype(jnp.uint32))
        new_body = jnp.concatenate([new_rows.reshape(-1),
                                    body[8 * n:]])

        # -- Outgoing envelopes -------------------------------------------
        # Slots [0, n-1): broadcast to every other replica; slot n-1:
        # the unicast (PrepareOk back to src, or DoViewChange to the
        # new primary). The broadcasting branches (Commit on quorum,
        # StartViewChange gossip, StartView on completion) are mutually
        # exclusive by kind.
        empty = jnp.uint32(EMPTY_ENV)
        outs = []
        bc_commit = ok_quorum
        bc_svc = svc_enter
        bc_sv = dvc_complete
        for k in range(n - 1):
            other = jnp.where(jnp.uint32(k) < dst, jnp.uint32(k),
                              jnp.uint32(k + 1))
            e = empty
            e = sel(bc_commit,
                    self._enc(s_view, s_op, _COMMIT, dst, other), e)
            e = sel(bc_svc,
                    self._enc(view, zero, _START_VC, dst, other), e)
            e = sel(bc_sv,
                    self._enc(view, dvc_best, _START_VIEW, dst, other),
                    e)
            outs.append(e)
        uni = empty
        uni = sel(prep_handled,
                  self._enc(view, zero, _PREPARE_OK, dst, src), uni)
        uni = sel(sv_ack,
                  self._enc(view, zero, _PREPARE_OK, dst, src), uni)
        uni = sel(svc_send_dvc,
                  self._enc(view, s_op, _DO_VC, dst, view % n), uni)
        outs.append(uni)
        return new_body, handled, jnp.stack(outs)

    # -- Timeout ----------------------------------------------------------

    def timeout(self, body, actor: int):
        n = self.n
        sel = self._sel
        rows = body[:8 * n].reshape(n, 8)
        row = rows[actor]
        s_view, s_status, s_op = row[_F_VIEW], row[_F_STATUS], row[_F_OP]
        i_bit = jnp.uint32(1 << actor)
        is_primary = (s_view % n) == actor

        propose = (s_status == 0) & is_primary & (s_op == 0)
        suspect = (s_status == 0) & ~is_primary
        val = s_view + 1
        nv = s_view + 1

        zero = jnp.uint32(0)
        new_row = jnp.stack([
            sel(suspect, nv, s_view),
            sel(suspect, jnp.uint32(1), s_status),
            sel(propose, val, s_op),
            row[_F_COMMITTED],
            sel(propose, i_bit, sel(suspect, zero, row[_F_OKS])),
            sel(suspect, i_bit, row[_F_SVC]),
            sel(suspect, zero, row[_F_DVC]),
            sel(suspect, zero, row[_F_BEST]),
        ]).astype(jnp.uint32)
        new_rows = rows.at[actor].set(new_row)
        new_body = jnp.concatenate([new_rows.reshape(-1),
                                    body[8 * n:]])

        empty = jnp.uint32(EMPTY_ENV)
        dst_i = jnp.uint32(actor)
        outs = []
        for k in range(n - 1):
            other = jnp.uint32(k if k < actor else k + 1)
            e = empty
            e = sel(propose,
                    self._enc(s_view, val, _PREPARE, dst_i, other), e)
            e = sel(suspect,
                    self._enc(nv, jnp.zeros((), jnp.uint32), _START_VC,
                              dst_i, other), e)
            outs.append(e)
        # slot n-1 unused by timeouts (keeps max_out uniform)
        outs.append(empty)
        # The host handler ALWAYS yields a successor (the timer re-arms,
        # so even the quiescent branch produces the identical state as a
        # self-loop) — handled mirrors that.
        handled = jnp.ones((), bool)
        return new_body, handled, jnp.stack(outs)

    # -- Boundary + properties --------------------------------------------

    def boundary(self, vec):
        n = self.n
        within = jnp.ones((), bool)
        for i in range(n):
            within = within & (vec[8 * i + _F_VIEW] <= self.cfg.max_view)
        return within

    def device_properties(self):
        n = self.n

        def agreement(v):
            holds = jnp.ones((), bool)
            for a in range(n):
                for b in range(a + 1, n):
                    ca = v[8 * a + _F_COMMITTED]
                    cb = v[8 * b + _F_COMMITTED]
                    holds = holds & ((ca == 0) | (cb == 0) | (ca == cb))
            return holds

        def can_commit(v):
            hit = jnp.zeros((), bool)
            for i in range(n):
                hit = hit | (v[8 * i + _F_COMMITTED] != 0)
            return hit

        def vc_completes(v):
            hit = jnp.zeros((), bool)
            for i in range(n):
                hit = hit | ((v[8 * i + _F_VIEW] > 0)
                             & (v[8 * i + _F_STATUS] == 0))
            return hit

        def commit_survives(v):
            hit = jnp.zeros((), bool)
            for i in range(n):
                hit = hit | ((v[8 * i + _F_COMMITTED] != 0)
                             & (v[8 * i + _F_VIEW] > 0))
            return hit

        return {
            "agreement": agreement,
            "can commit": can_commit,
            "view change completes": vc_completes,
            "commit survives view change": commit_survives,
        }

"""Device encoding of the lock-fixed counter (`examples/increment_lock.rs`).

State lanes (``W = 2 + 2*T`` uint32): ``[0]`` = shared counter, ``[1]`` =
lock held, then per-thread ``(t, pc)`` pairs (pc: 0 = wants lock,
1 = about to read, 2 = about to write, 3 = holds lock post-write,
4 = done). One action per thread, in thread order, selected by pc —
matching the host enumeration (`increment_lock.rs:60-75`).

The representative sorts threads by their full ``(t, pc)`` pair (an
exact canonical form, like the increment model's).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..device_model import DeviceModel

__all__ = ["IncrementLockDevice"]


class IncrementLockDevice(DeviceModel):
    def __init__(self, thread_count: int, host_module):
        self.thread_count = thread_count
        self.state_width = 2 + 2 * thread_count
        self.max_fanout = thread_count
        self._host = host_module

    def native_form(self):
        """Compiled C++ counterpart (``native/host_bfs.cc`` model 6):
        same lanes, fingerprints, and exact thread-sort representative."""
        return (6, [self.thread_count])

    def lane_bits(self):
        """Packed-row layout: counter/read values bounded by the thread
        count (one write per thread, serialized by the lock), a 1-bit
        lock, a 3-bit pc (0..4)."""
        t_bits = max(2, self.thread_count.bit_length())
        return [t_bits, 1] + [t_bits, 3] * self.thread_count

    # -- Codec -----------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        vec = np.zeros(self.state_width, np.uint32)
        vec[0] = state.i
        vec[1] = 1 if state.lock else 0
        for k, (t, pc) in enumerate(state.s):
            vec[2 + 2 * k] = t
            vec[3 + 2 * k] = pc
        return vec

    def decode(self, vec: np.ndarray):
        return self._host.LockState(
            int(vec[0]), bool(vec[1]),
            tuple((int(vec[2 + 2 * k]), int(vec[3 + 2 * k]))
                  for k in range(self.thread_count)))

    # -- Device transition (increment_lock.rs:60-96) ---------------------

    def step(self, vec):
        i = vec[0]
        lock = vec[1]
        succs = []
        valids = []
        for k in range(self.thread_count):
            t = vec[2 + 2 * k]
            pc = vec[3 + 2 * k]
            take = vec.at[1].set(1).at[3 + 2 * k].set(1)
            read = vec.at[2 + 2 * k].set(i).at[3 + 2 * k].set(2)
            write = vec.at[0].set(t + 1).at[3 + 2 * k].set(3)
            release = vec.at[1].set(0).at[3 + 2 * k].set(4)
            succ = jnp.where(pc == 0, take,
                             jnp.where(pc == 1, read,
                                       jnp.where(pc == 2, write, release)))
            succs.append(succ)
            valids.append(((pc == 0) & (lock == 0)) | (pc == 1)
                          | (pc == 2) | ((pc == 3) & (lock == 1)))
        return jnp.stack(succs), jnp.stack(valids)

    # -- Properties (increment_lock.rs:98-104) ---------------------------

    def device_properties(self):
        pcs = [3 + 2 * k for k in range(self.thread_count)]

        def fin(vec):
            done = sum((vec[p] >= 3).astype(jnp.uint32) for p in pcs)
            return done == vec[0]

        def mutex(vec):
            inside = sum(((vec[p] >= 1) & (vec[p] < 4)).astype(jnp.uint32)
                         for p in pcs)
            return inside <= 1

        return {"fin": fin, "mutex": mutex}

    # -- Symmetry --------------------------------------------------------

    def representative(self, vec):
        T = self.thread_count
        pairs = vec[2:].reshape(T, 2)
        key = pairs[:, 0] * 8 + pairs[:, 1]  # pc < 8: lexicographic
        order = jnp.argsort(key)
        return jnp.concatenate([vec[:2], pairs[order].reshape(2 * T)])

"""Device encoding of Single Decree Paxos under linearizability checking.

The north-star workload (`examples/paxos.rs`, BASELINE.json): a
``RegisterActor`` system — 3 Paxos servers + ``client_count`` clients each
doing one Put then one Get — with a ``LinearizabilityTester`` riding along
as ActorModel history, checked for ``always linearizable`` and
``sometimes value chosen``. Parity gate: 16,668 unique states at 2
clients / 3 servers (`examples/paxos.rs:289`).

Built on :class:`~stateright_tpu.tpu.register_workload.
RegisterWorkloadDevice`, which owns the client state machine, the
history codec, and the on-device linearizability predicate shared by
every register workload; this module implements only the Paxos *server*
(`paxos.rs:96-222`) and its bounded universes:

- **values**: ``0`` = NO_VALUE, ``1+k`` = client k's put value
  (`register.rs:119-217` derives values from client ids)
- **ballots**: ``(round, leader)`` with round <= client_count (rounds
  only increase when a Put is handled, and each Put is delivered at most
  once on the non-duplicating network) -> index ``1 + (r-1)*S + leader``
- **proposals**: client k's ``(request_id, requester, value)`` triple is
  fully determined by k -> index ``1+k``
- **accepted pairs**: ``(ballot, proposal)`` -> ``1 + (b-1)*C + (p-1)``;
  index order == the host's ``_accepted_key`` lexicographic order, so
  quorum-max selection is an integer max

Internal-message fields ride the envelope's ``extra`` bits:
``ballot[0:4] | proposal[4:6] | last_accepted[6:11]``.

Server lane layout (per server): ballot, proposal, prepares[S]
(0 = absent else 1+la), accepts mask, accepted la, is_decided.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..actor_device import EMPTY_ENV
from ..register_workload import (GET, GETOK, PUT, PUTOK,
                                 RegisterWorkloadDevice)

__all__ = ["PaxosDevice"]

# Internal kind codes follow the public four (see INTERNAL_KINDS below):
PREPARE, PREPARED, ACCEPT, ACCEPTED, DECIDED = range(4, 9)


class PaxosDevice(RegisterWorkloadDevice):
    SERVER_LANES = ("ballot", "proposal", "prep0", "prep1", "prep2",
                    "accepts", "accepted", "decided")
    INTERNAL_KINDS = ("Prepare", "Prepared", "Accept", "Accepted",
                      "Decided")
    max_out = 3  # Accepted-quorum: 2 Decided broadcasts + 1 PutOk

    def __init__(self, client_count: int, server_count: int, host_module,
                 net_slots: int = 0, liveness: bool = False):
        self.liveness = liveness
        if server_count != 3:
            from ..device_model import DeviceFormUnavailable

            raise DeviceFormUnavailable(
                "the device encoding is sized for 3 servers (the "
                "reference example pins server_count=3, "
                "paxos.rs:326-328); other counts run on the host "
                "engines")
        self._host = host_module
        super().__init__(client_count, server_count, host_module,
                         net_slots=net_slots,
                         duplicating=False,  # paxos.rs:213
                         lossy=False)
        # Internal-message extra layout: ballot[0:4] | proposal | last-
        # accepted. The proposal field holds 0..C so it widens with the
        # client count (like the envelope value field).
        self.prop_bits = 2 if client_count <= 3 else 3
        self.prop_mask = (1 << self.prop_bits) - 1
        self.la_shift = 4 + self.prop_bits

    def native_form(self):
        """Compiled C++ counterpart (``native/host_bfs.cc`` model 0):
        same lanes, envelopes, and fingerprints as this device form."""
        return (0, [self.C, 1 if self.liveness else 0])

    # -- Packed-row layout: the bounded universes above, as bit widths ----

    def _la_bits(self) -> int:
        """Width of a last-accepted index: ``1 + (b-1)*C + (p-1)`` with
        ballot <= C*S and proposal <= C (the module docstring's
        universes)."""
        la_max = 1 + (self.C * self.S - 1) * self.C + (self.C - 1)
        return la_max.bit_length()

    def server_lane_bits(self) -> tuple:
        ballot_bits = (self.C * self.S).bit_length()
        prop_bits = self.C.bit_length()
        prep_bits = (1 + (1 + (self.C * self.S - 1) * self.C
                          + (self.C - 1))).bit_length()
        return (ballot_bits, prop_bits,
                prep_bits, prep_bits, prep_bits,   # prepares[S=3]
                self.S,                            # accepts bitmask
                self._la_bits(),                   # accepted la index
                1)                                 # is_decided

    def extra_bits(self) -> int:
        # ballot[0:4] | proposal[4:4+prop_bits] | la above — the exact
        # field layout of encode_internal/decode_internal.
        return self.la_shift + self._la_bits()

    # -- Universe indices -------------------------------------------------

    # ballot: 0 = (0, Id(0)); 1+(r-1)*S+leader for r >= 1
    def _ballot_tuple(self, idx: int):
        from ...actor import Id

        if idx == 0:
            return (0, Id(0))
        return ((idx - 1) // self.S + 1, Id((idx - 1) % self.S))

    def _ballot_idx(self, ballot) -> int:
        r, leader = ballot
        return 0 if r == 0 else 1 + (r - 1) * self.S + int(leader)

    # proposal: 0 = None; 1+k = client k's (request_id, requester, value)
    def _proposal_tuple(self, idx: int):
        from ...actor import Id

        if idx == 0:
            return None
        i = self.S + idx - 1  # requester actor index
        return (1 * i, Id(i), self.value_of(idx))

    def _proposal_idx(self, proposal) -> int:
        return 0 if proposal is None else int(proposal[1]) - self.S + 1

    # accepted pair: 0 = None; 1 + (ballot_idx-1)*C + (proposal_idx-1)
    def _la_idx(self, accepted) -> int:
        if accepted is None:
            return 0
        ballot, proposal = accepted
        return (1 + (self._ballot_idx(ballot) - 1) * self.C
                + (self._proposal_idx(proposal) - 1))

    def _la_tuple(self, idx: int):
        if idx == 0:
            return None
        b = (idx - 1) // self.C + 1
        p = (idx - 1) % self.C + 1
        return (self._ballot_tuple(b), self._proposal_tuple(p))

    # -- Internal-message codec (extra = ballot | prop << 4 | la << 6) ----

    def encode_internal(self, inner) -> tuple:
        h = self._host
        it = type(inner)
        if it is h.Prepare:
            return "Prepare", 0, 0, self._ballot_idx(inner.ballot)
        if it is h.Prepared:
            return ("Prepared", 0, 0, self._ballot_idx(inner.ballot)
                    | self._la_idx(inner.last_accepted) << self.la_shift)
        if it is h.Accept:
            return ("Accept", 0, 0, self._ballot_idx(inner.ballot)
                    | self._proposal_idx(inner.proposal) << 4)
        if it is h.Accepted:
            return "Accepted", 0, 0, self._ballot_idx(inner.ballot)
        return ("Decided", 0, 0, self._ballot_idx(inner.ballot)
                | self._proposal_idx(inner.proposal) << 4)

    def decode_internal(self, kind_name: str, req: int, value: int,
                        extra: int):
        h = self._host
        ballot = self._ballot_tuple(extra & 15)
        prop = self._proposal_tuple((extra >> 4) & self.prop_mask)
        la = self._la_tuple(extra >> self.la_shift)
        if kind_name == "Prepare":
            return h.Prepare(ballot)
        if kind_name == "Prepared":
            return h.Prepared(ballot, la)
        if kind_name == "Accept":
            return h.Accept(ballot, prop)
        if kind_name == "Accepted":
            return h.Accepted(ballot)
        return h.Decided(ballot, prop)

    # -- Client symmetry (driver config 5) --------------------------------
    #
    # A client permutation touches paxos-specific universes: proposal
    # indices (1+k, client-derived), and accepted-pair / last-accepted
    # indices (which embed the proposal). Ballots are server-derived and
    # untouched. Soundness of the la-order-dependent quorum max
    # (`server_deliver``'s ``jnp.max(prep2)``) is preserved because on
    # reachable states a ballot has a unique proposal, so equal-ballot
    # entries never disagree after rewriting.

    def sym_extra_tables(self, sigma: tuple, t: dict) -> None:
        c, s = self.C, self.S
        la_max = 1 + (c * s - 1) * c + (c - 1)  # 1+(b-1)*C+(p-1), b<=C*S
        la = np.arange(la_max + 1, dtype=np.uint32)
        for i in range(1, la_max + 1):
            b = (i - 1) // c + 1
            p = (i - 1) % c + 1
            la[i] = 1 + (b - 1) * c + (sigma[p - 1] + 1 - 1)
        prep = np.arange(la_max + 2, dtype=np.uint32)
        prep[1:] = 1 + la[prep[1:] - 1]
        t["la"] = la
        t["prep"] = prep

    def sym_rewrite_servers(self, servers, t, xp):
        val_map = xp.asarray(t["val"])
        la_map = xp.asarray(t["la"])
        prep_map = xp.asarray(t["prep"])
        ballot = servers[:, 0:1]
        proposal = val_map[xp.minimum(servers[:, 1:2], self.value_mask)]
        preps = prep_map[xp.minimum(servers[:, 2:5],
                                    np.uint32(len(t["prep"]) - 1))]
        accepts = servers[:, 5:6]
        accepted = la_map[xp.minimum(servers[:, 6:7],
                                     np.uint32(len(t["la"]) - 1))]
        decided = servers[:, 7:8]
        return xp.concatenate(
            [ballot, proposal, preps, accepts, accepted, decided], axis=1)

    def sym_rewrite_internal_req(self, kind, req, t, xp):
        return req  # paxos internals leave the req field unused (0)

    def sym_rewrite_extra(self, kind, extra, t, xp):
        la_map = xp.asarray(t["la"])
        val_map = xp.asarray(t["val"])
        ballot = extra & 15
        prop = (extra >> 4) & self.prop_mask
        la = extra >> self.la_shift
        with_la = ballot | (la_map[xp.minimum(la, np.uint32(
            len(t["la"]) - 1))] << self.la_shift)
        with_prop = ballot | (val_map[xp.minimum(
            prop, self.value_mask)] << 4)
        out = xp.where(kind == PREPARED, with_la,
                       xp.where((kind == ACCEPT) | (kind == DECIDED),
                                with_prop, extra))
        return out

    # -- Server host codec ------------------------------------------------

    def encode_server(self, ps, vec: np.ndarray, base: int) -> None:
        from ...actor import Id

        s = self.S
        vec[base + 0] = self._ballot_idx(ps.ballot)
        vec[base + 1] = self._proposal_idx(ps.proposal)
        prepares = dict(ps.prepares)
        for a in range(s):
            if Id(a) in prepares:
                vec[base + 2 + a] = 1 + self._la_idx(prepares[Id(a)])
        vec[base + 5] = sum(1 << int(a) for a in ps.accepts)
        vec[base + 6] = self._la_idx(ps.accepted)
        vec[base + 7] = 1 if ps.is_decided else 0

    def decode_server(self, vec: np.ndarray, base: int, server_index: int):
        from ...actor import Id

        h = self._host
        s = self.S
        prepares = tuple(sorted(
            (Id(a), self._la_tuple(int(vec[base + 2 + a]) - 1))
            for a in range(s) if vec[base + 2 + a]))
        return h.PaxosState(
            ballot=self._ballot_tuple(int(vec[base])),
            proposal=self._proposal_tuple(int(vec[base + 1])),
            prepares=prepares,
            accepts=tuple(Id(a) for a in range(s)
                          if (int(vec[base + 5]) >> a) & 1),
            accepted=self._la_tuple(int(vec[base + 6])),
            is_decided=bool(vec[base + 7]),
        )

    # -- Server delivery (paxos.rs:96-222) --------------------------------

    def server_deliver(self, lanes, f):
        """PaxosActor.on_msg, vectorized over the server selected by
        ``f.dst``. Every branch computes; ``where`` selects — per LANE,
        not per branch-state: the message kinds are mutually exclusive,
        so each lane's final value is a short scalar where-chain instead
        of six sequential 8-lane selects over materialized branch
        vectors (which cost ~8x the data traffic per op on the CPU
        backend and fuse no better on TPU)."""
        s, c = self.S, self.C
        u = jnp.uint32
        dst, src = f.dst, f.src
        m_ballot = f.extra & 15
        m_prop = (f.extra >> 4) & self.prop_mask
        m_la = f.extra >> self.la_shift
        b, prop = lanes[0], lanes[1]
        prep = lanes[2:5]
        accmask, acc, dec = lanes[5], lanes[6], lanes[7]

        no_env = u(EMPTY_ENV)
        majority = s // 2 + 1

        # Branch: decided + Get -> GetOk with the accepted value
        # (paxos.rs:118-126). accepted proposal index == value index.
        acc_prop = jnp.where(acc == 0, u(0), (acc - 1) % c + 1)
        getok = self.build_env(dst=src, src=dst, kind=GETOK, req=f.req,
                               value=acc_prop)
        case_get = dec == 1
        get_handled = f.kind == GET

        # Branch: Put with no proposal (paxos.rs:123-133).
        r_cur = jnp.where(b == 0, u(0), (b - 1) // s + 1)
        put_ballot = r_cur * s + dst + 1  # (r_cur+1, dst)
        put_prop = (f.req & 3) + 1  # proposal idx = client k + 1
        put_outs = [jnp.where(dst == p, no_env,
                              self.build_env(dst=p, src=dst, kind=PREPARE,
                                             extra=put_ballot))
                    for p in range(s)]  # broadcast to peers (not self)
        case_put = (f.kind == PUT) & (prop == 0)

        # Branch: Prepare with a higher ballot (paxos.rs:138-143).
        prepared_out = self.build_env(dst=src, src=dst, kind=PREPARED,
                                      extra=m_ballot | acc << self.la_shift)
        case_prepare = (f.kind == PREPARE) & (b < m_ballot)

        # Branch: Prepared at the current ballot (paxos.rs:145-165).
        prep2 = [jnp.where(src == a, 1 + m_la, prep[a]) for a in range(s)]
        prep_count = sum((p != 0).astype(u) for p in prep2)
        quorum_p = prep_count == majority
        best = jnp.maximum(jnp.maximum(prep2[0], prep2[1]),
                           prep2[2]) - 1  # la order == _accepted_key order
        best_prop = jnp.where(best == 0, prop, (best - 1) % c + 1)
        accepted_new = 1 + (b - 1) * c + (best_prop - 1)
        accept_outs = [
            jnp.where(quorum_p & (dst != p),
                      self.build_env(dst=p, src=dst, kind=ACCEPT,
                                     extra=b | best_prop << 4),
                      no_env) for p in range(s)]
        case_prepared = (f.kind == PREPARED) & (m_ballot == b)

        # Branch: Accept at >= ballot (paxos.rs:167-170).
        accepted_out = self.build_env(dst=src, src=dst, kind=ACCEPTED,
                                      extra=m_ballot)
        la_m = 1 + (m_ballot - 1) * c + (m_prop - 1)  # shared w/ Decided
        case_accept = (f.kind == ACCEPT) & (b <= m_ballot)

        # Branch: Accepted at the current ballot (paxos.rs:172-182).
        accmask2 = accmask | (u(1) << src)
        acc_count = sum(((accmask2 >> a) & 1) for a in range(s))
        quorum_a = acc_count == majority
        # requester = proposal's client; req field = (op=1, client)
        req_k = prop - 1
        putok_out = self.build_env(dst=s + req_k, src=dst, kind=PUTOK,
                                   req=req_k)
        decided_outs = [
            jnp.where(quorum_a & (dst != p),
                      self.build_env(dst=p, src=dst, kind=DECIDED,
                                     extra=b | prop << 4),
                      no_env) for p in range(s)]
        case_accepted = (f.kind == ACCEPTED) & (m_ballot == b)

        # Branch: Decided (paxos.rs:184-187).
        case_decided = f.kind == DECIDED

        # Select, per lane. The decided guard short-circuits everything
        # else (paxos.rs:115-121); the kinds are mutually exclusive, so
        # select order between branches is immaterial.
        def sel(cond, a, b):
            return jnp.where(cond, a, b)

        live = ~case_get  # not decided
        g_put = live & case_put
        g_prep = live & case_prepare
        g_prpd = live & case_prepared
        g_prpd_q = g_prpd & quorum_p
        g_acc = live & case_accept
        g_accd = live & case_accepted
        g_dec = live & case_decided

        new_lanes = jnp.stack([
            sel(g_put, put_ballot,
                sel(g_prep | g_acc | g_dec, m_ballot, b)),        # ballot
            sel(g_put, put_prop,
                sel(g_prpd_q, best_prop, prop)),                  # proposal
            *[sel(g_put, jnp.where(dst == a, 1 + acc, u(0)),
                  sel(g_prpd, prep2[a], prep[a]))                 # prepares
              for a in range(s)],
            sel(g_put, u(0),
                sel(g_prpd_q, accmask | (u(1) << dst),
                    sel(g_accd, accmask2, accmask))),             # accepts
            sel(g_prpd_q, accepted_new,
                sel(g_acc | g_dec, la_m, acc)),                   # accepted
            sel((g_accd & quorum_a) | g_dec, u(1), dec),          # decided
        ])

        handled = jnp.where(
            case_get, get_handled,
            case_put | case_prepare | case_prepared | case_accept
            | case_accepted | case_decided)

        # one reply slot
        reply = sel(case_get & get_handled, getok, no_env)
        reply = sel(g_prep, prepared_out, reply)
        reply = sel(g_acc, accepted_out, reply)
        reply = sel(g_accd & quorum_a, putok_out, reply)
        # two broadcast slots: first two non-EMPTY of the three per-peer
        # envelopes, in peer order (the self-slot is EMPTY) — inlined
        # compact for s=3.
        bc = [sel(g_put, put_outs[p],
                  sel(g_prpd, accept_outs[p],
                      sel(g_accd, decided_outs[p], no_env)))
              for p in range(s)]
        b0e, b1e = bc[0] != no_env, bc[1] != no_env
        c0 = jnp.where(b0e, bc[0], jnp.where(b1e, bc[1], bc[2]))
        c1 = jnp.where(b0e & b1e, bc[1],
                       jnp.where(b0e ^ b1e, bc[2], no_env))
        outs = jnp.stack([reply, c0, c1])

        return new_lanes, handled, outs

"""Device encoding of Single Decree Paxos under linearizability checking.

The north-star workload (`examples/paxos.rs`, BASELINE.json): a
``RegisterActor`` system — 3 Paxos servers + ``client_count`` clients each
doing one Put then one Get — with a ``LinearizabilityTester`` riding along
as ActorModel history, checked for ``always linearizable`` and
``sometimes value chosen``. Parity gate: 16,668 unique states at 2
clients / 3 servers (`examples/paxos.rs:289`).

Everything is bounded, so every field enumerates:

- **values**: ``0`` = NO_VALUE, ``1+k`` = client k's put value
  (`register.rs:119-217` derives values from client ids)
- **ballots**: ``(round, leader)`` with round <= client_count (rounds
  only increase when a Put is handled, and each Put is delivered at most
  once on the non-duplicating network) -> index ``1 + (r-1)*S + leader``
- **proposals**: client k's ``(request_id, requester, value)`` triple is
  fully determined by k -> index ``1+k``
- **accepted pairs**: ``(ballot, proposal)`` -> ``1 + (b-1)*C + (p-1)``;
  index order == the host's ``_accepted_key`` lexicographic order, so
  quorum-max selection is an integer max
- **history**: per client — a status in {1: put in flight, 2: put done,
  3: put done + get in flight, 4: both done}, the Get's return value,
  and the Get-invoke happened-before edges (2 bits per peer). The Put's
  happened-before set is always empty (invoked at ``on_start`` before
  anything completes) and is not stored.

The ``linearizable`` predicate runs *on device*: all interleavings of the
<= 2 ops per client that respect per-thread order (90 multiset
permutations at 3 clients), crossed with every subset of in-flight ops to
include (they may take effect before returning), are enumerated
statically; each is validated vectorially against register semantics and
the recorded real-time edges — the reference's backtracking search
(`linearizability.rs:178-240`) becomes a data-parallel reduction.

Lane layout (S = servers, C = clients, E = net slots):

====================  ==========================================
``[0 .. 8S)``          per-server: ballot, proposal, prepares[S]
                       (0 = absent else 1+la), accepts mask,
                       accepted la, is_decided
``[8S .. 8S+C)``       per-client phase (1 awaiting put-ok,
                       2 awaiting get-ok, 3 done)
``[.. +3C)``           per-client history: status, get-ret, hb-edges
``[.. +E+1)``          network slots + overflow flag
====================  ==========================================
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..actor_device import EMPTY_ENV, ActorDeviceModel
from ..register_workload import perm_tables as _perm_tables

__all__ = ["PaxosDevice"]

# Message kinds (envelope bits [6:10]).
PUT, GET, PUTOK, GETOK, PREPARE, PREPARED, ACCEPT, ACCEPTED, DECIDED = \
    range(9)


class PaxosDevice(ActorDeviceModel):
    max_out = 3  # Accepted-quorum: 2 Decided broadcasts + 1 PutOk

    def __init__(self, client_count: int, server_count: int, host_module,
                 net_slots: int = 0):
        if server_count != 3:
            raise NotImplementedError(
                "the device encoding is sized for 3 servers (the "
                "reference example's configuration)")
        if not 1 <= client_count <= 3:
            raise NotImplementedError(
                "bit fields sized for at most 3 clients")
        self._host = host_module
        self.S = server_count
        self.C = client_count
        self.net_slots = net_slots or 16 * client_count
        self.duplicating = False  # paxos.rs:213 (non-duplicating)
        self.lossy = False
        s, c = self.S, self.C
        self.phase_off = 8 * s
        self.hist_off = 8 * s + c
        self.net_offset = self.hist_off + 3 * c
        self.state_width = self.net_offset + self.net_slots + 1
        self.error_lane = self.net_offset + self.net_slots
        self._perm_thread, self._perm_occ, self._perm_pos = _perm_tables(c)

    # -- Universe indices -------------------------------------------------

    # ballot: 0 = (0, Id(0)); 1+(r-1)*S+leader for r >= 1
    def _ballot_tuple(self, idx: int):
        from ...actor import Id

        if idx == 0:
            return (0, Id(0))
        return ((idx - 1) // self.S + 1, Id((idx - 1) % self.S))

    def _ballot_idx(self, ballot) -> int:
        r, leader = ballot
        return 0 if r == 0 else 1 + (r - 1) * self.S + int(leader)

    def _value_idx(self, value) -> int:
        if value == self._host.NO_VALUE:
            return 0
        return ord(value) - ord("A") + 1

    def _value(self, idx: int):
        return self._host.NO_VALUE if idx == 0 else chr(ord("A") + idx - 1)

    # proposal: 0 = None; 1+k = client k's (request_id, requester, value)
    def _proposal_tuple(self, idx: int):
        from ...actor import Id

        if idx == 0:
            return None
        i = self.S + idx - 1  # requester actor index
        return (1 * i, Id(i), self._value(idx))

    def _proposal_idx(self, proposal) -> int:
        return 0 if proposal is None else int(proposal[1]) - self.S + 1

    # accepted pair: 0 = None; 1 + (ballot_idx-1)*C + (proposal_idx-1)
    def _la_idx(self, accepted) -> int:
        if accepted is None:
            return 0
        ballot, proposal = accepted
        return (1 + (self._ballot_idx(ballot) - 1) * self.C
                + (self._proposal_idx(proposal) - 1))

    def _la_tuple(self, idx: int):
        if idx == 0:
            return None
        b = (idx - 1) // self.C + 1
        p = (idx - 1) % self.C + 1
        return (self._ballot_tuple(b), self._proposal_tuple(p))

    # request id field: (op-1) << 2 | client  (request_id = op * actor)
    def _req_field(self, request_id: int) -> int:
        for k in range(self.C):
            actor = self.S + k
            for op in (1, 2):
                if op * actor == request_id:
                    return (op - 1) << 2 | k
        raise ValueError(f"request id {request_id} outside the universe")

    def _req_id(self, field: int) -> int:
        op = (field >> 2) + 1
        k = field & 3
        return op * (self.S + k)

    # -- Envelope codec ---------------------------------------------------
    # dst[0:3] src[3:6] kind[6:10] ballot[10:14] prop[14:16] la[16:21]
    # req[21:24] value[24:26]

    def env_encode(self, envelope) -> int:
        from ...actor.register import Get, GetOk, Put, PutOk

        h = self._host
        msg = envelope.msg
        kind = ballot = prop = la = req = value = 0
        t = type(msg)
        if t is Put:
            kind, req, value = PUT, self._req_field(msg.request_id), \
                self._value_idx(msg.value)
        elif t is Get:
            kind, req = GET, self._req_field(msg.request_id)
        elif t is PutOk:
            kind, req = PUTOK, self._req_field(msg.request_id)
        elif t is GetOk:
            kind, req, value = GETOK, self._req_field(msg.request_id), \
                self._value_idx(msg.value)
        else:  # Internal
            inner = msg.msg
            it = type(inner)
            if it is h.Prepare:
                kind, ballot = PREPARE, self._ballot_idx(inner.ballot)
            elif it is h.Prepared:
                kind, ballot, la = (PREPARED, self._ballot_idx(inner.ballot),
                                    self._la_idx(inner.last_accepted))
            elif it is h.Accept:
                kind, ballot, prop = (ACCEPT, self._ballot_idx(inner.ballot),
                                      self._proposal_idx(inner.proposal))
            elif it is h.Accepted:
                kind, ballot = ACCEPTED, self._ballot_idx(inner.ballot)
            else:  # Decided
                kind, ballot, prop = (DECIDED, self._ballot_idx(inner.ballot),
                                      self._proposal_idx(inner.proposal))
        return (int(envelope.dst) | int(envelope.src) << 3 | kind << 6
                | ballot << 10 | prop << 14 | la << 16 | req << 21
                | value << 24)

    def env_decode(self, code: int):
        from ...actor import Id
        from ...actor.model_state import Envelope
        from ...actor.register import Get, GetOk, Internal, Put, PutOk

        h = self._host
        dst = Id(code & 7)
        src = Id((code >> 3) & 7)
        kind = (code >> 6) & 15
        ballot = self._ballot_tuple((code >> 10) & 15)
        prop = self._proposal_tuple((code >> 14) & 3)
        la = self._la_tuple((code >> 16) & 31)
        req = self._req_id((code >> 21) & 7)
        value = self._value((code >> 24) & 3)
        if kind == PUT:
            msg = Put(req, value)
        elif kind == GET:
            msg = Get(req)
        elif kind == PUTOK:
            msg = PutOk(req)
        elif kind == GETOK:
            msg = GetOk(req, value)
        elif kind == PREPARE:
            msg = Internal(h.Prepare(ballot))
        elif kind == PREPARED:
            msg = Internal(h.Prepared(ballot, la))
        elif kind == ACCEPT:
            msg = Internal(h.Accept(ballot, prop))
        elif kind == ACCEPTED:
            msg = Internal(h.Accepted(ballot))
        else:
            msg = Internal(h.Decided(ballot, prop))
        return Envelope(src, dst, msg)

    # -- State codec ------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        from ...actor import Id

        s, c = self.S, self.C
        vec = np.zeros(self.state_width, np.uint32)
        for i in range(s):
            ps = state.actor_states[i].state  # RegisterServerState wrapper
            base = 8 * i
            vec[base + 0] = self._ballot_idx(ps.ballot)
            vec[base + 1] = self._proposal_idx(ps.proposal)
            prepares = dict(ps.prepares)
            for a in range(s):
                if Id(a) in prepares:
                    vec[base + 2 + a] = 1 + self._la_idx(prepares[Id(a)])
            vec[base + 5] = sum(1 << int(a) for a in ps.accepts)
            vec[base + 6] = self._la_idx(ps.accepted)
            vec[base + 7] = 1 if ps.is_decided else 0
        for k in range(c):
            cs = state.actor_states[s + k]
            # phase 1: awaiting put-ok; 2: awaiting get-ok; 3: done
            vec[self.phase_off + k] = (3 if cs.awaiting is None
                                       else cs.op_count)
        self._encode_history(state.history, vec)
        vec[self.net_offset:] = self.encode_network(state.network)
        return vec

    def _encode_history(self, tester, vec: np.ndarray) -> None:
        from ...actor import Id

        s, c = self.S, self.C
        assert tester.is_valid_history, \
            "paxos workload cannot produce invalid histories"
        for k in range(c):
            tid = Id(s + k)
            completed = tester.history_by_thread.get(tid, ())
            inflight = tester.in_flight_by_thread.get(tid)
            if len(completed) == 0:
                status = 1 if inflight is not None else 0
            elif len(completed) == 1:
                status = 3 if inflight is not None else 2
            else:
                status = 4
            ret = 0
            if len(completed) == 2:
                ret = self._value_idx(completed[1][2].value)  # ReadOk
            hb = 0
            read_cs = None
            if status == 3:
                read_cs = inflight[0]
            elif status == 4:
                read_cs = completed[1][0]
            if read_cs is not None:
                for peer_tid, last_idx in read_cs:
                    j = int(peer_tid) - s
                    hb |= (last_idx + 1) << (2 * j)
            base = self.hist_off + 3 * k
            vec[base] = status
            vec[base + 1] = ret
            vec[base + 2] = hb

    def decode(self, vec: np.ndarray):
        from ...actor import Id
        from ...actor.model_state import ActorModelState, Network
        from ...actor.register import (RegisterClientState,
                                       RegisterServerState)
        from ...semantics import LinearizabilityTester, Register

        h = self._host
        s, c = self.S, self.C
        actor_states = []
        for i in range(s):
            base = 8 * i
            prepares = tuple(sorted(
                (Id(a), self._la_tuple(int(vec[base + 2 + a]) - 1))
                for a in range(s) if vec[base + 2 + a]))
            actor_states.append(RegisterServerState(h.PaxosState(
                ballot=self._ballot_tuple(int(vec[base])),
                proposal=self._proposal_tuple(int(vec[base + 1])),
                prepares=prepares,
                accepts=tuple(Id(a) for a in range(s)
                              if (int(vec[base + 5]) >> a) & 1),
                accepted=self._la_tuple(int(vec[base + 6])),
                is_decided=bool(vec[base + 7]),
            )))
        for k in range(c):
            phase = int(vec[self.phase_off + k])
            i = s + k
            if phase == 3:
                cs = RegisterClientState(awaiting=None, op_count=3)
            else:
                cs = RegisterClientState(awaiting=phase * i, op_count=phase)
            actor_states.append(cs)
        tester = LinearizabilityTester(Register(h.NO_VALUE))
        for k in range(c):
            base = self.hist_off + 3 * k
            status = int(vec[base])
            if status == 0:
                continue
            tid = Id(s + k)
            hb = int(vec[base + 2])
            read_cs = tuple(sorted(
                (Id(s + j), ((hb >> (2 * j)) & 3) - 1)
                for j in range(c) if (hb >> (2 * j)) & 3))
            write_entry = ((), self._write_op(k), self._write_ok())
            tester.history_by_thread[tid] = ()
            if status == 1:
                tester.in_flight_by_thread[tid] = ((), self._write_op(k))
            else:
                tester.history_by_thread[tid] = (write_entry,)
            if status == 3:
                tester.in_flight_by_thread[tid] = (read_cs, self._read_op())
            elif status == 4:
                ret = self._read_ok(self._value(int(vec[base + 1])))
                tester.history_by_thread[tid] = (
                    write_entry, (read_cs, self._read_op(), ret))
        return ActorModelState(
            actor_states=actor_states,
            network=Network(self.decode_network(vec[self.net_offset:])),
            is_timer_set=[],
            history=tester,
        )

    def _write_op(self, k: int):
        from ...semantics.register import Write

        return Write(self._value(k + 1))

    def _write_ok(self):
        from ...semantics.register import WriteOk

        return WriteOk()

    def _read_op(self):
        from ...semantics.register import Read

        return Read()

    def _read_ok(self, value):
        from ...semantics.register import ReadOk

        return ReadOk(value)

    # -- Delivery ---------------------------------------------------------

    def deliver(self, vec, env):
        s = self.S
        dst = env & 7
        is_server = dst < s
        srv_vec, srv_handled, srv_outs = self._deliver_server(vec, env)
        cli_vec, cli_handled, cli_outs = self._deliver_client(vec, env)
        new_vec = jnp.where(is_server, srv_vec, cli_vec)
        handled = jnp.where(is_server, srv_handled, cli_handled)
        outs = jnp.where(is_server, srv_outs, cli_outs)
        return new_vec, handled, outs

    def _env(self, *, dst, src, kind, ballot=0, prop=0, la=0, req=0,
             value=0):
        return (jnp.uint32(dst) | jnp.uint32(src) << 3
                | jnp.uint32(kind) << 6 | jnp.uint32(ballot) << 10
                | jnp.uint32(prop) << 14 | jnp.uint32(la) << 16
                | jnp.uint32(req) << 21 | jnp.uint32(value) << 24)

    def _deliver_server(self, vec, env):
        """PaxosActor.on_msg (`paxos.rs:96-222`), vectorized over the
        server selected by ``dst``. Every branch computes; ``where``
        selects."""
        s, c = self.S, self.C
        u = jnp.uint32
        dst = env & 7
        src = (env >> 3) & 7
        kind = (env >> 6) & 15
        m_ballot = (env >> 10) & 15
        m_prop = (env >> 14) & 3
        m_la = (env >> 16) & 31
        m_req = (env >> 21) & 7

        # Gather the destination server's lanes.
        base = 8 * dst
        lanes = jnp.stack([vec[base + j] for j in range(8)])

        def make(ballot=None, proposal=None, prep=None, accepts=None,
                 accepted=None, decided=None):
            out = lanes
            if ballot is not None:
                out = out.at[0].set(ballot)
            if proposal is not None:
                out = out.at[1].set(proposal)
            if prep is not None:
                out = out.at[2:5].set(prep)
            if accepts is not None:
                out = out.at[5].set(accepts)
            if accepted is not None:
                out = out.at[6].set(accepted)
            if decided is not None:
                out = out.at[7].set(decided)
            return out

        b, prop = lanes[0], lanes[1]
        prep = lanes[2:5]
        accmask, acc, dec = lanes[5], lanes[6], lanes[7]
        no_env = u(EMPTY_ENV)
        majority = s // 2 + 1

        # Branch: decided + Get -> GetOk with the accepted value
        # (paxos.rs:118-126). accepted proposal index == value index.
        acc_prop = jnp.where(acc == 0, u(0), (acc - 1) % c + 1)
        getok = self._env(dst=src, src=dst, kind=GETOK, req=m_req,
                          value=acc_prop)
        case_get = dec == 1
        get_handled = kind == GET

        # Branch: Put with no proposal (paxos.rs:123-133).
        r_cur = jnp.where(b == 0, u(0), (b - 1) // s + 1)
        put_ballot = r_cur * s + dst + 1  # (r_cur+1, dst)
        put_prop = (m_req & 3) + 1  # proposal idx = client k + 1
        put_prep = jnp.zeros(s, u).at[dst].set(1 + acc)
        put_lanes = make(ballot=put_ballot, proposal=put_prop,
                         prep=put_prep, accepts=u(0))
        # broadcast to peers only (not self)
        put_outs = jnp.stack(
            [jnp.where(dst == p, no_env,
                       self._env(dst=p, src=dst, kind=PREPARE,
                                 ballot=put_ballot)) for p in range(s)])
        case_put = (kind == PUT) & (prop == 0)

        # Branch: Prepare with a higher ballot (paxos.rs:138-143).
        prepared_out = self._env(dst=src, src=dst, kind=PREPARED,
                                 ballot=m_ballot, la=acc)
        prepare_lanes = make(ballot=m_ballot)
        case_prepare = (kind == PREPARE) & (b < m_ballot)

        # Branch: Prepared at the current ballot (paxos.rs:145-165).
        src_is = [src == a for a in range(s)]
        prep2 = jnp.stack([
            jnp.where(src_is[a], 1 + m_la, prep[a]) for a in range(s)])
        prep_count = jnp.sum((prep2 != 0).astype(u))
        quorum_p = prep_count == majority
        best = jnp.max(prep2) - 1  # la order == _accepted_key order
        best_prop = jnp.where(best == 0, prop, (best - 1) % c + 1)
        accepted_new = 1 + (b - 1) * c + (best_prop - 1)
        prepared_lanes = make(
            proposal=jnp.where(quorum_p, best_prop, prop),
            prep=prep2,
            accepts=jnp.where(quorum_p, accmask | (u(1) << dst), accmask),
            accepted=jnp.where(quorum_p, accepted_new, acc))
        accept_outs = jnp.stack([
            jnp.where(quorum_p & (dst != p),
                      self._env(dst=p, src=dst, kind=ACCEPT, ballot=b,
                                prop=best_prop),
                      no_env) for p in range(s)])
        case_prepared = (kind == PREPARED) & (m_ballot == b)

        # Branch: Accept at >= ballot (paxos.rs:167-170).
        accepted_out = self._env(dst=src, src=dst, kind=ACCEPTED,
                                 ballot=m_ballot)
        accept_lanes = make(ballot=m_ballot,
                            accepted=1 + (m_ballot - 1) * c + (m_prop - 1))
        case_accept = (kind == ACCEPT) & (b <= m_ballot)

        # Branch: Accepted at the current ballot (paxos.rs:172-182).
        accmask2 = accmask | (u(1) << src)
        acc_count = sum(((accmask2 >> a) & 1) for a in range(s))
        quorum_a = acc_count == majority
        # requester = proposal's client; req field = (op=1, client)
        req_k = prop - 1
        putok_out = self._env(dst=s + req_k, src=dst, kind=PUTOK,
                              req=req_k)
        decided_outs = [
            jnp.where(quorum_a & (dst != p),
                      self._env(dst=p, src=dst, kind=DECIDED, ballot=b,
                                prop=prop),
                      no_env) for p in range(s)]
        accepted_lanes = make(accepts=accmask2,
                              decided=jnp.where(quorum_a, u(1), dec))
        case_accepted = (kind == ACCEPTED) & (m_ballot == b)

        # Branch: Decided (paxos.rs:184-187).
        decided_lanes = make(ballot=m_ballot,
                             accepted=1 + (m_ballot - 1) * c + (m_prop - 1),
                             decided=u(1))
        case_decided = kind == DECIDED

        # Select. Order mirrors the host's if-chain; the decided guard
        # short-circuits everything else (paxos.rs:115-121).
        def sel(cond, a, b):
            return jnp.where(cond, a, b)

        live = ~case_get  # not decided
        new_lanes = lanes
        new_lanes = sel(live & case_decided, decided_lanes, new_lanes)
        new_lanes = sel(live & case_accepted, accepted_lanes, new_lanes)
        new_lanes = sel(live & case_accept, accept_lanes, new_lanes)
        new_lanes = sel(live & case_prepared, prepared_lanes, new_lanes)
        new_lanes = sel(live & case_prepare, prepare_lanes, new_lanes)
        new_lanes = sel(live & case_put, put_lanes, new_lanes)

        handled = jnp.where(
            case_get, get_handled,
            case_put | case_prepare | case_prepared | case_accept
            | case_accepted | case_decided)

        outs = jnp.full((self.max_out,), EMPTY_ENV, u)
        # one reply slot
        reply = sel(case_get & get_handled, getok, no_env)
        reply = sel(live & case_prepare, prepared_out, reply)
        reply = sel(live & case_accept, accepted_out, reply)
        reply = sel(live & case_accepted & quorum_a, putok_out, reply)
        outs = outs.at[0].set(reply)
        # two broadcast slots (to the two peers; the self-slot is EMPTY)
        bcast = jnp.stack([
            sel(live & case_put, put_outs[p],
                sel(live & case_prepared, accept_outs[p],
                    sel(live & case_accepted, decided_outs[p], no_env)))
            for p in range(s)])
        order = jnp.argsort(bcast == no_env, stable=True)
        compacted = bcast[order]
        outs = outs.at[1].set(compacted[0])
        outs = outs.at[2].set(compacted[1])

        # Write back the server lanes.
        new_vec = vec
        for j in range(8):
            lane_val = new_lanes[j]
            for i in range(s):
                new_vec = new_vec.at[8 * i + j].set(
                    jnp.where(dst == i, lane_val, new_vec[8 * i + j]))
        return new_vec, handled, outs

    def _deliver_client(self, vec, env):
        """RegisterActor client (`register.rs:174-199`) + history
        recording (`register.rs:37-88`)."""
        s, c = self.S, self.C
        u = jnp.uint32
        dst = env & 7
        kind = (env >> 6) & 15
        m_req = (env >> 21) & 7
        m_value = (env >> 24) & 3
        k = dst - s  # client index
        phase = vec[self.phase_off + jnp.clip(k, 0, c - 1)]
        req_op = (m_req >> 2) + 1
        req_k = m_req & 3
        req_matches = (req_k == k) & (req_op == phase)

        putok_case = (kind == PUTOK) & (phase == 1) & req_matches
        getok_case = (kind == GETOK) & (phase == 2) & req_matches
        handled = putok_case | getok_case

        new_vec = vec
        # phase transition
        new_phase = jnp.where(putok_case, u(2),
                              jnp.where(getok_case, u(3), phase))
        for kk in range(c):
            new_vec = new_vec.at[self.phase_off + kk].set(
                jnp.where(k == kk, new_phase, vec[self.phase_off + kk]))

        # history: record_msg_in (PutOk -> WriteOk, GetOk -> ReadOk)
        # then record_msg_out for the Get send (Read invoke with
        # happened-before edges over peers' completed counts).
        hb = jnp.uint32(0)
        for j in range(c):
            st_j = vec[self.hist_off + 3 * j]
            comp_j = jnp.where(st_j >= 4, u(2),
                               jnp.where(st_j >= 2, u(1), u(0)))
            edge = jnp.where(j == k, u(0), comp_j)  # (len-1)+1 encoding
            hb = hb | (edge << (2 * j))
        for kk in range(c):
            base = self.hist_off + 3 * kk
            st = vec[base]
            is_k = k == kk
            new_st = jnp.where(
                is_k & putok_case, u(3),  # write done + read in flight
                jnp.where(is_k & getok_case, u(4), st))
            new_vec = new_vec.at[base].set(new_st)
            new_vec = new_vec.at[base + 1].set(
                jnp.where(is_k & getok_case, m_value, vec[base + 1]))
            new_vec = new_vec.at[base + 2].set(
                jnp.where(is_k & putok_case, hb, vec[base + 2]))

        # the Get goes to server (i + 1) % s where i = client actor index
        get_out = self._env(dst=(dst + 1) % s, src=dst, kind=GET,
                            req=(u(1) << 2) | jnp.clip(k, 0, 3).astype(u))
        outs = jnp.full((self.max_out,), EMPTY_ENV, u)
        outs = outs.at[0].set(
            jnp.where(putok_case, get_out, jnp.uint32(EMPTY_ENV)))
        return new_vec, handled, outs

    # -- Properties -------------------------------------------------------

    def device_properties(self):
        s, c = self.S, self.C
        e = self.net_slots
        off = self.net_offset
        thread = jnp.asarray(self._perm_thread)   # [NC, 2c]
        occ = jnp.asarray(self._perm_occ)         # [NC, 2c]
        pos = jnp.asarray(self._perm_pos)         # [NC, c, 2]
        nc = thread.shape[0]

        def value_chosen(vec):
            net = vec[off:off + e]
            kind = (net >> 6) & 15
            value = (net >> 24) & 3
            return jnp.any((net != EMPTY_ENV) & (kind == GETOK)
                           & (value != 0))

        def linearizable(vec):
            status = jnp.stack(
                [vec[self.hist_off + 3 * j] for j in range(c)])     # [c]
            rets = jnp.stack(
                [vec[self.hist_off + 3 * j + 1] for j in range(c)])
            hbs = jnp.stack(
                [vec[self.hist_off + 3 * j + 2] for j in range(c)])
            # Present/in-flight per (thread, op).
            w_completed = status >= 2                               # [c]
            w_inflight = status == 1
            r_completed = status == 4
            r_inflight = status == 3
            ok_any = jnp.zeros((), bool)
            for mask in range(1 << c):
                include = jnp.asarray(
                    [bool((mask >> t) & 1) for t in range(c)])
                # placed[t, kop]: op is serialized in this config
                w_placed = w_completed | (w_inflight & include)     # [c]
                r_placed = r_completed | (r_inflight & include)
                placed = jnp.stack([w_placed, r_placed], axis=1)    # [c, 2]
                # Walk each permutation: register value + validity.
                reg = jnp.zeros((nc,), jnp.uint32)                  # [NC]
                ok = jnp.ones((nc,), bool)
                for p in range(2 * c):
                    t = thread[:, p]                                # [NC]
                    kop = occ[:, p]
                    is_placed = placed[t, kop]
                    is_write = kop == 0
                    # write: reg := value(t) = t+1
                    reg = jnp.where(is_placed & is_write,
                                    (t + 1).astype(jnp.uint32), reg)
                    # completed read: value must match
                    read_done = (kop == 1) & r_completed[t] & is_placed
                    ok = ok & jnp.where(read_done, reg == rets[t], True)
                    # real-time edges for the read op (write edges are
                    # always empty): every peer op at index <= edge-1
                    # must already be serialized (placed before p) —
                    # linearizability.rs:198-206.
                    read_any = (kop == 1) & is_placed
                    for j in range(c):
                        edge = (hbs[t] >> (2 * j)) & 3  # 0 none; else len
                        peer0_later = pos[:, j, 0] > p
                        peer1_later = pos[:, j, 1] > p
                        viol = ((edge >= 1) & peer0_later) | \
                               ((edge >= 2) & peer1_later)
                        ok = ok & jnp.where(read_any & (t != j), ~viol,
                                            True)
                ok_any = ok_any | jnp.any(ok)
            return ok_any

        return {"linearizable": linearizable, "value chosen": value_chosen}

"""Device encoding of the ping-pong fixture (`actor_test_util.rs:4-96`).

The parity workout for the actor-device layer: exercises duplicating and
lossy networks, history recording, boundary pruning, and all three
property expectations against the reference's exact counts
(14 / 4,094 / 11 — `actor/model.rs:547,629,660`).

Lanes:

- ``[0]``, ``[1]`` — per-actor message counters
- ``[2]``, ``[3]`` — history (msgs_in, msgs_out) when maintained
- ``[4 .. 4+E)`` — network slots; ``[4+E]`` — overflow flag

Envelope code: ``value << 3 | kind << 2 | src << 1 | dst`` with kind
Ping=0 / Pong=1.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..actor_device import EMPTY_ENV, ActorDeviceModel

__all__ = ["PingPongDevice"]

_PING, _PONG = 0, 1


class PingPongDevice(ActorDeviceModel):
    max_out = 1

    def __init__(self, cfg, host_module, net_slots: int = 16,
                 duplicating: bool = True, lossy: bool = False):
        self.cfg = cfg
        self._host = host_module
        self.net_slots = net_slots
        self.net_offset = 4
        self.state_width = 4 + net_slots + 1
        self.error_lane = 4 + net_slots
        self.duplicating = duplicating
        self.lossy = lossy

    # -- Envelope codec ---------------------------------------------------

    def env_encode(self, envelope) -> int:
        h = self._host
        msg = envelope.msg
        kind = _PONG if type(msg) is h.Pong else _PING
        return (msg.value << 3) | (kind << 2) \
            | (int(envelope.src) << 1) | int(envelope.dst)

    def env_decode(self, code: int):
        from ...actor import Id
        from ...actor.model_state import Envelope

        h = self._host
        value = code >> 3
        msg = h.Pong(value) if (code >> 2) & 1 else h.Ping(value)
        return Envelope(Id((code >> 1) & 1), Id(code & 1), msg)

    # -- State codec ------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        vec = np.zeros(self.state_width, np.uint32)
        vec[0], vec[1] = state.actor_states
        if self.cfg.maintains_history:
            vec[2], vec[3] = state.history
        net = self.encode_network(state.network)
        vec[4:] = net
        return vec

    def decode(self, vec: np.ndarray):
        from ...actor.model_state import ActorModelState, Network

        history = ((int(vec[2]), int(vec[3]))
                   if self.cfg.maintains_history else (0, 0))
        return ActorModelState(
            actor_states=[int(vec[0]), int(vec[1])],
            network=Network(self.decode_network(vec[4:])),
            is_timer_set=[],
            history=history,
        )

    # -- Delivery (actor_test_util.rs:20-37) ------------------------------

    def deliver(self, body, env):
        dst = env & 1
        src = (env >> 1) & 1
        kind = (env >> 2) & 1
        value = env >> 3
        count = jnp.where(dst == 0, body[0], body[1])
        handled = count == value
        # Pong(v) -> Ping(v+1); Ping(v) -> Pong(v); both reply to src.
        reply_kind = jnp.where(kind == _PONG,
                               jnp.uint32(_PING), jnp.uint32(_PONG))
        reply_value = jnp.where(kind == _PONG, value + 1, value)
        out = ((reply_value << 3) | (reply_kind << 2)
               | (dst << 1) | src).astype(jnp.uint32)
        new_body = body.at[0].set(jnp.where(dst == 0, count + 1, body[0]))
        new_body = new_body.at[1].set(
            jnp.where(dst == 1, count + 1, body[1]))
        if self.cfg.maintains_history:
            # record_msg_in then record_msg_out per send
            # (actor/model.rs:280-300, actor_test_util.rs:64-75).
            new_body = new_body.at[2].set(body[2] + 1)
            new_body = new_body.at[3].set(body[3] + 1)
        outs = jnp.where(handled, out, jnp.uint32(EMPTY_ENV))[None]
        return new_body, handled, outs

    # -- Boundary + properties (actor_test_util.rs:60-95) -----------------

    def boundary(self, vec):
        m = self.cfg.max_nat
        return (vec[0] <= m) & (vec[1] <= m)

    def device_properties(self):
        m = self.cfg.max_nat

        props = {
            "delta within 1": lambda v: (
                jnp.abs(v[0].astype(jnp.int64) - v[1].astype(jnp.int64))
                <= 1),
            "can reach max": lambda v: (v[0] == m) | (v[1] == m),
            "must reach max": lambda v: (v[0] == m) | (v[1] == m),
            "must exceed max": lambda v: (v[0] == m + 1) | (v[1] == m + 1),
        }
        # The history properties exist regardless; with history not
        # maintained the lanes stay (0, 0) and both hold trivially, same
        # as the host model's constant (0, 0) history.
        props["#in <= #out"] = lambda v: v[2] <= v[3]
        props["#out <= #in + 1"] = lambda v: v[3] <= v[2] + 1
        return props

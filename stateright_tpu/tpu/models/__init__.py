"""Device encodings for the bundled example models.

Each module pairs a host example model with its :class:`DeviceModel`:
an injective fixed-width ``uint32`` state encoding plus a jittable
successor function, in the same action order as the host model so the TPU
engine reproduces the reference's exact state-count and discovery parity
gates (BASELINE.md).
"""

"""Device encoding of the racy shared counter (`examples/increment.rs`).

State lanes (``W = 1 + 2*T`` uint32): ``[0]`` = shared counter ``i``;
per thread k, ``[1+2k]`` = local read value ``t``, ``[2+2k]`` = program
counter (1 = about to read, 2 = about to write, 3 = done).

Fan-out: one action per thread, in thread order (matching the host
enumeration `increment.rs:163-171`): read when pc == 1, write when
pc == 2.

The representative sorts threads by their full ``(t, pc)`` pair — an
EXACT canonical form (a thread's contribution is exactly that pair), so
the documented 13 -> 8 reduction at 2 threads (`increment.rs:36-105`)
is traversal-order independent on every engine. The host model's
``sorted(s)`` representative is the same form, so host and device agree.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..device_model import DeviceModel

__all__ = ["IncrementDevice"]


class IncrementDevice(DeviceModel):
    def __init__(self, thread_count: int, host_module):
        self.thread_count = thread_count
        self.state_width = 1 + 2 * thread_count
        self.max_fanout = thread_count
        self._host = host_module

    def native_form(self):
        """Compiled C++ counterpart (``native/host_bfs.cc`` model 5):
        same lanes, fingerprints, and exact thread-sort representative."""
        return (5, [self.thread_count])

    def lane_bits(self):
        """Packed-row layout: the counter and every read value are
        bounded by the thread count (each thread writes exactly once),
        the pc is 1..3."""
        t_bits = max(2, self.thread_count.bit_length())
        return [t_bits] + [t_bits, 2] * self.thread_count

    # -- Codec -----------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        vec = np.zeros(self.state_width, np.uint32)
        vec[0] = state.i
        for k, (t, pc) in enumerate(state.s):
            vec[1 + 2 * k] = t
            vec[2 + 2 * k] = pc
        return vec

    def decode(self, vec: np.ndarray):
        return self._host.IncrementState(
            int(vec[0]),
            tuple((int(vec[1 + 2 * k]), int(vec[2 + 2 * k]))
                  for k in range(self.thread_count)))

    # -- Device transition (increment.rs:163-185) ------------------------

    def step(self, vec):
        i = vec[0]
        succs = []
        valids = []
        for k in range(self.thread_count):
            t = vec[1 + 2 * k]
            pc = vec[2 + 2 * k]
            read = vec.at[1 + 2 * k].set(i).at[2 + 2 * k].set(2)
            write = vec.at[0].set(t + 1).at[2 + 2 * k].set(3)
            succs.append(jnp.where(pc == 1, read, write))
            valids.append((pc == 1) | (pc == 2))
        return jnp.stack(succs), jnp.stack(valids)

    # -- Properties ------------------------------------------------------

    def device_properties(self):
        pcs = [2 + 2 * k for k in range(self.thread_count)]

        def fin(vec):
            done = sum((vec[p] == 3).astype(jnp.uint32) for p in pcs)
            return done == vec[0]

        return {"fin": fin}

    # -- Symmetry (exact: threads are exchangeable (t, pc) pairs) --------

    def representative(self, vec):
        T = self.thread_count
        pairs = vec[1:].reshape(T, 2)
        key = pairs[:, 0] * 4 + pairs[:, 1]  # pc < 4: lexicographic
        order = jnp.argsort(key)
        return jnp.concatenate([vec[:1], pairs[order].reshape(2 * T)])

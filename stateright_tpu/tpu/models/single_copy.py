"""Device form of the single-copy register example.

The simplest register workload (`single-copy-register.rs:18-38`): each
server is one value cell; Put overwrites and acks, Get replies with the
cell. Intentionally NOT linearizable with more than one server — the
device ``linearizable`` predicate finds the counterexample. Built on
:class:`RegisterWorkloadDevice`, which supplies the client, the history
lanes, the envelope codec, and both properties; the server logic below is
the entire per-model surface.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..actor_device import EMPTY_ENV
from ..register_workload import GET, GETOK, PUT, PUTOK, \
    RegisterWorkloadDevice

__all__ = ["SingleCopyDevice"]


class SingleCopyDevice(RegisterWorkloadDevice):
    SERVER_LANES = ("value",)
    max_out = 1

    def native_form(self):
        """Compiled C++ counterpart (``native/host_bfs.cc`` model 3):
        same lanes, envelopes, and fingerprints as this device form."""
        return (3, [self.C, self.S])

    # -- Packed-row layout: one value cell per server; no internal
    # kinds, so the base class's 0-bit extra field is exact.

    def server_lane_bits(self) -> tuple:
        return (max(1, self.C.bit_length()),)  # value index 0..C

    # -- Client symmetry: the server's only client-derived datum is the
    # stored value index (1+k); no internal kinds, so the generic
    # envelope rewrite covers the rest. At 1 server every client shares
    # residue class 0 — the full symmetric group applies.

    def sym_rewrite_servers(self, servers, t, xp):
        val_map = xp.asarray(t["val"])
        return val_map[xp.minimum(servers, self.value_mask)]

    def server_deliver(self, lanes, f):
        u = jnp.uint32
        value = self.lane(lanes, "value")

        put_case = f.kind == PUT
        get_case = f.kind == GET
        handled = put_case | get_case

        new_lanes = self.with_lane(
            lanes, "value", jnp.where(put_case, f.value, value))

        putok = self.build_env(dst=f.src, src=f.dst, kind=PUTOK, req=f.req)
        getok = self.build_env(dst=f.src, src=f.dst, kind=GETOK, req=f.req,
                               value=value)
        reply = jnp.where(put_case, putok,
                          jnp.where(get_case, getok, u(EMPTY_ENV)))
        outs = jnp.full((self.max_out,), EMPTY_ENV, u).at[0].set(reply)
        return new_lanes, handled, outs

    # -- Host codec: server state is the bare value string ---------------

    def encode_server(self, server_state, vec: np.ndarray,
                      base: int) -> None:
        vec[base] = self.value_idx(server_state)

    def decode_server(self, vec: np.ndarray, base: int, server_index: int):
        return self.value_of(int(vec[base]))

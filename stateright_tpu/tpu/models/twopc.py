"""Device encoding of two-phase commit (reference `examples/2pc.rs:43-121`).

State lanes (``W = rm_count + 3`` uint32):

- ``[0, N)``   — per-RM state (WORKING=0, PREPARED=1, COMMITTED=2, ABORTED=3)
- ``[N]``      — TM state (INIT=0, COMMITTED=1, ABORTED=2)
- ``[N+1]``    — TM-prepared bitmask (bit i = RM i observed prepared)
- ``[N+2]``    — message-set bitmask (bit 0 = Commit, bit 1 = Abort,
  bit 2+i = Prepared(i)); the 2pc message *set* is finite and enumerable,
  so the reference's ``HashableHashSet<Message>`` becomes one lane with
  order-insensitivity for free.

Fan-out: ``2 + 5*N`` potential actions per state in the host model's
enumeration order (TmCommit, TmAbort, then per-RM TmRcvPrepared /
RmPrepare / RmChooseToAbort / RmRcvCommitMsg / RmRcvAbortMsg).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..device_model import DeviceModel

__all__ = ["TwoPhaseDevice"]


class TwoPhaseDevice(DeviceModel):
    def __init__(self, rm_count: int, host_module):
        """``host_module`` is the module defining ``TwoPhaseState`` etc.;
        passed in (rather than imported) because examples are plain
        scripts, not an importable package."""
        if rm_count > 28:
            raise ValueError("bitmask encoding supports at most 28 RMs")
        self.rm_count = rm_count
        self.state_width = rm_count + 3
        self.max_fanout = 2 + 5 * rm_count
        self._host = host_module

    def native_form(self):
        """Compiled C++ counterpart (``native/host_bfs.cc`` model 2):
        same lanes and fingerprints; its ``representative`` implements
        the HOST RewritePlan heuristic (665-gate semantics), not this
        class's exact composite-key canonicalization."""
        return (2, [self.rm_count])

    def lane_bits(self):
        """Packed-row layout (tpu/packing.py): 2-bit RM/TM states, an
        N-bit prepared mask, an (N+2)-bit message-set mask — the whole
        7-RM state packs into one word."""
        n = self.rm_count
        return [2] * n + [2, n, n + 2]

    # -- Codec -----------------------------------------------------------

    def encode(self, state) -> np.ndarray:
        n = self.rm_count
        vec = np.zeros(self.state_width, np.uint32)
        for i, s in enumerate(state.rm_state):
            vec[i] = s.value
        vec[n] = state.tm_state.value
        vec[n + 1] = sum(1 << i for i, p in enumerate(state.tm_prepared) if p)
        msgs = 0
        for m in state.msgs:
            if m[0] == "commit":
                msgs |= 1
            elif m[0] == "abort":
                msgs |= 2
            else:  # ("prepared", rm)
                msgs |= 1 << (2 + m[1])
        vec[n + 2] = msgs
        return vec

    def decode(self, vec: np.ndarray):
        h = self._host
        n = self.rm_count
        msgs = set()
        bits = int(vec[n + 2])
        if bits & 1:
            msgs.add(h.COMMIT)
        if bits & 2:
            msgs.add(h.ABORT)
        for i in range(n):
            if (bits >> (2 + i)) & 1:
                msgs.add(h.prepared(i))
        return h.TwoPhaseState(
            rm_state=tuple(h.RmState(int(vec[i])) for i in range(n)),
            tm_state=h.TmState(int(vec[n])),
            tm_prepared=tuple(
                bool((int(vec[n + 1]) >> i) & 1) for i in range(n)),
            msgs=frozenset(msgs),
        )

    # -- Device transition -----------------------------------------------

    def step(self, vec):
        n = self.rm_count
        rm = vec[:n]
        tm = vec[n]
        prep = vec[n + 1]
        msgs = vec[n + 2]
        full = jnp.uint32((1 << n) - 1)
        one = jnp.uint32(1)
        succs = []
        valids = []
        # TmCommit (2pc.rs:56-59)
        succs.append(vec.at[n].set(1).at[n + 2].set(msgs | one))
        valids.append((tm == 0) & (prep == full))
        # TmAbort (2pc.rs:60-63)
        succs.append(vec.at[n].set(2).at[n + 2].set(msgs | jnp.uint32(2)))
        valids.append(tm == 0)
        for i in range(n):
            # TmRcvPrepared(i) (2pc.rs:52-55)
            succs.append(vec.at[n + 1].set(prep | jnp.uint32(1 << i)))
            valids.append((tm == 0) & (((msgs >> (2 + i)) & one) == one))
            # RmPrepare(i) (2pc.rs:64-67)
            succs.append(
                vec.at[i].set(1).at[n + 2].set(msgs | jnp.uint32(1 << (2 + i))))
            valids.append(rm[i] == 0)
            # RmChooseToAbort(i) (2pc.rs:68-70)
            succs.append(vec.at[i].set(3))
            valids.append(rm[i] == 0)
            # RmRcvCommitMsg(i) (2pc.rs:71-73)
            succs.append(vec.at[i].set(2))
            valids.append((msgs & one) == one)
            # RmRcvAbortMsg(i) (2pc.rs:74-76)
            succs.append(vec.at[i].set(3))
            valids.append((msgs & jnp.uint32(2)) == jnp.uint32(2))
        return jnp.stack(succs), jnp.stack(valids)

    # -- Properties (2pc.rs:106-121) -------------------------------------

    def device_properties(self):
        n = self.rm_count

        def abort_agreement(vec):
            return jnp.all(vec[:n] == 3)

        def commit_agreement(vec):
            return jnp.all(vec[:n] == 2)

        def consistent(vec):
            return ~(jnp.any(vec[:n] == 3) & jnp.any(vec[:n] == 2))

        return {
            "abort agreement": abort_agreement,
            "commit agreement": commit_agreement,
            "consistent": consistent,
        }

    # -- Symmetry (2pc.rs:165-182) ---------------------------------------

    def representative(self, vec):
        """EXACT canonicalization: an RM's entire contribution to the
        state is the triple (rm_state, tm_prepared bit, prepared-msg
        bit), so sorting RMs by the packed composite key canonicalizes
        the whole orbit — unlike the host's value-only ``RewritePlan``
        sort (`rewrite_plan.rs:36-49`), ties cannot hide differing
        auxiliary bits. Exactness makes the quotient size
        traversal-order independent (single-device and sharded engines
        count identically) and strictly smaller: 8,832 states -> 314
        orbits at 5 RMs, vs 665 for the reference's heuristic under DFS
        (`2pc.rs:138`). Cheap on device: one tiny sort per state, vmapped
        over the wave."""
        n = self.rm_count
        rm = vec[:n]
        prep = vec[n + 1]
        msgs = vec[n + 2]
        idx = jnp.arange(n, dtype=jnp.uint32)
        prep_bits = (prep >> idx) & 1
        msg_bits = (msgs >> (2 + idx)) & 1
        key = rm * 4 + prep_bits * 2 + msg_bits
        order = jnp.argsort(key)  # equal keys are identical triples
        shifts = jnp.arange(n, dtype=jnp.uint32)
        new_prep = jnp.sum(prep_bits[order] << shifts, dtype=jnp.uint32)
        new_msg_prepared = jnp.sum(msg_bits[order] << shifts,
                                   dtype=jnp.uint32)
        new_msgs = (msgs & jnp.uint32(3)) | (new_msg_prepared << 2)
        return jnp.concatenate([
            rm[order],
            vec[n:n + 1],
            new_prep[None].astype(jnp.uint32),
            new_msgs[None].astype(jnp.uint32),
        ])

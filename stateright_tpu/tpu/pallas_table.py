"""Pallas insert-or-test kernel for the device visited table.

The BASELINE.json north star names an "HBM-resident hash table written
in Pallas" as the visited-set design. The XLA path
(`engine.dedup_and_insert`) runs the probe loop as a ``lax.while_loop``
whose per-round gathers and claim-scatters hit the table at HBM
latency; this kernel stages the whole table into VMEM once, runs every
probe round at VMEM latency, and writes the table back once —
the structure a TPU actually wants for a probe chain (VMEM is ~16 MB
per core, so tables up to 2^20 uint64 entries = 8 MB fit; the engine
falls back to the XLA path above that and at load time when Pallas is
unavailable).

Semantics are bit-identical to ``dedup_and_insert`` (same intra-wave
first-occurrence rule, same ``_TABLE_MIX``/``_STEP_MIX`` double-hash
probe sequence, same claim rule), so counts, discoveries, and
checkpoints are engine-interchangeable; the differential test runs both
paths on the same candidate streams. On the CPU backend the kernel runs
in Pallas interpret mode (``pl.pallas_call(..., interpret=True)``) —
correct but not fast; the TPU lowering is what the hardware session
A/Bs (MEASUREMENTS round-5 plan).

Reference analog: the ``DashMap`` visited set of `bfs.rs:26,245-259`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .hashing import SENTINEL

__all__ = ["PALLAS_AVAILABLE", "pallas_table_capacity_ok",
           "dedup_and_insert_pallas"]

try:  # pallas ships with jax, but keep the engine loadable without it
    from jax.experimental import pallas as pl

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover - jax always bundles pallas here
    pl = None
    PALLAS_AVAILABLE = False

#: tables at or below this capacity fit the kernel's VMEM budget
#: (uint64 entries; 2^20 * 8 B = 8 MB of ~16 MB VMEM)
_MAX_VMEM_CAPACITY = 1 << 20


def pallas_table_capacity_ok(capacity: int) -> bool:
    return PALLAS_AVAILABLE and capacity <= _MAX_VMEM_CAPACITY


def _kernel(capacity: int):
    import numpy as np

    from .engine import _STEP_MIX, _TABLE_MIX

    # Plain numpy scalars: a closed-over traced jnp constant would be
    # rejected by pallas_call ("captures constants").
    sentinel = np.uint64(SENTINEL)
    shift = np.uint64(64 - (capacity.bit_length() - 1))
    slot_mask = np.int32(capacity - 1)

    def kernel(fps_ref, candidate_ref, table_in_ref, new_mask_ref,
               table_out_ref):
        # The intra-wave first-occurrence mask is computed OUTSIDE (an
        # XLA stable sort — sorts don't lower inside TPU kernels); this
        # kernel is pure probe/claim.
        fps = fps_ref[:]
        candidate = candidate_ref[:]
        idx0 = ((fps * np.uint64(_TABLE_MIX)) >> shift).astype(jnp.int32)
        step = (((fps * np.uint64(_STEP_MIX)) >> shift)
                .astype(jnp.int32) | 1)

        # The probe loop runs on the VMEM-staged table value; every
        # round's gather/claim-scatter is VMEM traffic, not HBM.
        table0 = table_in_ref[:]

        def cond(carry):
            _, _, pending, _ = carry
            return pending.any()

        def body(carry):
            table, idx, pending, is_new = carry
            cur = table[idx]
            found = pending & (cur == fps)
            empty = pending & (cur == sentinel)
            table = table.at[jnp.where(empty, idx, capacity)].set(
                fps, mode="drop")
            won = empty & (table[idx] == fps)
            is_new = is_new | won
            pending = pending & ~(found | won)
            idx = jnp.where(pending, (idx + step) & slot_mask, idx)
            return table, idx, pending, is_new

        table, _, _, new_mask = jax.lax.while_loop(
            cond, body,
            (table0, idx0, candidate, jnp.zeros(fps.shape, bool)))
        new_mask_ref[:] = new_mask
        table_out_ref[:] = table

    return kernel


def dedup_and_insert_pallas(dedup_fps, visited, capacity: int,
                            interpret: Optional[bool] = None):
    """Drop-in for ``engine.dedup_and_insert`` behind
    ``table_impl="pallas"``: returns ``(new_mask, new_count, visited)``.

    ``interpret`` defaults to True off-TPU (the kernel still computes
    exactly; only the lowering differs).
    """
    if not pallas_table_capacity_ok(capacity):
        raise ValueError(
            f"pallas table kernel supports capacities <= "
            f"{_MAX_VMEM_CAPACITY} (got {capacity}); use the XLA table")
    from .engine import first_occurrence_candidates

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = dedup_fps.shape[0]
    # Intra-wave first-occurrence stays XLA-side (sorts don't lower
    # inside TPU kernels) and is shared with the XLA table path.
    candidate = first_occurrence_candidates(dedup_fps)
    new_mask, visited = pl.pallas_call(
        _kernel(capacity),
        out_shape=(
            jax.ShapeDtypeStruct((n,), jnp.bool_),
            jax.ShapeDtypeStruct((capacity,), jnp.uint64),
        ),
        input_output_aliases={2: 1},  # table updated in place
        interpret=interpret,
    )(dedup_fps, candidate, visited)
    return new_mask, jnp.sum(new_mask, dtype=jnp.int32), visited

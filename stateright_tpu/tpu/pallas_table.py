"""Pallas kernels for the device wave: visited-table probe and the
single-kernel wave megakernel.

The BASELINE.json north star names an "HBM-resident hash table written
in Pallas" as the visited-set design. The XLA path
(`engine.dedup_and_insert`) runs the probe loop as a ``lax.while_loop``
whose per-round gathers and claim-scatters hit the table at HBM
latency; the round-5/7 kernel (``dedup_and_insert_pallas``) stages the
whole table into VMEM once, runs every probe round at VMEM latency,
and writes the table back once — the structure a TPU actually wants
for a probe chain. The capacity gate derives from the backend's
reported per-core VMEM budget when it exposes one
(``_vmem_budget_bytes``) and falls back to the classic 16 MB
assumption (tables up to 2^20 uint64 entries = 8 MB) otherwise; the
engine degrades to the XLA path above the gate and when Pallas is
unavailable.

Two dedup levels run in the probe kernel (ISSUE 2): the intra-wave
*local dedup* (first-occurrence collapse of duplicate fingerprints
among the B*F candidates) and the global probe. By default the local
pass runs in-kernel against a VMEM scratch table (``fuse_local=True``)
— the GPUexplore observation that duplicate successors should die in
fast local memory before ever touching the global structure — using
the same sort-free scatter-min group resolution as
``engine.first_occurrence_candidates``; ``fuse_local=False`` keeps the
round-5 behavior (mask computed XLA-side, kernel is pure probe/claim)
for A/B and for backends where the fused lowering regresses.

**The wave megakernel (ISSUE 10).** ``build_wave_megakernel`` extends
the probe kernel into the whole successor path: one ``pallas_call``
runs in-kernel unpack of the packed ``uint32[Wp]`` storage rows
(``tpu/packing.py``), vmapped successor expansion (``DeviceModel.
step`` + boundary pruning), fingerprinting (``tpu/hashing.py`` mixes),
the in-VMEM first-occurrence local dedup, the global probe/claim
against the VMEM-staged visited table, and the re-pack of the
successor rows for storage — so between reading the packed batch and
writing the packed survivors, nothing touches HBM but the one table
round trip. ``build_sender_megakernel`` is the table-less front half
(expand → fingerprint → local dedup) the sharded engines run per shard
under ``shard_map``, where the visited table is partitioned and the
probe stays owner-side after the all-to-all.

Semantics are bit-identical to the XLA ladder in every case: the
kernels trace the ENGINE's own ``expand_frontier`` /
``fingerprint_successors`` / ``first_occurrence_candidates`` functions
and the shared probe/claim body (``_probe_claim``) inside the kernel,
so the bit-identity contract has exactly one implementation per stage;
the differential suites (``tests/test_wave_kernel.py``) pin counts,
discoveries, parent maps, and checkpoint payload bytes knob-on vs off
across all four engines. On the CPU backend the kernels run in Pallas
interpret mode (``pl.pallas_call(..., interpret=True)``) — correct but
not fast; the TPU lowering is what the hardware session A/Bs.

Reference analog: the ``DashMap`` visited set of `bfs.rs:26,245-259`
plus the per-worker successor loop of `bfs.rs:75-152`, collapsed into
one device program (the BLEST/GPU-hash-table observation: per-level
BFS work belongs fused next to the table it probes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .hashing import SENTINEL

__all__ = ["PALLAS_AVAILABLE", "pallas_table_capacity_ok",
           "pallas_table_capacity_limit", "dedup_and_insert_pallas",
           "default_interpret", "wave_kernel_ok", "sender_kernel_ok",
           "wave_kernel_bytes", "build_wave_megakernel",
           "build_sender_megakernel"]

try:  # pallas ships with jax, but keep the engine loadable without it
    from jax.experimental import pallas as pl

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover - jax always bundles pallas here
    pl = None
    PALLAS_AVAILABLE = False

#: fallback VMEM capacity gate when the backend does not expose a VMEM
#: budget (uint64 entries; 2^20 * 8 B = 8 MB of the canonical ~16 MB)
_MAX_VMEM_CAPACITY = 1 << 20

#: fraction of the reported VMEM budget the resident table may take —
#: the probe state (fps, candidate mask, indices, steps) and the local
#: dedup scratch must co-reside with it.
_VMEM_TABLE_FRACTION = 0.5

_CAPACITY_LIMIT_CACHE: list = []

#: fraction of the VMEM budget the megakernel's co-resident working set
#: (table + batch + successors + fps + scratch) may take — headroom for
#: the compiler's own spills and double-buffering.
_WAVE_KERNEL_VMEM_FRACTION = 0.9

#: the canonical per-core VMEM assumption when the backend exposes no
#: budget (the same 16 MB the table-fraction gate is derived from).
_FALLBACK_VMEM_BYTES = 16 << 20

_BACKEND_DECISION_CACHE: list = []


def default_interpret() -> bool:
    """Whether pallas kernels on this process's default backend should
    run in interpret mode (every backend but TPU). Cached at module
    level: the backend is a process property, and
    ``dedup_and_insert_pallas`` used to re-derive it through
    ``jax.default_backend()`` on every dispatch-program trace."""
    if not _BACKEND_DECISION_CACHE:
        _BACKEND_DECISION_CACHE.append(jax.default_backend() != "tpu")
    return _BACKEND_DECISION_CACHE[0]


def _vmem_budget_bytes() -> Optional[int]:
    """The per-core VMEM budget, when the backend exposes one. JAX has
    no stable cross-version API for this, so probe the known spellings
    (device attribute, then ``memory_stats()`` keys) and return None —
    caller falls back to the canonical constant — when none answers.
    Note ``jax.local_devices()`` initializes the default backend if
    none exists yet; the engines only reach this from wave-program
    builds (a backend is already live), but a DIRECT call to
    ``pallas_table_capacity_limit()`` before platform selection will
    pin the default backend as a side effect."""
    try:
        device = jax.local_devices()[0]
    except Exception:  # noqa: BLE001 — no backend, no budget
        return None
    for attr in ("vmem_size_bytes", "core_vmem_size_bytes"):
        value = getattr(device, attr, None)
        if value:
            return int(value)
    stats_fn = getattr(device, "memory_stats", None)
    if callable(stats_fn):
        try:
            stats = stats_fn() or {}
        except Exception:  # noqa: BLE001 — some backends raise here
            return None
        for key in ("vmem_size_bytes", "vmem_bytes_limit",
                    "vmem_bytes_reservable_limit"):
            if stats.get(key):
                return int(stats[key])
    return None


def pallas_table_capacity_limit() -> int:
    """Largest table capacity (uint64 entries, power of two) the kernel
    will stage into VMEM: derived from the backend budget when exposed,
    else the canonical ``2^20``. Cached per process — the budget is a
    hardware property, and this is called per wave-program build."""
    if not _CAPACITY_LIMIT_CACHE:
        budget = _vmem_budget_bytes()
        if budget:
            entries = max(1, int(budget * _VMEM_TABLE_FRACTION) // 8)
            limit = 1 << (entries.bit_length() - 1)  # power-of-two floor
            limit = max(limit, 1 << 12)
        else:
            limit = _MAX_VMEM_CAPACITY
        _CAPACITY_LIMIT_CACHE.append(limit)
    return _CAPACITY_LIMIT_CACHE[0]


def pallas_table_capacity_ok(capacity: int) -> bool:
    return PALLAS_AVAILABLE and capacity <= pallas_table_capacity_limit()


def _probe_claim(fps, candidate, table0, capacity: int):
    """The in-kernel global probe/claim loop over a VMEM-staged table
    value, shaped as batched probe *rounds* (arXiv:1712.09494): each
    while-loop round issues exactly ONE contiguous gather across the
    whole candidate block, serving both the claim resolutions deferred
    from the previous round and this round's probes, instead of the
    per-row probe → claim-scatter → verify-gather chain (two gathers a
    round). A row that observes an empty slot enters ``claiming`` and
    scatters its fingerprint at the START of the next round; the same
    round's single gather then tells it whether it won. Same slot/step
    functions and claim-scatter winner rule as ``engine.
    global_insert`` — the one probe implementation both the probe
    kernel and the wave megakernels trace. Returns ``(table,
    new_mask)``."""
    import numpy as np

    from .engine import _STEP_MIX, _TABLE_MIX

    # Plain numpy scalars: a closed-over traced jnp constant would be
    # rejected by pallas_call ("captures constants").
    sentinel = np.uint64(SENTINEL)
    shift = np.uint64(64 - (capacity.bit_length() - 1))
    slot_mask = np.int32(capacity - 1)
    idx0 = ((fps * np.uint64(_TABLE_MIX)) >> shift).astype(jnp.int32)
    step = (((fps * np.uint64(_STEP_MIX)) >> shift)
            .astype(jnp.int32) | 1)

    def cond(carry):
        _, _, pending, _, _ = carry
        # claiming is always a subset of pending (a claim resolves
        # before its row leaves the pending set), so one test suffices.
        return pending.any()

    def body(carry):
        table, idx, pending, claiming, is_new = carry
        # Claim-scatter for the rows that observed an empty slot last
        # round — then ONE gather across the block resolves those
        # claims AND probes every other pending row's current slot.
        table = table.at[jnp.where(claiming, idx, capacity)].set(
            fps, mode="drop")
        cur = table[idx]
        won = claiming & (cur == fps)
        lost = claiming & ~won
        probing = pending & ~claiming
        found = probing & (cur == fps)
        empty = probing & (cur == sentinel)
        is_new = is_new | won
        pending = pending & ~(found | won)
        claiming = empty
        # Losers and occupied-by-other probes advance their chain;
        # empty observers hold the slot index for next round's claim.
        advance = lost | (probing & ~found & ~empty)
        idx = jnp.where(advance, (idx + step) & slot_mask, idx)
        return table, idx, pending, claiming, is_new

    table, _, _, _, new_mask = jax.lax.while_loop(
        cond, body,
        (table0, idx0, candidate, jnp.zeros(fps.shape, bool),
         jnp.zeros(fps.shape, bool)))
    return table, new_mask


def _kernel(capacity: int, fuse_local: bool):
    def kernel(fps_ref, candidate_ref, table_in_ref, new_mask_ref,
               cand_mask_ref, table_out_ref):
        fps = fps_ref[:]
        if fuse_local:
            # Intra-wave first-occurrence against a scratch table in
            # the kernel's VMEM value domain — duplicates die here,
            # before the global table sees them. The ENGINE's function
            # traces directly inside the kernel (jnp ops only, all
            # constants created in-trace), so the bit-identity contract
            # has exactly one implementation.
            from .engine import first_occurrence_candidates

            candidate = first_occurrence_candidates(fps)
        else:
            candidate = candidate_ref[:]
        table, new_mask = _probe_claim(fps, candidate, table_in_ref[:],
                                       capacity)
        new_mask_ref[:] = new_mask
        cand_mask_ref[:] = candidate
        table_out_ref[:] = table

    return kernel


def dedup_and_insert_pallas(dedup_fps, visited, capacity: int,
                            interpret: Optional[bool] = None,
                            fuse_local: bool = True):
    """Drop-in for the ``engine.dedup_impl`` contract behind
    ``table_impl="pallas"``: returns ``(new_mask, new_count, cand_count,
    visited)``.

    ``interpret`` defaults to True off-TPU (the kernel still computes
    exactly; only the lowering differs). ``fuse_local`` runs the
    intra-wave local dedup inside the kernel (VMEM scratch); False
    computes it XLA-side as before — both bit-identical.
    """
    if not pallas_table_capacity_ok(capacity):
        raise ValueError(
            f"pallas table kernel supports capacities <= "
            f"{pallas_table_capacity_limit()} (got {capacity}); use the "
            "XLA table")
    from .engine import first_occurrence_candidates

    if interpret is None:
        interpret = default_interpret()
    n = dedup_fps.shape[0]
    if fuse_local:
        # The kernel ignores this operand; a cheap placeholder keeps the
        # call signature/kernel arity uniform across both variants.
        candidate = jnp.zeros((n,), jnp.bool_)
    else:
        candidate = first_occurrence_candidates(dedup_fps)
    new_mask, cand_mask, visited = pl.pallas_call(
        _kernel(capacity, fuse_local),
        out_shape=(
            jax.ShapeDtypeStruct((n,), jnp.bool_),
            jax.ShapeDtypeStruct((n,), jnp.bool_),
            jax.ShapeDtypeStruct((capacity,), jnp.uint64),
        ),
        input_output_aliases={2: 2},  # table updated in place
        interpret=interpret,
    )(dedup_fps, candidate, visited)
    return (new_mask, jnp.sum(new_mask, dtype=jnp.int32),
            jnp.sum(cand_mask, dtype=jnp.int32), visited)


# -- The single-kernel wave (ISSUE 10) ------------------------------------

def wave_kernel_bytes(batch: int, fanout: int, width: int,
                      row_width: int, capacity: int = 0,
                      extra_bytes: int = 0) -> int:
    """Conservative VMEM bytes the megakernel's working set co-resides
    in: the staged table (``capacity`` entries; 0 for the table-less
    sender variant), the packed batch + its unpacked registers, the
    full successor window in both forms, the fingerprint pairs, the
    probe state, and the first-occurrence scratch (a power-of-two table
    of >= 2S int32 slots). ``extra_bytes`` adds a caller-enumerated
    term — the matmul-wave plan's transition tables plus its widest
    one-hot block (``matmul_wave.plan_bytes``) when the expand stage
    runs in matmul form. Everything is enumerated — the gate compares
    the total against the budget instead of reserving a blanket
    fraction for "the rest" like the table-only gate does."""
    s = batch * fanout
    scratch = 1 << max(int(s - 1).bit_length() + 1, 4)  # >= 2S slots
    return (8 * capacity                       # visited table
            + 4 * batch * (width + row_width)  # batch: packed + registers
            + 4 * s * (width + row_width)      # successors, both forms
            + 16 * s                           # dedup + path fingerprints
            + 8 * s                            # probe idx + step (int32)
            + 16 * s                           # masks / pending lanes
            + 4 * scratch                      # local-dedup scratch
            + extra_bytes)                     # caller extras (matmul)


def _vmem_budget() -> int:
    return _vmem_budget_bytes() or _FALLBACK_VMEM_BYTES


def wave_kernel_ok(capacity: int, batch: int, fanout: int, width: int,
                   row_width: int, extra_bytes: int = 0) -> bool:
    """Whether the full megakernel (with the table staged in VMEM) fits
    this backend at this (batch, capacity). The engines degrade to the
    XLA ladder above the gate — mid-run table growth must never kill a
    checker, exactly like the probe-kernel gate."""
    return (PALLAS_AVAILABLE
            and wave_kernel_bytes(batch, fanout, width, row_width,
                                  capacity, extra_bytes)
            <= _WAVE_KERNEL_VMEM_FRACTION * _vmem_budget())


def sender_kernel_ok(batch: int, fanout: int, width: int,
                     row_width: int, extra_bytes: int = 0) -> bool:
    """The table-less gate for the sharded engines' sender-side kernel
    (expand → fingerprint → local dedup; the partitioned table is
    probed owner-side after the all-to-all)."""
    return (PALLAS_AVAILABLE
            and wave_kernel_bytes(batch, fanout, width, row_width, 0,
                                  extra_bytes)
            <= _WAVE_KERNEL_VMEM_FRACTION * _vmem_budget())


def _wave_front(dm, use_sym: bool, layout, store_rows, valid,
                matmul_plan=None, matmul_tables=None):
    """The kernel-traced front half shared by both megakernels: unpack
    the packed storage rows to register lanes, expand, fingerprint.
    Traces the ENGINE's own functions so every stage has exactly one
    implementation (the bit-identity contract). With ``matmul_plan``
    the expand stage traces ``matmul_wave.matmul_expand`` instead of
    the vmapped ``dm.step`` — in-kernel the one-hot registers live in
    VMEM and the per-action transition tables (``matmul_tables``, one
    kernel operand per key group: a pallas kernel may not close over
    array constants) are exactly the dense operands Mosaic can put on
    the MXU."""
    from .engine import expand_frontier, fingerprint_successors
    from .matmul_wave import matmul_expand

    reg = store_rows if layout is None else layout.unpack(store_rows)
    succ_flat, sflat, _, _ = (
        matmul_expand(dm, matmul_plan, reg, valid,
                      tables=matmul_tables)
        if matmul_plan is not None
        else expand_frontier(dm, reg, valid))
    dedup_fps, path_fps = fingerprint_successors(dm, succ_flat, sflat,
                                                 use_sym)
    succ_store = succ_flat if layout is None else layout.pack(succ_flat)
    return succ_store, dedup_fps, path_fps, sflat


def build_wave_megakernel(dm, batch: int, capacity: int,
                          use_sym: bool = False, layout=None,
                          interpret: Optional[bool] = None,
                          matmul_plan=None):
    """One ``pallas_call`` for the whole successor path of a wave::

        mega(vecs: uint32[B, Wr], valid: bool[B], visited: uint64[C])
          -> (succ_store: uint32[B*F, Wr], path_fps: uint64[B*F],
              sflat: bool[B*F], new_mask: bool[B*F],
              cand_mask: bool[B*F], visited: uint64[C])

    In-kernel stages: unpack (``layout`` — the packed rows are what
    rides HBM; registers exist only in VMEM), vmapped ``dm.step`` +
    boundary pruning, the hashing.py fingerprint mixes, the
    first-occurrence local dedup, the global probe/claim against the
    VMEM-staged table (``_probe_claim``), and the storage re-pack of
    the successor window. Scalar reductions (successor/novel counts,
    terminal rows) and the ladder's K-row compaction stay XLA-side —
    they are cheap and their outputs cross to the host anyway.

    ``visited`` is aliased in-place (the engines' donation contract).
    The caller gates with ``wave_kernel_ok`` first; ``interpret``
    defaults to the cached backend decision (interpret off-TPU)."""
    B, F, W = batch, dm.max_fanout, dm.state_width
    Wr = W if layout is None else layout.packed_width
    S = B * F
    n_tab = 0 if matmul_plan is None else len(matmul_plan.groups)
    if interpret is None:
        interpret = default_interpret()

    def kernel(vecs_ref, valid_ref, table_in_ref, *refs):
        from .engine import first_occurrence_candidates

        tabs = [r[:] for r in refs[:n_tab]] if n_tab else None
        (succ_ref, pfp_ref, sflat_ref, new_mask_ref, cand_mask_ref,
         table_out_ref) = refs[n_tab:]
        succ_store, dedup_fps, path_fps, sflat = _wave_front(
            dm, use_sym, layout, vecs_ref[:], valid_ref[:],
            matmul_plan=matmul_plan, matmul_tables=tabs)
        candidate = first_occurrence_candidates(dedup_fps)
        table, new_mask = _probe_claim(dedup_fps, candidate,
                                       table_in_ref[:], capacity)
        succ_ref[:] = succ_store
        pfp_ref[:] = path_fps
        sflat_ref[:] = sflat
        new_mask_ref[:] = new_mask
        cand_mask_ref[:] = candidate
        table_out_ref[:] = table

    def mega(vecs, valid, visited):
        # The plan's transition tables ride as trailing operands (a
        # pallas kernel may not capture array constants).
        tabs = ([jnp.asarray(g.table) for g in matmul_plan.groups]
                if n_tab else [])
        return pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((S, Wr), jnp.uint32),
                jax.ShapeDtypeStruct((S,), jnp.uint64),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((capacity,), jnp.uint64),
            ),
            input_output_aliases={2: 5},  # table updated in place
            interpret=interpret,
        )(vecs, valid, visited, *tabs)

    return mega


def build_sender_megakernel(dm, batch: int, use_sym: bool = False,
                            layout=None, local_dedup: bool = True,
                            interpret: Optional[bool] = None,
                            matmul_plan=None):
    """The sharded engines' per-shard kernel — the megakernel's front
    half, no table::

        sender(vecs: uint32[B, Wr], valid: bool[B])
          -> (succ_store: uint32[B*F, Wr], dedup_fps: uint64[B*F],
              path_fps: uint64[B*F], sflat: bool[B*F],
              send_mask: bool[B*F])

    ``dedup_fps`` drives the owner routing of the all-to-all;
    ``send_mask`` is the sender-side first-occurrence mask when
    ``local_dedup`` (the ``exchange_novel_only`` contract) and plainly
    ``sflat`` otherwise. The global probe/claim stays owner-side (the
    visited table is partitioned across the mesh). Runs per shard
    under ``shard_map``; gate with ``sender_kernel_ok``."""
    B, F, W = batch, dm.max_fanout, dm.state_width
    Wr = W if layout is None else layout.packed_width
    S = B * F
    n_tab = 0 if matmul_plan is None else len(matmul_plan.groups)
    if interpret is None:
        interpret = default_interpret()

    def kernel(vecs_ref, valid_ref, *refs):
        from .engine import first_occurrence_candidates

        tabs = [r[:] for r in refs[:n_tab]] if n_tab else None
        (succ_ref, dfp_ref, pfp_ref, sflat_ref,
         send_ref) = refs[n_tab:]
        succ_store, dedup_fps, path_fps, sflat = _wave_front(
            dm, use_sym, layout, vecs_ref[:], valid_ref[:],
            matmul_plan=matmul_plan, matmul_tables=tabs)
        send = (first_occurrence_candidates(dedup_fps) if local_dedup
                else sflat)
        succ_ref[:] = succ_store
        dfp_ref[:] = dedup_fps
        pfp_ref[:] = path_fps
        sflat_ref[:] = sflat
        send_ref[:] = send

    def sender(vecs, valid):
        tabs = ([jnp.asarray(g.table) for g in matmul_plan.groups]
                if n_tab else [])
        return pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((S, Wr), jnp.uint32),
                jax.ShapeDtypeStruct((S,), jnp.uint64),
                jax.ShapeDtypeStruct((S,), jnp.uint64),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
                jax.ShapeDtypeStruct((S,), jnp.bool_),
            ),
            interpret=interpret,
        )(vecs, valid, *tabs)

    return sender

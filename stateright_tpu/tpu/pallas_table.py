"""Pallas insert-or-test kernel for the device visited table.

The BASELINE.json north star names an "HBM-resident hash table written
in Pallas" as the visited-set design. The XLA path
(`engine.dedup_and_insert`) runs the probe loop as a ``lax.while_loop``
whose per-round gathers and claim-scatters hit the table at HBM
latency; this kernel stages the whole table into VMEM once, runs every
probe round at VMEM latency, and writes the table back once —
the structure a TPU actually wants for a probe chain. The capacity
gate derives from the backend's reported per-core VMEM budget when it
exposes one (``_vmem_budget_bytes``) and falls back to the classic
16 MB assumption (tables up to 2^20 uint64 entries = 8 MB) otherwise;
the engine degrades to the XLA path above the gate and when Pallas is
unavailable.

Two dedup levels run here (ISSUE 2): the intra-wave *local dedup*
(first-occurrence collapse of duplicate fingerprints among the B*F
candidates) and the global probe. By default the local pass runs
in-kernel against a VMEM scratch table (``fuse_local=True``) — the
GPUexplore observation that duplicate successors should die in fast
local memory before ever touching the global structure — using the
same sort-free scatter-min group resolution as
``engine.first_occurrence_candidates``; ``fuse_local=False`` keeps the
round-5 behavior (mask computed XLA-side, kernel is pure probe/claim)
for A/B and for backends where the fused lowering regresses.

Semantics are bit-identical to ``dedup_and_insert`` either way (same
first-occurrence rule, same ``_TABLE_MIX``/``_STEP_MIX`` double-hash
probe sequence, same claim rule), so counts, discoveries, and
checkpoints are engine-interchangeable; the differential suites run
all paths on the same candidate streams. On the CPU backend the kernel
runs in Pallas interpret mode (``pl.pallas_call(..., interpret=True)``)
— correct but not fast; the TPU lowering is what the hardware session
A/Bs (MEASUREMENTS round-5 plan).

Reference analog: the ``DashMap`` visited set of `bfs.rs:26,245-259`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .hashing import SENTINEL

__all__ = ["PALLAS_AVAILABLE", "pallas_table_capacity_ok",
           "pallas_table_capacity_limit", "dedup_and_insert_pallas"]

try:  # pallas ships with jax, but keep the engine loadable without it
    from jax.experimental import pallas as pl

    PALLAS_AVAILABLE = True
except ImportError:  # pragma: no cover - jax always bundles pallas here
    pl = None
    PALLAS_AVAILABLE = False

#: fallback VMEM capacity gate when the backend does not expose a VMEM
#: budget (uint64 entries; 2^20 * 8 B = 8 MB of the canonical ~16 MB)
_MAX_VMEM_CAPACITY = 1 << 20

#: fraction of the reported VMEM budget the resident table may take —
#: the probe state (fps, candidate mask, indices, steps) and the local
#: dedup scratch must co-reside with it.
_VMEM_TABLE_FRACTION = 0.5

_CAPACITY_LIMIT_CACHE: list = []


def _vmem_budget_bytes() -> Optional[int]:
    """The per-core VMEM budget, when the backend exposes one. JAX has
    no stable cross-version API for this, so probe the known spellings
    (device attribute, then ``memory_stats()`` keys) and return None —
    caller falls back to the canonical constant — when none answers.
    Note ``jax.local_devices()`` initializes the default backend if
    none exists yet; the engines only reach this from wave-program
    builds (a backend is already live), but a DIRECT call to
    ``pallas_table_capacity_limit()`` before platform selection will
    pin the default backend as a side effect."""
    try:
        device = jax.local_devices()[0]
    except Exception:  # noqa: BLE001 — no backend, no budget
        return None
    for attr in ("vmem_size_bytes", "core_vmem_size_bytes"):
        value = getattr(device, attr, None)
        if value:
            return int(value)
    stats_fn = getattr(device, "memory_stats", None)
    if callable(stats_fn):
        try:
            stats = stats_fn() or {}
        except Exception:  # noqa: BLE001 — some backends raise here
            return None
        for key in ("vmem_size_bytes", "vmem_bytes_limit",
                    "vmem_bytes_reservable_limit"):
            if stats.get(key):
                return int(stats[key])
    return None


def pallas_table_capacity_limit() -> int:
    """Largest table capacity (uint64 entries, power of two) the kernel
    will stage into VMEM: derived from the backend budget when exposed,
    else the canonical ``2^20``. Cached per process — the budget is a
    hardware property, and this is called per wave-program build."""
    if not _CAPACITY_LIMIT_CACHE:
        budget = _vmem_budget_bytes()
        if budget:
            entries = max(1, int(budget * _VMEM_TABLE_FRACTION) // 8)
            limit = 1 << (entries.bit_length() - 1)  # power-of-two floor
            limit = max(limit, 1 << 12)
        else:
            limit = _MAX_VMEM_CAPACITY
        _CAPACITY_LIMIT_CACHE.append(limit)
    return _CAPACITY_LIMIT_CACHE[0]


def pallas_table_capacity_ok(capacity: int) -> bool:
    return PALLAS_AVAILABLE and capacity <= pallas_table_capacity_limit()


def _kernel(capacity: int, fuse_local: bool):
    import numpy as np

    from .engine import _STEP_MIX, _TABLE_MIX

    # Plain numpy scalars: a closed-over traced jnp constant would be
    # rejected by pallas_call ("captures constants").
    sentinel = np.uint64(SENTINEL)
    shift = np.uint64(64 - (capacity.bit_length() - 1))
    slot_mask = np.int32(capacity - 1)

    def kernel(fps_ref, candidate_ref, table_in_ref, new_mask_ref,
               cand_mask_ref, table_out_ref):
        fps = fps_ref[:]
        if fuse_local:
            # Intra-wave first-occurrence against a scratch table in
            # the kernel's VMEM value domain — duplicates die here,
            # before the global table sees them. The ENGINE's function
            # traces directly inside the kernel (jnp ops only, all
            # constants created in-trace), so the bit-identity contract
            # has exactly one implementation.
            from .engine import first_occurrence_candidates

            candidate = first_occurrence_candidates(fps)
        else:
            candidate = candidate_ref[:]
        idx0 = ((fps * np.uint64(_TABLE_MIX)) >> shift).astype(jnp.int32)
        step = (((fps * np.uint64(_STEP_MIX)) >> shift)
                .astype(jnp.int32) | 1)

        # The probe loop runs on the VMEM-staged table value; every
        # round's gather/claim-scatter is VMEM traffic, not HBM.
        table0 = table_in_ref[:]

        def cond(carry):
            _, _, pending, _ = carry
            return pending.any()

        def body(carry):
            table, idx, pending, is_new = carry
            cur = table[idx]
            found = pending & (cur == fps)
            empty = pending & (cur == sentinel)
            table = table.at[jnp.where(empty, idx, capacity)].set(
                fps, mode="drop")
            won = empty & (table[idx] == fps)
            is_new = is_new | won
            pending = pending & ~(found | won)
            idx = jnp.where(pending, (idx + step) & slot_mask, idx)
            return table, idx, pending, is_new

        table, _, _, new_mask = jax.lax.while_loop(
            cond, body,
            (table0, idx0, candidate, jnp.zeros(fps.shape, bool)))
        new_mask_ref[:] = new_mask
        cand_mask_ref[:] = candidate
        table_out_ref[:] = table

    return kernel


def dedup_and_insert_pallas(dedup_fps, visited, capacity: int,
                            interpret: Optional[bool] = None,
                            fuse_local: bool = True):
    """Drop-in for the ``engine.dedup_impl`` contract behind
    ``table_impl="pallas"``: returns ``(new_mask, new_count, cand_count,
    visited)``.

    ``interpret`` defaults to True off-TPU (the kernel still computes
    exactly; only the lowering differs). ``fuse_local`` runs the
    intra-wave local dedup inside the kernel (VMEM scratch); False
    computes it XLA-side as before — both bit-identical.
    """
    if not pallas_table_capacity_ok(capacity):
        raise ValueError(
            f"pallas table kernel supports capacities <= "
            f"{pallas_table_capacity_limit()} (got {capacity}); use the "
            "XLA table")
    from .engine import first_occurrence_candidates

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = dedup_fps.shape[0]
    if fuse_local:
        # The kernel ignores this operand; a cheap placeholder keeps the
        # call signature/kernel arity uniform across both variants.
        candidate = jnp.zeros((n,), jnp.bool_)
    else:
        candidate = first_occurrence_candidates(dedup_fps)
    new_mask, cand_mask, visited = pl.pallas_call(
        _kernel(capacity, fuse_local),
        out_shape=(
            jax.ShapeDtypeStruct((n,), jnp.bool_),
            jax.ShapeDtypeStruct((n,), jnp.bool_),
            jax.ShapeDtypeStruct((capacity,), jnp.uint64),
        ),
        input_output_aliases={2: 2},  # table updated in place
        interpret=interpret,
    )(dedup_fps, candidate, visited)
    return (new_mask, jnp.sum(new_mask, dtype=jnp.int32),
            jnp.sum(cand_mask, dtype=jnp.int32), visited)

"""``DeviceModel``: the contract a model satisfies to run on the TPU engine.

The reference accepts arbitrary Rust closures as transition functions
(`lib.rs:155-237`); XLA cannot. A model opts into the TPU engine by
supplying a *device form*: a fixed-width ``uint32`` encoding of its states
plus a jittable successor function with a static maximum fan-out and a
validity mask (the device analog of actions returning ``None`` /
``within_boundary`` pruning). The host ``Model`` remains the source of
truth for path reconstruction, formatting, and the explorer; the engine
checks that both agree via the shared encoding.

Conventions:

- A state is ``uint32[state_width]``; the encoding must be *injective*
  (distinct states -> distinct vectors), since device identity is a hash of
  the vector.
- ``step(vec) -> (succ, valid)`` with ``succ: uint32[max_fanout,
  state_width]`` and ``valid: bool[max_fanout]``. Row ``i`` corresponds to
  the i-th action in the *same order the host model enumerates actions*, so
  device BFS visits states in the same level order as the host BFS — this
  is what makes the exact state-count/discovery parity gates of
  BASELINE.md reproducible on device. Invalid rows may contain garbage.
- ``device_properties()`` maps property names (matching
  ``Model.properties()``) to jittable predicates ``uint32[W] -> bool``.
  Properties without a device predicate fall back to host evaluation on
  decoded states (correct but slow; the engine warns once).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

__all__ = ["DeviceModel", "DeviceFormUnavailable"]


class DeviceFormUnavailable(NotImplementedError):
    """This model configuration exceeds what the device encoding can
    express (e.g. a register workload beyond the statically enumerated
    client bound). ``spawn_tpu_bfs`` catches this and falls back to the
    host BFS engine with a warning, so CLI/bench configurations above
    the device caps still run end to end."""


class DeviceModel:
    """The device form of a :class:`~stateright_tpu.model.Model`."""

    #: number of uint32 lanes per encoded state
    state_width: int
    #: static maximum number of actions per state
    max_fanout: int
    #: lane index that must stay 0; a nonzero value in any generated state
    #: makes the engine raise (used for encoding-capacity overflows, e.g.
    #: a bounded network exceeding its slots). None disables the check.
    error_lane: Optional[int] = None

    # -- Host-side codec -------------------------------------------------

    def encode(self, state) -> np.ndarray:
        """Encodes a host state as ``uint32[state_width]`` (injective)."""
        raise NotImplementedError

    def decode(self, vec: np.ndarray):
        """Decodes an encoded state back to the host representation."""
        raise NotImplementedError

    # -- Device-side (jittable, per single state vector) -----------------

    def step(self, vec):
        """``uint32[W] -> (uint32[max_fanout, W], bool[max_fanout])``.

        Successor states for every potential action plus a validity mask.
        Must be a pure JAX function (it is ``vmap``-ed over the frontier
        and compiled once per frontier shape).
        """
        raise NotImplementedError

    def device_properties(self) -> Dict[str, Callable]:
        """Jittable predicates ``uint32[W] -> bool`` keyed by property name."""
        return {}

    def lane_bits(self):
        """Per-lane bit widths of the encoding, for the packed storage
        row format (``tpu/packing.py``): a sequence of ``state_width``
        specs, each an int ``b`` (values fit ``b`` bits) or a
        ``(b, sentinel)`` pair for lanes with one out-of-band sentinel
        value (e.g. an actor network slot's ``EMPTY_ENV``). The declared
        widths are part of the encoding contract, like injectivity: a
        value beyond its lane's width would be silently truncated in
        the packed arena. ``None`` (the conservative default) means 32
        bits per lane — the engines then store rows unpacked.

        The declared widths also bound the matmul-wave transition
        compiler (``tpu/matmul_wave.py``): lane domains come straight
        from these bits, so only models with small plain-int lanes (no
        sentinels, every lane within ``LANE_DOMAIN_CAP``) are
        candidates for the compiled matmul expand path — the same
        declaration feeds both the packed arena and the regularity
        gate."""
        return None

    def boundary(self, vec) -> Optional[object]:
        """``uint32[W] -> bool``: device analog of ``within_boundary``.

        Return ``None`` (the default, checked at trace time) when every
        successor produced by ``step`` is already within the boundary.
        """
        return None

    def representative(self, vec):
        """``uint32[W] -> uint32[W]``: canonical member of the state's
        symmetry equivalence class (device analog of `representative.rs:65`).

        Used for visited-set dedup only when the builder enables symmetry;
        paths keep original-state fingerprints (the `dfs.rs:258-267` rule).
        Default: identity-free ``None`` meaning symmetry is unsupported.
        """
        return None

    def native_form(self):
        """``(model_id, cfg)`` of this model's compiled C++ counterpart in
        ``native/host_bfs.cc``, or ``None`` (the default) when the model
        has no native form. The native model must use this exact encoding
        (it is differentially tested against ``step``), which lets
        ``spawn_native_bfs`` share fingerprints with the device engines.
        """
        return None

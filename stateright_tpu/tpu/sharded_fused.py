"""Fused multi-chip BFS: per-shard device arenas + in-loop all-to-all.

``ShardedTpuBfsChecker`` routes each wave through the host (per-shard
batch assembly up, per-shard survivor blocks down), so multi-chip wall
time inherits the same host-boundary tax the fused single-chip engine
removed. This engine keeps the whole checker state device-resident *per
shard* and runs up to ``waves_per_dispatch`` waves per dispatch:

- **Per-shard arena**: shard ``i`` owns fingerprints with
  ``fp % n == i`` and appends every state it owns to its local arena
  (vecs/fps/parent-fps/ebits) — rows ``[head_i, tail_i)`` are its
  frontier share. Ownership doubles as load balancing, exactly like the
  unfused engine.
- **In-loop shuffle**: each wave, every shard expands its share,
  fingerprints successors, buckets them by owner, and one
  ``lax.all_to_all`` (ICI on a TPU slice) routes them home, where the
  owner dedups against its local table slice and appends survivors —
  all inside one ``lax.while_loop`` under ``shard_map``.
- **Lockstep stop conditions**: every shard computes identical global
  predicates (``psum`` of live rows / successor counts, ``pmax`` of
  arena/table occupancy, replicated discovery slots), so the loop stays
  collectively synchronized and exits together — growth and checkpoints
  then happen between dispatches, at rest.
- **Shard-major discovery order**: per wave, each shard proposes its
  first-hit fingerprint per property; an ``all_gather`` picks the lowest
  shard index with a hit — the same identity the unfused sharded engine
  derives on the host from its concatenated batch, preserved here so the
  two engines are discovery-identical (and, like the reference's
  multithreaded BFS, not guaranteed shortest: `checker.rs:115-118`).

Host-per-dispatch traffic is one packed per-shard stats array; parent
rows are fetched lazily, as in the single-chip fused engine.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import shard_map

from ..model import Expectation
from ..resilience.membership import EpochOwnership, OwnerMap
from .engine import (compaction_order, dedup_and_insert, dedup_impl,
                     eval_properties, expand_frontier,
                     fingerprint_successors, first_occurrence_candidates,
                     host_table_insert, matmul_expand, pick_bucket,
                     sender_kernel_impl)
from .fused import (FusedTpuBfsChecker, ST_CAND, ST_DISC, ST_ERR, ST_HEAD,
                    ST_OCC, ST_SUCC, ST_TAIL, ST_TARGET, ST_WAVES, _pow2,
                    _releasing)
from .hashing import SENTINEL

__all__ = ["ShardedFusedTpuBfsChecker"]


class ShardedFusedTpuBfsChecker(EpochOwnership, FusedTpuBfsChecker):
    """The fused engine over a device mesh. ``batch_size`` is per shard.

    ``exchange_novel_only`` (default on): run the intra-wave local dedup
    on the sender side, before the in-loop all-to-all, so duplicate
    successors die in their producer's local pass instead of riding the
    interconnect (same rule and bit-identity argument as the classic
    sharded engine)."""

    _ENGINE_ID = "sharded_fused"

    def __init__(self, builder, batch_size: int = 512,
                 mesh: Optional[Mesh] = None,
                 exchange_novel_only: Optional[bool] = None, **kwargs):
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), ("shard",))
        self._mesh = mesh
        self._n = mesh.devices.size
        # Epoch-versioned ownership (resilience.membership): identity
        # assignment unless remapped at a rest point; the dispatch
        # cache is epoch-keyed, exactly like the unfused engine.
        self._owner_map = OwnerMap.identity(self._n)
        self._exchange_novel = (True if exchange_novel_only is None
                                else bool(exchange_novel_only))
        if kwargs.get("table_impl") == "pallas":
            import warnings

            warnings.warn(
                "the sharded engines run the XLA visited table; "
                "table_impl='pallas' is single-device for now",
                RuntimeWarning, stacklevel=2)
            kwargs["table_impl"] = "xla"
        super().__init__(builder, batch_size=batch_size, **kwargs)

    # -- Sharded device state ---------------------------------------------

    def _shard_spec(self):
        return NamedSharding(self._mesh, P("shard"))

    def _new_table(self, fps) -> jax.Array:
        """[n * capacity] visited table — shard ``i``'s slice is an
        open-addressing table over its owned fingerprints
        (``fp % n == i``). Sharded arrays stay flat on the shard axis so
        every ``shard_map`` local view is exactly one shard's block."""
        n, cap = self._n, self._capacity
        table = np.full((n, cap), SENTINEL, np.uint64)
        buckets: list = [[] for _ in range(n)]
        for fp in fps:
            buckets[self._owner(int(fp))].append(fp)
        for i, bucket in enumerate(buckets):
            host_table_insert(table[i], np.fromiter(
                (int(f) for f in bucket), np.uint64, len(bucket)))
        self._seed_occ = [len(b) for b in buckets]
        self._resident = len(fps)
        return jax.device_put(table.reshape(n * cap), self._shard_spec())

    def _table_bytes(self, capacity: int) -> int:
        # Capacity is PER SHARD; the device footprint is the mesh's.
        return self._n * capacity * 8

    # The single-kernel wave here is the table-less per-shard sender
    # megakernel; the base _kernel_path gates on this.
    _SENDER_KERNEL = True

    def _roll_fn(self, ucap: int, dtype, width: int = 0):
        """Per-shard arena-span shift under ``shard_map``: each shard's
        local slice rolls down by ITS OWN head (the shifts ride in a
        sharded [n] array), so every shard's live window lands at its
        slice base."""
        key = ("roll", ucap, str(dtype), width)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached

        def roll_local(arr, shift):
            return jnp.roll(arr, -shift[0], axis=0)

        sharded = shard_map(
            roll_local, mesh=self._mesh,
            in_specs=(P("shard"), P("shard")), out_specs=P("shard"),
            check_vma=False)
        jitted = jax.jit(sharded, donate_argnums=(0,))
        spec = self._shard_spec()
        n = self._n
        shape = ((n * ucap, width) if width else (n * ucap,))
        jitted = self._aot(jitted, (
            jax.ShapeDtypeStruct(shape, dtype, sharding=spec),
            jax.ShapeDtypeStruct((n,), jnp.int64, sharding=spec)))
        self._wave_cache[key] = jitted
        return jitted

    # -- Dispatch program --------------------------------------------------

    def _dispatch_fn(self, batch: int, capacity: int, ucap: int):
        key = ("sharded-dispatch", batch, capacity, ucap,
               self._owner_map.epoch)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached
        dm = self._dm
        mesh = self._mesh
        n = self._n
        B, F, W, K = batch, self._F, self._W, self._K
        Wr = self._Wrow
        layout = self._wave_layout()
        S = B * F        # successors produced per shard per wave
        CAP = S          # per-destination bucket capacity (worst case)
        R = n * CAP      # rows a shard can receive per wave
        prop_fns = list(self._prop_fns)
        use_sym = self._use_symmetry
        exchange_novel = self._exchange_novel
        properties = self._properties
        Pn = len(properties)
        sentinel = jnp.uint64(SENTINEL)
        err_lane = dm.error_lane
        dedup = dedup_impl(self._table_impl, capacity)
        # Single-kernel wave (ISSUE 10): the per-shard sender megakernel
        # inside the device-resident multi-wave loop — each shard's
        # front half (unpack → expand → fingerprint → sender-side local
        # dedup → re-pack) is one pallas_call per wave; the owner-side
        # probe stays on the partitioned XLA table after the in-loop
        # all-to-all.
        sender = sender_kernel_impl(self._wave_kernel_on, dm, B,
                                    use_sym, layout, exchange_novel,
                                    matmul_plan=self._matmul_plan)
        # Ownership assignment baked into the compiled dispatch (the
        # cache key carries the epoch); identity keeps the raw modulo.
        assign = (None if self._owner_map.is_identity
                  else jnp.asarray(
                      np.asarray(self._owner_map.assignment(),
                                 np.int32)))

        def propose_first(hit, bfps):
            """This shard's (has-hit, first-hit fp) for one property."""
            row = jnp.argmax(hit)
            return hit.any(), bfps[row]

        def combine_first(disc_i, has, fp):
            """Lowest shard index with a hit wins — the shard-major
            order of the unfused engine's concatenated batch."""
            all_has = jax.lax.all_gather(has, "shard")   # [n]
            all_fp = jax.lax.all_gather(fp, "shard")     # [n]
            winner = jnp.argmax(all_has)                 # first True
            found = all_has.any()
            return jnp.where((disc_i == sentinel) & found,
                             all_fp[winner], disc_i)

        def wave(carry):
            (vecs_a, fps_a, par_a, eb_a, visited, head, tail, occ,
             succ_total, cand_total, err, disc, waves, target) = carry
            # Local frontier slice (scalars head/tail are per shard).
            idx = head + jnp.arange(B, dtype=jnp.int64)
            valid = idx < tail
            idx_c = jnp.minimum(idx, ucap - 1)
            # Per-shard arenas store PACKED rows; unpack for compute.
            bstore = vecs_a[idx_c]
            bvecs = bstore
            if layout is not None:
                bvecs = layout.unpack(bstore)
            bfps = fps_a[idx_c]
            bebits = eb_a[idx_c]

            conds = eval_properties(prop_fns, bvecs)
            for i, prop in enumerate(properties):
                if prop.expectation is Expectation.ALWAYS:
                    hit = valid & ~conds[i]
                elif prop.expectation is Expectation.SOMETIMES:
                    hit = valid & conds[i]
                else:
                    continue
                disc = disc.at[i].set(
                    combine_first(disc[i], *propose_first(hit, bfps)))

            if sender is not None:
                (succ_store, dedup_fps, path_fps, sflat,
                 send_mask) = sender(bstore, valid)
                succ_count = jnp.sum(sflat, dtype=jnp.int64)
                terminal = valid & ~sflat.reshape(B, F).any(axis=1)
            else:
                succ_flat, sflat, succ_count, terminal = (
                    matmul_expand(dm, self._matmul_plan, bvecs, valid)
                    if self._matmul_plan is not None
                    else expand_frontier(dm, bvecs, valid))
                dedup_fps, path_fps = fingerprint_successors(
                    dm, succ_flat, sflat, use_sym)
            parent_fps = jnp.repeat(bfps, F)

            cleared = bebits
            for i, prop in enumerate(properties):
                if prop.expectation is Expectation.EVENTUALLY:
                    cleared = cleared & ~jnp.where(
                        conds[i], jnp.uint32(1 << i), jnp.uint32(0))
            for i, prop in enumerate(properties):
                if prop.expectation is Expectation.EVENTUALLY:
                    hit = valid & terminal & ((cleared >> i) & 1
                                              ).astype(bool)
                    disc = disc.at[i].set(
                        combine_first(disc[i], *propose_first(hit, bfps)))
            child_ebits = jnp.repeat(cleared, F)

            # Bucket successors by owner and route them home (one ICI
            # all-to-all per wave, as in the unfused engine). With
            # exchange_novel_only, sender-side local dedup thins the
            # candidate stream first (same-shard later duplicates could
            # never win the owner's first-occurrence rule anyway).
            if sender is None:
                if exchange_novel:
                    send_mask = first_occurrence_candidates(dedup_fps)
                else:
                    send_mask = sflat
            part = (dedup_fps % n).astype(jnp.int32)
            dest = part if assign is None else assign[part]
            owner = jnp.where(send_mask, dest, n)
            order = jnp.argsort(owner, stable=True)
            so = owner[order]
            starts = jnp.searchsorted(so, jnp.arange(n + 1))
            rank = jnp.arange(S) - starts[jnp.clip(so, 0, n)]
            slot = so * CAP + rank   # invalid bucket rows drop

            def scatter(x, fill):
                out = jnp.full((n * CAP,) + x.shape[1:], fill, x.dtype)
                return out.at[slot].set(x[order], mode="drop")

            a2a = partial(jax.lax.all_to_all, axis_name="shard",
                          split_axis=0, concat_axis=0, tiled=True)
            # Pack before the in-loop exchange: the ICI moves Wr words
            # per state, and the owner appends the received rows to its
            # arena without ever unpacking them. (The sender megakernel
            # already emitted storage rows.)
            if sender is None:
                succ_store = (succ_flat if layout is None
                              else layout.pack(succ_flat))
            recv_vecs = a2a(scatter(succ_store, 0).reshape(
                n, CAP, Wr)).reshape(R, Wr)
            recv_dedup = a2a(scatter(dedup_fps, sentinel).reshape(
                n, CAP)).reshape(R)
            recv_path = a2a(scatter(path_fps, sentinel).reshape(
                n, CAP)).reshape(R)
            recv_parent = a2a(scatter(parent_fps, sentinel).reshape(
                n, CAP)).reshape(R)
            recv_ebits = a2a(scatter(child_ebits, 0).reshape(
                n, CAP)).reshape(R)

            new_mask, new_count, cand_count, visited = dedup(
                recv_dedup, visited)
            comp = compaction_order(new_mask)

            # Full-window append on purpose: a cond-narrowed window
            # breaks the donated arena's in-place aliasing (see the
            # single-chip fused wave).
            new_vecs = recv_vecs[comp]
            if err_lane is not None:
                # Rows are packed here; extract just the error lane
                # from the packed words (no full unpack).
                err_col = (new_vecs[:, err_lane] if layout is None
                           else layout.lane(new_vecs, err_lane))
                err = err | jnp.any((err_col != 0)
                                    & (jnp.arange(R) < new_count))
            vecs_a = jax.lax.dynamic_update_slice(
                vecs_a, new_vecs, (tail, jnp.int64(0)))
            fps_a = jax.lax.dynamic_update_slice(
                fps_a, recv_path[comp], (tail,))
            par_a = jax.lax.dynamic_update_slice(
                par_a, recv_parent[comp], (tail,))
            eb_a = jax.lax.dynamic_update_slice(
                eb_a, recv_ebits[comp], (tail,))

            nc = new_count.astype(jnp.int64)
            succ_all = jax.lax.psum(succ_count, "shard")
            cand_all = jax.lax.psum(cand_count.astype(jnp.int64), "shard")
            return (vecs_a, fps_a, par_a, eb_a, visited,
                    jnp.minimum(head + B, tail), tail + nc, occ + nc,
                    succ_total + succ_all, cand_total + cand_all, err,
                    disc, waves + 1, target)

        def cond(carry):
            (_, _, _, _, _, head, tail, occ, succ_total, _cand, err,
             disc, waves, target) = carry
            # Every operand is either replicated (succ_total, disc,
            # waves, target) or globally reduced, so all shards agree.
            live = jax.lax.psum(tail - head, "shard")
            worst_tail = jax.lax.pmax(tail, "shard")
            worst_occ = jax.lax.pmax(occ, "shard")
            any_err = jax.lax.pmax(err.astype(jnp.int32), "shard") > 0
            more = (waves < K) & (live > 0) & ~any_err
            more = more & (worst_tail + R <= ucap)
            more = more & (worst_occ + R <= capacity // 2)
            if Pn:
                more = more & ~jnp.all(disc != sentinel)
            return more & (succ_total < target)

        def local(vecs_a, fps_a, par_a, eb_a, visited, disc, stats_in):
            # Per-shard views: vecs_a [U, W], visited [capacity],
            # stats_in [1, L] (this shard's head/tail/occ/err +
            # replicated succ_total/target), disc [P] replicated. The
            # ST_* row layout is identical on input and output so a
            # successor dispatch chains on this one's device-resident
            # stats without a host round trip.
            head, tail, occ = (stats_in[0, i]
                               for i in (ST_HEAD, ST_TAIL, ST_OCC))
            succ_total = stats_in[0, ST_SUCC]
            cand_total = stats_in[0, ST_CAND]
            target = stats_in[0, ST_TARGET]
            carry = (vecs_a, fps_a, par_a, eb_a, visited, head, tail,
                     occ, succ_total, cand_total,
                     stats_in[0, ST_ERR] != 0, disc,
                     jnp.zeros((), jnp.int64), target)
            (vecs_a, fps_a, par_a, eb_a, visited, head, tail, occ,
             succ_total, cand_total, err, disc, waves,
             _) = jax.lax.while_loop(cond, wave, carry)
            # Discovery slots (replicated) ride in each shard's stats row
            # so the host reads one packed array per dispatch.
            stats = jnp.concatenate([
                jnp.stack([head, tail, occ, succ_total, cand_total,
                           target, err.astype(jnp.int64), waves]),
                jax.lax.bitcast_convert_type(disc, jnp.int64)])[None]
            return vecs_a, fps_a, par_a, eb_a, visited, disc, stats

        sharded = shard_map(
            local, mesh=mesh,
            in_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                      P("shard"), P(), P("shard")),
            out_specs=(P("shard"), P("shard"), P("shard"), P("shard"),
                       P("shard"), P(), P("shard")),
            check_vma=False)
        # stats_in is NOT donated: the host reads dispatch k's stats
        # after dispatch k+1 (which consumes them as input) has launched.
        jitted = jax.jit(sharded, donate_argnums=(0, 1, 2, 3, 4, 5))
        spec = self._shard_spec()
        rep = NamedSharding(mesh, P())

        def sds(shape, dtype, sharding=spec):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

        L = ST_DISC + max(Pn, 1)
        jitted = self._aot(jitted, (
            sds((n * ucap, Wr), jnp.uint32), sds((n * ucap,), jnp.uint64),
            sds((n * ucap,), jnp.uint64), sds((n * ucap,), jnp.uint32),
            sds((n * capacity,), jnp.uint64),
            sds((max(Pn, 1),), jnp.uint64, rep),
            sds((n, L), jnp.int64)))
        if self._prof.enabled:
            # Sharded dispatch programs bypass the shared program cache
            # (the ownership epoch keys them per instance), so static
            # cost capture (obs/prof.py) rides here.
            self._prof.capture(self._prof_key(key), jitted)
        self._wave_cache[key] = jitted
        return jitted

    def _grow_fn(self, old_cap: int, new_cap: int, dtype, width: int = 0):
        """Per-shard arena copy into a bigger buffer (runs under
        shard_map so each shard pads its own rows)."""
        key = ("sharded-grow", old_cap, new_cap, str(dtype), width)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached

        def grow_local(arr):
            shape = (new_cap, width) if width else (new_cap,)
            fill = SENTINEL if arr.dtype == jnp.uint64 else 0
            out = jnp.full(shape, fill, arr.dtype)
            start = (0, 0) if width else (0,)
            return jax.lax.dynamic_update_slice(out, arr, start)

        n = self._n
        shape = ((n * old_cap, width) if width else (n * old_cap,))
        jitted = _releasing(self._aot(
            jax.jit(shard_map(
                grow_local, mesh=self._mesh, in_specs=P("shard"),
                out_specs=P("shard"), check_vma=False),
                donate_argnums=(0,)),
            (jax.ShapeDtypeStruct(shape, dtype,
                                  sharding=self._shard_spec()),)))
        self._wave_cache[key] = jitted
        return jitted

    def _rehash_fn(self, old_cap: int, new_cap: int):
        key = ("sharded-rehash", old_cap, new_cap)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached

        def rehash_local(old_table):
            # Local view: this shard's [old_cap] slice of the flat table.
            new_table = jnp.full((new_cap,), SENTINEL, jnp.uint64)
            _, _, new_table = dedup_and_insert(old_table, new_table,
                                               new_cap)
            return new_table

        jitted = _releasing(self._aot(
            jax.jit(shard_map(
                rehash_local, mesh=self._mesh, in_specs=P("shard"),
                out_specs=P("shard"), check_vma=False),
                donate_argnums=(0,)),
            (jax.ShapeDtypeStruct((self._n * old_cap,), jnp.uint64,
                                  sharding=self._shard_spec()),)))
        self._wave_cache[key] = jitted
        return jitted

    # -- Host orchestration ------------------------------------------------

    def _run_waves(self) -> None:
        """The pipelined adaptive host loop over per-shard arenas — the
        single-chip fused schedule (see ``FusedTpuBfsChecker``) with
        per-shard head/tail/occ rows in the chained stats array. Every
        dispatch exits at a collectively-agreed rest point, so chained
        speculative launches are no-ops past one, never hazards."""
        n = self._n
        F, W = self._F, self._Wrow  # storage row width (packed form)
        R_max = n * self._B_max * F
        properties = self._properties
        Pn = len(properties)
        L = ST_DISC + max(Pn, 1)

        # Split the pending blocks into per-shard seeds by ownership.
        blocks = list(self._pending)
        self._pending.clear()
        if blocks:
            all_vecs = np.concatenate([b[0] for b in blocks])
            all_fps = np.concatenate([b[1] for b in blocks])
            all_ebits = np.concatenate([b[2] for b in blocks])
        else:
            all_vecs = np.zeros((0, W), np.uint32)
            all_fps = np.zeros(0, np.uint64)
            all_ebits = np.zeros(0, np.uint32)
        assign_np = np.asarray(self._owner_map.assignment(), np.int64)
        owners = assign_np[(all_fps % np.uint64(n)).astype(np.int64)]
        seeds = [(all_vecs[owners == i], all_fps[owners == i],
                  all_ebits[owners == i]) for i in range(n)]
        max_seed = max((len(s[1]) for s in seeds), default=0)

        ucap = self._arena_capacity or max(1 << 14, 4 * R_max,
                                           _pow2(max_seed))
        ucap = max(_pow2(ucap), _pow2(max_seed))
        pad = _pow2(max(max_seed, 1))
        # Flat [n * pad] layout (shard-major) like the visited table.
        pv = np.zeros((n * pad, W), np.uint32)
        pf = np.full(n * pad, SENTINEL, np.uint64)
        pe = np.zeros(n * pad, np.uint32)
        tails = np.zeros(n, np.int64)
        for i, (sv, sf, se) in enumerate(seeds):
            k = len(sf)
            pv[i * pad:i * pad + k] = sv
            pf[i * pad:i * pad + k] = sf
            pe[i * pad:i * pad + k] = se
            tails[i] = k
        spec = self._shard_spec()
        vecs_a = self._grow_fn(pad, ucap, jnp.uint32, W)(
            jax.device_put(pv, spec))
        fps_a = self._grow_fn(pad, ucap, jnp.uint64)(
            jax.device_put(pf, spec))
        par_a = self._grow_fn(pad, ucap, jnp.uint64)(
            jax.device_put(np.full(n * pad, SENTINEL, np.uint64), spec))
        eb_a = self._grow_fn(pad, ucap, jnp.uint32)(
            jax.device_put(pe, spec))
        self._ucap = ucap
        disc = jnp.full((max(Pn, 1),), SENTINEL, jnp.uint64)
        visited = self._visited
        occs = np.array(self._seed_occ, np.int64)
        base_states = self._state_count
        target_eff = ((self._target_state_count - base_states)
                      if self._target_state_count is not None else 1 << 62)
        succ_total = 0
        cand_seen = 0  # candidates attributed to processed dispatches
        n_seed_rows = int(tails.sum())
        # Parent-log bookkeeping is per shard for this engine.
        self._shard_synced = tails.copy()
        self._shard_tails = tails.copy()
        self._shard_heads = np.zeros(n, np.int64)

        self.wave_log.append((time.monotonic(), self._state_count))
        self._arena = (vecs_a, fps_a, par_a, eb_a)
        arena_total = n_seed_rows
        last_ckpt_states = 0

        stats_np = np.zeros((n, L), np.int64)
        stats_np[:, ST_HEAD] = self._shard_heads
        stats_np[:, ST_TAIL] = self._shard_tails
        stats_np[:, ST_OCC] = occs
        stats_np[:, ST_SUCC] = succ_total   # replicated
        stats_np[:, ST_TARGET] = target_eff  # replicated
        stats_dev = jax.device_put(stats_np, self._shard_spec())

        from collections import deque
        inflight: deque = deque()  # (stats_dev, meta), oldest first

        def process(entry) -> None:
            nonlocal occs, succ_total, cand_seen, arena_total
            if self._faults.active:
                # Same placement rationale as the single-chip fused
                # engine: before any bookkeeping, the torn-frontier
                # worst case.
                self._faults.crash("wave_crash", self._tracer,
                                   wave=len(self.dispatch_log))
            stats_out, meta = entry
            stats_h = np.asarray(stats_out)      # [n, L]
            heads_prev = self._shard_heads
            heads = stats_h[:, ST_HEAD].copy()
            tails = stats_h[:, ST_TAIL].copy()
            occs = stats_h[:, ST_OCC].copy()
            succ_prev = succ_total
            succ_total = int(stats_h[0, ST_SUCC])
            cand_total = int(stats_h[0, ST_CAND])
            cand_prev, cand_seen = cand_seen, cand_total
            if stats_h[:, ST_ERR].any():
                lane = self._dm.error_lane
                raise RuntimeError(
                    f"device model error lane {lane} is set in a "
                    "generated state: an encoding capacity was exceeded "
                    "(for actor models: raise net_slots)")
            new_total = int(tails.sum())
            with self._lock:
                self._shard_heads = heads
                self._shard_tails = tails
                self._resident = int(occs.sum())  # device occupancy
                self._state_count = base_states + succ_total
                novel = new_total - arena_total
                self._unique_count += novel
                arena_total = new_total
                now = time.monotonic()
                self.wave_log.append((now, self._state_count))
                # Unified wave event (obs schema): deltas vs the last
                # processed dispatch; load factor is the fullest
                # shard's table slice (the growth-gating quantity).
                wave_evt = dict(
                    meta, t=now, states=self._state_count,
                    unique=self._unique_count,
                    waves=int(stats_h[0, ST_WAVES]),
                    compiled=self._take_compile(),
                    successors=succ_total - succ_prev,
                    candidates=cand_total - cand_prev, novel=novel,
                    # Frontier rows consumed across every shard (the
                    # kernel-occupancy numerator).
                    rows=int((heads - heads_prev).sum()),
                    out_rows=None, capacity=self._capacity,
                    load_factor=round(
                        int(occs.max()) / self._capacity, 4),
                    overflow=False,
                    # Bandwidth gauges (obs schema v2): per-shard arena
                    # and table slices, summed over the mesh.
                    bytes_per_state=4 * self._Wrow,
                    arena_bytes=n * ucap * (4 * self._Wrow + 8 + 8 + 4),
                    table_bytes=n * self._capacity * 8,
                    # v10: wave-loop host-I/O stall since the last
                    # wave event (safe-point joins + inline writes).
                    io_stall_s=self._take_io_stall(),
                    # v5 attribution: the ownership epoch this wave's
                    # routing was compiled against.
                    epoch=self._owner_map.epoch)
                if self._store.active:
                    # Tier occupancy gauges (obs schema v6).
                    wave_evt.update(
                        self._store.gauges(),
                        tier_device_rows=int(occs.sum()),
                        tier_device_bytes=n * ucap
                        * self._arena_row_bytes()
                        + n * self._capacity * 8)
                if self._prof.enabled:
                    # v13 cost stamping + (on sampled dispatches) the
                    # profile_snapshot roofline event; the internal
                    # riders never reach the dispatch log or trace.
                    self._prof.wave(
                        wave_evt, wave_evt.pop("_prof_key", None),
                        wave_evt.pop("_prof_s", None),
                        self._tracer, self._flight)
                self.dispatch_log.append(wave_evt)
                if self._flight.armed:
                    self._flight.record(wave_evt)
                if Pn:
                    disc_h = np.ascontiguousarray(
                        stats_h[0, ST_DISC:ST_DISC + Pn]).view(np.uint64)
                    for i, prop in enumerate(properties):
                        fp = int(disc_h[i])
                        if (fp != int(SENTINEL)
                                and prop.name not in self._discoveries):
                            self._discoveries[prop.name] = fp
            if self._tracer.enabled:
                self._tracer.wave(wave_evt)
            if self._wave_obs.enabled:
                self._wave_obs.wave(wave_evt, self._tracer, self._flight)
            self._service_sync(None)

        while True:
            with self._lock:
                # Vacuously true with zero properties (bfs.rs:117).
                done = (len(self._discoveries) == Pn
                        or (self._target_state_count is not None
                            and self._state_count
                            >= self._target_state_count))
            live = int((self._shard_tails - self._shard_heads).sum())
            if done or (live <= 0 and not inflight):
                break

            # Intended next bucket from the fullest shard's live rows.
            bucket = pick_bucket(
                self._buckets,
                int((self._shard_tails - self._shard_heads).max()))
            R_b = n * bucket * F
            growth = (int(occs.max()) + R_b > self._capacity // 2
                      or int(self._shard_tails.max()) + R_b > ucap)
            ckpt_due = (self._ckpt_path is not None
                        and (self._unique_count - last_ckpt_states
                             >= self._ckpt_every * self._B))
            if (growth or ckpt_due or live <= 0) and inflight:
                process(inflight.popleft())
                continue
            if growth:
                # Wrapped for OOM graceful degradation like the
                # single-chip fused engine: shed the top batch bucket
                # and re-evaluate at the loop top.
                try:
                    self._grow_requested = (
                        self._capacity * 2 if int(occs.max()) + R_b
                        > self._capacity // 2 else self._capacity)
                    if self._faults.active:
                        self._faults.crash("grow_oom", self._tracer)
                    while int(occs.max()) + R_b > self._capacity // 2:
                        new_cap = self._capacity * 2
                        if self._tracer.enabled:
                            self._tracer.event(
                                "grow", kind="table",
                                old=self._capacity, new=new_cap)
                        visited = self._rehash_fn(self._capacity,
                                                  new_cap)(visited)
                        self._capacity = new_cap
                        self._visited = visited
                    while int(self._shard_tails.max()) + R_b > ucap:
                        budget = self._store.device_budget \
                            if self._store.active else None
                        over = (budget is not None
                                and 2 * n * ucap * self._arena_row_bytes()
                                + n * self._capacity * 8 > budget)
                        if over and int(self._shard_heads.max()) > 0:
                            # Per-shard arena-span spill (tiered
                            # store): parent-sync every shard, then
                            # shift each shard's live window down by
                            # its own head — headroom without growing
                            # past the device budget. Bit-identical:
                            # each shard's [head_i, tail_i) rows are
                            # unchanged, just re-based.
                            self._fetch_parents(None)
                            shifts = self._shard_heads.copy()
                            sh = jax.device_put(shifts.astype(np.int64),
                                                self._shard_spec())
                            vecs_a = self._roll_fn(
                                ucap, jnp.uint32, W)(vecs_a, sh)
                            fps_a = self._roll_fn(
                                ucap, jnp.uint64)(fps_a, sh)
                            par_a = self._roll_fn(
                                ucap, jnp.uint64)(par_a, sh)
                            eb_a = self._roll_fn(
                                ucap, jnp.uint32)(eb_a, sh)
                            self._arena = (vecs_a, fps_a, par_a, eb_a)
                            with self._lock:
                                self._shard_tails = \
                                    self._shard_tails - shifts
                                self._shard_heads = np.zeros(
                                    n, np.int64)
                                self._shard_synced = \
                                    self._shard_synced - shifts
                            rows = int(shifts.sum())
                            # Re-base the novel-count baseline: novel
                            # is the next dispatch's tails.sum() minus
                            # this, and every tail just moved down by
                            # its shard's shift.
                            arena_total -= rows
                            self._store.note_arena_span(
                                rows, rows * self._arena_row_bytes())
                            # Rebuild the chained per-shard stats at
                            # rest (discovery slots are outputs only).
                            st = np.zeros((n, L), np.int64)
                            st[:, ST_HEAD] = 0
                            st[:, ST_TAIL] = self._shard_tails
                            st[:, ST_OCC] = occs
                            st[:, ST_SUCC] = succ_total
                            st[:, ST_CAND] = cand_seen
                            st[:, ST_TARGET] = target_eff
                            stats_dev = jax.device_put(
                                st, self._shard_spec())
                            continue
                        if over and self._store.active:
                            self._store.note_device_pressure(
                                2 * n * ucap * self._arena_row_bytes()
                                + n * self._capacity * 8, budget)
                        new_ucap = ucap * 2
                        if self._tracer.enabled:
                            self._tracer.event("grow", kind="arena",
                                               old=ucap, new=new_ucap)
                        vecs_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint32, W)(vecs_a)
                        fps_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint64)(fps_a)
                        par_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint64)(par_a)
                        eb_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint32)(eb_a)
                        ucap = new_ucap
                        self._ucap = ucap
                        self._slice_cache.clear()
                        self._arena = (vecs_a, fps_a, par_a, eb_a)
                except Exception as e:  # noqa: BLE001 — non-OOM re-raised
                    self._handle_grow_failure(e)
                continue
            if ckpt_due:
                self._write_checkpoint(self._ckpt_path)
                last_ckpt_states = self._unique_count
                continue

            pkey = prof_s = t0 = None
            if self._prof.enabled:
                pkey = self._prof_key(
                    ("sharded-dispatch", bucket, self._capacity, ucap,
                     self._owner_map.epoch))
                if self._prof.should_sample(pkey):
                    t0 = time.monotonic()
            (vecs_a, fps_a, par_a, eb_a, visited, disc,
             stats_dev) = self._dispatch_fn(
                bucket, self._capacity, ucap)(
                vecs_a, fps_a, par_a, eb_a, visited, disc, stats_dev)
            if t0 is not None:
                # Rest-point timing (obs/prof.py): draining the
                # multi-dispatch pipeline for this one sample is the
                # 1/N price of a real device-time measurement.
                jax.block_until_ready(stats_dev)
                prof_s = time.monotonic() - t0
            self._arena = (vecs_a, fps_a, par_a, eb_a)
            self._visited = visited
            meta = {
                "bucket": bucket, "inflight": len(inflight) + 1,
                "kernel_path": self._kernel_path(self._capacity,
                                                 bucket),
                "expand_impl": self._expand_impl()}
            if pkey is not None:
                # Internal riders for process() — popped there before
                # the event reaches the schema'd streams.
                meta["_prof_key"] = pkey
                if prof_s is not None:
                    meta["_prof_s"] = prof_s
            inflight.append((stats_dev, meta))
            if len(inflight) >= self._depth:
                process(inflight.popleft())
        # Retire every launched dispatch (normal exit); see the
        # single-chip fused loop for the rationale.
        while inflight:
            process(inflight.popleft())

        self._fetch_parents(None)

    def _reset_engine_state(self) -> None:
        super()._reset_engine_state()
        for attr in ("_shard_synced", "_shard_tails", "_shard_heads",
                     "_ucap"):
            self.__dict__.pop(attr, None)

    # -- Parent log / checkpoint (per-shard arenas) ------------------------

    def _fetch_parents(self, _tail=None) -> None:
        if hasattr(self, "_arena"):
            _, fps_a, par_a, _ = self._arena
            u = self._ucap
            for i in range(self._n):
                lo = int(self._shard_synced[i])
                hi = int(self._shard_tails[i])
                if hi <= lo:
                    continue
                child = self._fetch_rows(fps_a, i * u + lo, hi - lo)
                parent = self._fetch_rows(par_a, i * u + lo, hi - lo)
                with self._lock:
                    self._parent_log.append((child, parent))
                self._shard_synced[i] = hi
        with self._sync_cond:
            self._sync_generation += 1
            self._sync_cond.notify_all()

    def _pending_blocks(self) -> list:
        if not hasattr(self, "_arena"):
            return list(self._pending)
        vecs_a, fps_a, _, eb_a = self._arena
        u = self._ucap
        blocks = []
        for i in range(self._n):
            lo = int(self._shard_heads[i])
            hi = int(self._shard_tails[i])
            if hi <= lo:
                continue
            blocks.append((
                self._fetch_rows(vecs_a, i * u + lo, hi - lo, self._Wrow),
                self._fetch_rows(fps_a, i * u + lo, hi - lo),
                self._fetch_rows(eb_a, i * u + lo, hi - lo)))
        return blocks

    def _write_checkpoint(self, path: str) -> None:
        from .engine import TpuBfsChecker

        if hasattr(self, "_arena"):
            self._fetch_parents(None)
        # Skip FusedTpuBfsChecker's override (single-arena bookkeeping);
        # the base writer consumes _pending_blocks/_parent_map, which
        # this class provides per shard.
        TpuBfsChecker._write_checkpoint(self, path)

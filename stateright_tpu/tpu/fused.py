"""Fused device-queue BFS: the whole checker state lives on device.

``TpuBfsChecker`` keeps the frontier queue and parent map on the host, so
every wave pays two state-tensor transfers (batch up, survivors down) plus
several dispatch round trips. On a tunneled or remote accelerator that
host boundary dominates wall time (measured ~0.9 s/wave against ~0.4 s of
device compute on the paxos bench config). This engine removes the
boundary entirely:

- **Arena**: every discovered state lives in a device-resident append-only
  arena — ``vecs[U, W]``, ``fps[U]``, ``parent fps[U]``, ``ebits[U]``.
  Rows ``[head, tail)`` are the not-yet-expanded BFS frontier, so the
  arena *is* the queue (FIFO ⇒ level order, like the pending queue of
  `bfs.rs:70-74`), *is* the parent map (`bfs.rs:26`), and *is* the
  checkpoint payload. Appends are one ``dynamic_update_slice`` per wave —
  contiguous, no scatter.
- **Fused waves**: one dispatch runs up to ``waves_per_dispatch`` BFS
  waves in a ``lax.while_loop``; property discoveries are resolved on
  device (first-hit fingerprint per property, in frontier order — the
  dedup/queue order of `bfs.rs:196-226,245-262`), so the host uploads
  nothing and downloads one packed stats vector per dispatch.
- **Lazy parent fetch**: ``(fp, parent fp)`` rows cross to the host only
  when a path is actually reconstructed (discoveries, checkpoint) —
  16 bytes per unique state, once, instead of per wave.

Growth (visited table or arena full) and checkpoints happen between
dispatches; the table rehash runs on device (old table entries re-probed
into a table of twice the capacity), so the resident set never crosses
the host boundary.

Semantics are bit-identical to ``TpuBfsChecker`` (same wave composition,
same dedup-order rule, same eventually-bits handling incl. the documented
revisit caveats of `bfs.rs:239-259`); the parity suite runs both.
"""

from __future__ import annotations

import threading
import warnings
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..model import Expectation
from .engine import (TpuBfsChecker, compaction_order, dedup_and_insert,
                     dedup_impl, eval_properties, expand_frontier,
                     fingerprint_successors, matmul_expand, pick_bucket,
                     wave_kernel_impl)
from .hashing import SENTINEL

__all__ = ["FusedTpuBfsChecker", "FusedUnsupported"]

# Dispatch-stats vector layout (int64). The SAME layout is consumed and
# produced by every dispatch program, so a dispatch can be launched
# directly from its predecessor's still-device-resident stats — the
# host only materializes a stats vector when it processes that dispatch
# (possibly one or more launches later). ``WAVES`` is reset per
# dispatch; ``TARGET`` rides along unchanged; ``CAND`` accumulates the
# distinct candidates that reached the global probe (the local-dedup
# collapse telemetry); discovery fingerprints are bitcast into the tail
# slots (they also travel as a separate donated array between
# dispatches).
(ST_HEAD, ST_TAIL, ST_OCC, ST_SUCC, ST_CAND, ST_TARGET, ST_ERR,
 ST_WAVES) = range(8)
ST_DISC = 8


class FusedUnsupported(TypeError):
    """The model/builder needs a host-side per-wave hook; use the classic
    engine (``spawn_tpu_bfs(fused=False)``)."""


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def _releasing(fn):
    """Wraps a jitted grow/rehash program so growth never retains the
    pre-growth buffer: the input is donated (backends that can alias or
    reuse its pages do), the cosmetic "donated buffers were not usable"
    warning is silenced where the shape change makes aliasing impossible,
    and the old buffer is explicitly deleted once the program has
    consumed it — peak memory during a doubling is the one unavoidable
    copy, not old + new + scratch."""
    def call(arr):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            out = fn(arr)
        if isinstance(arr, jax.Array) and not arr.is_deleted():
            # Deleting an input of a still-in-flight async program frees
            # it under the reader (observed as garbage fingerprints in
            # the visited table on the CPU client); growth is a rest
            # point, so waiting out the copy costs nothing.
            jax.block_until_ready(out)
            arr.delete()
        return out
    return call


class FusedTpuBfsChecker(TpuBfsChecker):
    """Device-arena BFS with multi-wave dispatches."""

    _ENGINE_ID = "fused"

    # The fused engines dedup entirely on device across multi-wave
    # dispatches: a host-side probe of spilled visited partitions would
    # come too late (re-admitted rows would already be re-expanded into
    # the arena), so the tiered store must not evict from their tables.
    # Their device relief valve is the ARENA-SPAN spill instead: rows
    # [0, head) are the already-expanded prefix — the wave only ever
    # reads [head, tail) and the parent log is the rows' host-RAM home
    # — so under a device byte budget the prefix is parent-synced to
    # the host and the live window shifted down, freeing arena headroom
    # without growing (see _run_waves).
    _VISITED_SPILL_CAPABLE = False

    # No per-wave host boundary: frontiers, stats, and the dedup all
    # live in the donated device arena across a multi-wave dispatch, so
    # there is no point at which a wave's outputs could be split per
    # tenant — fused jobs run solo and share only compiled programs
    # (the jit cache), never dispatches (service/mux.py checks this).
    _MUX_CAPABLE = False

    # The fused wave appends to the donated arena through a full-window
    # dynamic_update_slice on purpose (narrowing it breaks XLA's
    # in-place aliasing — see the wave body), and its outputs never
    # cross the host boundary, so the successor output ladder has
    # nothing to bound here. Local dedup still runs (inside
    # dedup_impl), and its collapse telemetry rides the ST_CAND slot.
    _SUCC_LADDER_CAPABLE = False

    def __init__(self, builder, batch_size: int = 1024,
                 waves_per_dispatch: Optional[int] = None,
                 arena_capacity: Optional[int] = None,
                 inflight_dispatches: int = 2, **kwargs):
        kwargs.pop("pipeline", None)  # per-wave pipelining is subsumed
        if waves_per_dispatch is None:
            # One dispatch round trip per 16 waves; the loop exits early
            # on a drained queue / completed discoveries / growth, so a
            # large cap costs small models nothing (measured fastest on
            # the CPU backend too).
            waves_per_dispatch = 16
        self._K = max(1, int(waves_per_dispatch))
        self._arena_capacity = arena_capacity
        # Dispatch pipeline depth: how many dispatches may be launched
        # before the oldest one's stats are read back. Depth 2 keeps one
        # dispatch in flight while the host processes its predecessor;
        # depth 1 is the synchronous round-trip-per-dispatch schedule.
        # Safe at any depth: every dispatch re-checks its stop
        # predicates on device before expanding a wave, so a dispatch
        # launched past a rest point (growth due, queue drained, all
        # discovered) is a no-op, not a hazard.
        self._depth = max(1, int(inflight_dispatches))
        super().__init__(builder, batch_size=batch_size, pipeline=False,
                         **kwargs)

    def _check_support(self) -> None:
        if self._visitor is not None:
            raise FusedUnsupported(
                "visitors need the per-wave host loop; the builder falls "
                "back to the classic engine")
        if any(fn is None for fn in self._prop_fns):
            raise FusedUnsupported(
                "host-fallback properties need the per-wave host loop; "
                "the builder falls back to the classic engine")

    def _pre_spawn_check(self) -> None:
        # Worker/device-state handshake (parent fetches are worker-only;
        # other threads request one via the condition).
        self._sync_cond = threading.Condition()
        self._sync_requested = False
        self._sync_generation = 0
        self._synced_rows = 0  # arena rows already in the parent log
        self._slice_cache: dict = {}

    # -- Dispatch program --------------------------------------------------

    def _dispatch_fn(self, batch: int, capacity: int, ucap: int):
        # The shared-cache key carries the fused schedule knob K too:
        # two jobs share a dispatch program only when their wave
        # cadence agrees (engine id / packing / symmetry ride in
        # _cached_program's shared prefix).
        return self._cached_program(
            ("dispatch", batch, capacity, ucap, self._K),
            lambda: self._build_dispatch_fn(batch, capacity, ucap))

    def _build_dispatch_fn(self, batch: int, capacity: int, ucap: int):
        dm = self._dm
        B, F, W, K = batch, self._F, self._W, self._K
        Wr = self._Wrow
        layout = self._wave_layout()
        S = B * F
        prop_fns = list(self._prop_fns)
        use_sym = self._use_symmetry
        properties = self._properties
        P = len(properties)
        sentinel = jnp.uint64(SENTINEL)
        err_lane = dm.error_lane
        ebits_masks = [jnp.uint32(1 << i) for i in range(P)]
        dedup = dedup_impl(self._table_impl, capacity)
        # Single-kernel wave (ISSUE 10): with the megakernel resolved,
        # each iteration of the device-resident multi-wave loop below
        # runs its whole successor path as ONE pallas_call — K waves of
        # fused kernel dispatches per host round-trip, stats vector
        # chained exactly as before (the loop's rest-point predicates
        # are untouched, so checkpoint/fault/spill hooks still fire at
        # dispatch exits).
        mega = wave_kernel_impl(self._wave_kernel_on, dm, B, capacity,
                                use_sym, layout,
                                matmul_plan=self._matmul_plan)

        def first_hit(disc_i, hit, bfps):
            """Keeps the first (frontier-order) hit's fingerprint, set
            exactly once across the whole run (bfs.rs:196-211)."""
            row = jnp.argmax(hit)  # first True
            fp = bfps[row]
            return jnp.where((disc_i == sentinel) & hit.any(), fp, disc_i)

        def wave(carry):
            (vecs_a, fps_a, par_a, eb_a, visited, head, tail, occ,
             succ_total, cand_total, err, disc, waves) = carry
            idx = head + jnp.arange(B, dtype=jnp.int64)
            valid = idx < tail
            idx_c = jnp.minimum(idx, ucap - 1)
            # The arena stores PACKED rows; unpack the batch to real
            # lanes at wave start (compute is layout-independent).
            bstore = vecs_a[idx_c]
            bvecs = bstore
            if layout is not None:
                bvecs = layout.unpack(bstore)
            bfps = fps_a[idx_c]
            bebits = eb_a[idx_c]

            conds = eval_properties(prop_fns, bvecs)
            for i, prop in enumerate(properties):
                if prop.expectation is Expectation.ALWAYS:
                    hit = valid & ~conds[i]
                elif prop.expectation is Expectation.SOMETIMES:
                    hit = valid & conds[i]
                else:
                    continue
                disc = disc.at[i].set(first_hit(disc[i], hit, bfps))

            if mega is not None:
                # Single-kernel wave: expand, fingerprint, local dedup,
                # and the table probe/claim fused into one pallas_call
                # on the PACKED batch rows; the reductions below derive
                # the same quantities expand_frontier/dedup return.
                (succ_store, path_fps, sflat, new_mask, cand_mask,
                 visited) = mega(bstore, valid, visited)
                succ_count = jnp.sum(sflat, dtype=jnp.int64)
                terminal = valid & ~sflat.reshape(B, F).any(axis=1)
                new_count = jnp.sum(new_mask, dtype=jnp.int32)
                cand_count = jnp.sum(cand_mask, dtype=jnp.int32)
            else:
                succ_flat, sflat, succ_count, terminal = (
                    matmul_expand(dm, self._matmul_plan, bvecs, valid)
                    if self._matmul_plan is not None
                    else expand_frontier(dm, bvecs, valid))
                dedup_fps, path_fps = fingerprint_successors(
                    dm, succ_flat, sflat, use_sym)
                new_mask, new_count, cand_count, visited = dedup(
                    dedup_fps, visited)
            comp = compaction_order(new_mask)

            # Eventually bits: clear satisfied at the parent, then flag
            # terminal parents with leftover bits (bfs.rs:212-226,265-272).
            cleared = bebits
            for i, prop in enumerate(properties):
                if prop.expectation is Expectation.EVENTUALLY:
                    cleared = cleared & ~jnp.where(
                        conds[i], ebits_masks[i], jnp.uint32(0))
            for i, prop in enumerate(properties):
                if prop.expectation is Expectation.EVENTUALLY:
                    hit = valid & terminal & ((cleared >> i) & 1  # noqa: E501
                                              ).astype(bool)
                    disc = disc.at[i].set(first_hit(disc[i], hit, bfps))

            # Append the survivors at the arena tail (frontier order —
            # the bfs.rs:262 enqueue order). Rows past new_count are
            # garbage beyond tail: overwritten by the next wave, never
            # read (all reads mask by tail). The append window is the
            # full S rows on purpose: narrowing it behind a lax.cond
            # breaks XLA's in-place aliasing of the donated arena and
            # forces whole-arena copies per wave (measured ~2x wall on
            # the CPU backend), which dwarfs the bytes saved.
            parent_rows = comp // F
            # Megakernel rows arrive already packed for storage; the
            # ladder packs after the gather as before.
            new_vecs = (succ_store[comp] if mega is not None
                        else succ_flat[comp])
            new_fps = path_fps[comp]
            new_parent = bfps[parent_rows]
            new_ebits = cleared[parent_rows]
            if err_lane is not None:
                # On packed rows, extract just the error lane (the
                # sharded-fused precedent); unpacked rows index it.
                err_col = (layout.lane(new_vecs, err_lane)
                           if mega is not None and layout is not None
                           else new_vecs[:, err_lane])
                err = err | jnp.any((err_col != 0)
                                    & (jnp.arange(S) < new_count))
            if mega is None and layout is not None:
                new_vecs = layout.pack(new_vecs)
            start = (tail,)
            vecs_a = jax.lax.dynamic_update_slice(vecs_a, new_vecs,
                                                  (tail, jnp.int64(0)))
            fps_a = jax.lax.dynamic_update_slice(fps_a, new_fps, start)
            par_a = jax.lax.dynamic_update_slice(par_a, new_parent, start)
            eb_a = jax.lax.dynamic_update_slice(eb_a, new_ebits, start)

            nc = new_count.astype(jnp.int64)
            return (vecs_a, fps_a, par_a, eb_a, visited,
                    jnp.minimum(head + B, tail), tail + nc, occ + nc,
                    succ_total + succ_count,
                    cand_total + cand_count.astype(jnp.int64), err, disc,
                    waves + 1)

        def cond(carry):
            (_, _, _, _, _, head, tail, occ, succ_total, _cand, err,
             disc, waves, target) = carry
            more = (waves < K) & (head < tail) & ~err
            more = more & (tail + S <= ucap)
            more = more & (occ + S <= capacity // 2)
            if P:
                more = more & ~jnp.all(disc != sentinel)
            # target is dynamic (carried): this run's successor budget.
            return more & (succ_total < target)

        def wave_t(carry):
            return wave(carry[:-1]) + (carry[-1],)

        def dispatch(vecs_a, fps_a, par_a, eb_a, visited, disc, stats_in):
            # stats_in/stats_out share the ST_* layout, so a successor
            # dispatch chains on this one's device-resident outputs
            # without a host round trip (the pipelined schedule).
            head, tail, occ, succ_total, cand_total, target = (
                stats_in[i] for i in (ST_HEAD, ST_TAIL, ST_OCC,
                                      ST_SUCC, ST_CAND, ST_TARGET))
            carry = (vecs_a, fps_a, par_a, eb_a, visited, head, tail, occ,
                     succ_total, cand_total, stats_in[ST_ERR] != 0, disc,
                     jnp.zeros((), jnp.int64), target)
            (vecs_a, fps_a, par_a, eb_a, visited, head, tail, occ,
             succ_total, cand_total, err, disc, waves,
             _) = jax.lax.while_loop(cond, wave_t, carry)
            # Discovery slots ride in the stats vector (bitcast, so the
            # SENTINEL survives) — one host fetch per dispatch, not two.
            stats = jnp.concatenate([
                jnp.stack([head, tail, occ, succ_total, cand_total,
                           target, err.astype(jnp.int64), waves]),
                jax.lax.bitcast_convert_type(disc, jnp.int64)])
            return vecs_a, fps_a, par_a, eb_a, visited, disc, stats

        # stats_in is NOT donated: the host reads dispatch k's stats
        # after dispatch k+1 (which consumes them as input) has launched.
        jitted = jax.jit(dispatch, donate_argnums=(0, 1, 2, 3, 4, 5))
        sds = jax.ShapeDtypeStruct
        jitted = self._aot(jitted, (
            sds((ucap, Wr), jnp.uint32), sds((ucap,), jnp.uint64),
            sds((ucap,), jnp.uint64), sds((ucap,), jnp.uint32),
            sds((capacity,), jnp.uint64), sds((max(P, 1),), jnp.uint64),
            sds((ST_DISC + max(P, 1),), jnp.int64)))
        return jitted

    def _grow_fn(self, old_cap: int, new_cap: int, dtype, width: int = 0):
        key = ("grow", old_cap, new_cap, str(dtype), width)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached

        def grow(arr):
            shape = (new_cap, width) if width else (new_cap,)
            fill = SENTINEL if arr.dtype == jnp.uint64 else 0
            out = jnp.full(shape, fill, arr.dtype)
            start = (0, 0) if width else (0,)
            return jax.lax.dynamic_update_slice(out, arr, start)

        shape = (old_cap, width) if width else (old_cap,)
        jitted = _releasing(self._aot(
            jax.jit(grow, donate_argnums=(0,)),
            (jax.ShapeDtypeStruct(shape, dtype),)))
        self._wave_cache[key] = jitted
        return jitted

    def _rehash_fn(self, old_cap: int, new_cap: int):
        key = ("rehash", old_cap, new_cap)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached

        def rehash(old_table):
            new_table = jnp.full((new_cap,), SENTINEL, jnp.uint64)
            _, _, new_table = dedup_and_insert(old_table, new_table,
                                               new_cap)
            return new_table

        jitted = _releasing(self._aot(
            jax.jit(rehash, donate_argnums=(0,)),
            (jax.ShapeDtypeStruct((old_cap,), jnp.uint64),)))
        self._wave_cache[key] = jitted
        return jitted

    def _roll_fn(self, ucap: int, dtype, width: int = 0):
        """The arena-span shift program: moves rows [shift, ucap) down
        to 0 (``jnp.roll`` — the wrapped-around prefix lands beyond
        ``tail`` where no read ever looks). Donated, so backends alias
        in place."""
        key = ("roll", ucap, str(dtype), width)
        cached = self._wave_cache.get(key)
        if cached is not None:
            return cached

        def roll(arr, shift):
            return jnp.roll(arr, -shift, axis=0)

        shape = (ucap, width) if width else (ucap,)
        jitted = self._aot(
            jax.jit(roll, donate_argnums=(0,)),
            (jax.ShapeDtypeStruct(shape, dtype),
             jax.ShapeDtypeStruct((), jnp.int64)))
        self._wave_cache[key] = jitted
        return jitted

    def _arena_row_bytes(self) -> int:
        """Device bytes per arena row (packed vec words + fp + parent
        fp + ebits)."""
        return 4 * self._Wrow + 8 + 8 + 4

    def _fetch_rows(self, arr, start: int, count: int,
                    width: int = 0) -> np.ndarray:
        """Device-slice [start, start+count) with O(log U) compiled
        shapes (power-of-two lengths, dynamic start)."""
        if count <= 0:
            shape = (0, width) if width else (0,)
            return np.zeros(shape, arr.dtype)
        ucap = arr.shape[0]
        kb = min(_pow2(count), ucap)
        key = ("slice", ucap, kb, str(arr.dtype), width)
        fn = self._slice_cache.get(key)
        if fn is None:
            size = (kb, width) if width else (kb,)

            def slice_fn(a, s):
                starts = (s, jnp.int64(0)) if width else (s,)
                return jax.lax.dynamic_slice(a, starts, size)

            fn = jax.jit(slice_fn)
            self._slice_cache[key] = fn
        clamped = min(start, ucap - kb)  # dynamic_slice clamps the same
        off = start - clamped
        return np.asarray(fn(arr, jnp.int64(clamped)))[off:off + count]

    # -- Host orchestration ------------------------------------------------

    def _run_waves(self) -> None:
        """The pipelined adaptive host loop.

        Every dispatch runs to a *true rest point* on device (queue
        drained, wave cap, all discovered, target met, error, or — the
        key ones — table/arena headroom exhausted), so the host can
        launch dispatch k+1 directly from k's device-resident carry
        BEFORE reading k's stats: a dispatch launched past a rest point
        re-checks the same predicates on device and no-ops. The host
        therefore keeps up to ``inflight_dispatches`` launches ahead of
        its stats reads, and only truly blocks at rest points that need
        host action (growth, checkpoints, discovery retirement).

        Batch width is re-picked per launch from the last *processed*
        frontier width over the bucket ladder — a stale estimate is a
        performance wrinkle, never a correctness one (results are
        bucket-independent; the cross-B parity suite pins this)."""
        F, W = self._F, self._Wrow  # storage row width (packed form)
        properties = self._properties
        P = len(properties)
        L = ST_DISC + max(P, 1)

        # Seed the arena from the pending blocks (fresh init states, or a
        # checkpoint's frontier). Parents of these rows are already known
        # host-side; only rows beyond _synced_rows are fetched later.
        blocks = list(self._pending)
        self._pending.clear()
        if blocks:
            seed_vecs = np.concatenate([b[0] for b in blocks])
            seed_fps = np.concatenate([b[1] for b in blocks])
            seed_ebits = np.concatenate([b[2] for b in blocks])
        else:
            seed_vecs = np.zeros((0, W), np.uint32)
            seed_fps = np.zeros(0, np.uint64)
            seed_ebits = np.zeros(0, np.uint32)
        n_seed = len(seed_fps)
        self._synced_rows = n_seed
        ucap = self._arena_capacity or max(1 << 15, 4 * self._B_max * F,
                                           _pow2(n_seed))
        ucap = _pow2(ucap)

        # Device state. The arena is built with on-device fills — only
        # the seed rows cross the boundary.
        pad = _pow2(max(n_seed, 1))
        ucap = max(ucap, pad)  # an explicit arena_capacity never truncates
                               # a resumed frontier
        pv = np.zeros((pad, W), np.uint32)
        pf = np.full(pad, SENTINEL, np.uint64)
        pe = np.zeros(pad, np.uint32)
        pv[:n_seed] = seed_vecs
        pf[:n_seed] = seed_fps
        pe[:n_seed] = seed_ebits
        vecs_a = self._grow_fn(pad, ucap, jnp.uint32, W)(jnp.asarray(pv))
        fps_a = self._grow_fn(pad, ucap, jnp.uint64)(jnp.asarray(pf))
        par_a = self._grow_fn(pad, ucap, jnp.uint64)(
            jnp.full(pad, SENTINEL, jnp.uint64))
        eb_a = self._grow_fn(pad, ucap, jnp.uint32)(jnp.asarray(pe))
        disc = jnp.full((max(P, 1),), SENTINEL, jnp.uint64)
        visited = self._visited
        # occupancy of the visited table (== arena rows unless resuming,
        # where the table also holds already-expanded states).
        occ = self._unique_count
        head, tail = 0, n_seed
        base_states = self._state_count
        # This run's successor budget (the target counts cumulative
        # state_count, which starts at base_states on resume).
        target_eff = ((self._target_state_count - base_states)
                      if self._target_state_count is not None else 1 << 62)
        succ_total = 0
        cand_seen = 0  # candidates attributed to processed dispatches

        self.wave_log.append((time.monotonic(), self._state_count))
        self._arena = (vecs_a, fps_a, par_a, eb_a)
        self._arena_tail = tail
        self._head = head
        last_ckpt_states = 0

        stats_np = np.zeros(L, np.int64)
        stats_np[ST_HEAD], stats_np[ST_TAIL] = head, tail
        stats_np[ST_OCC], stats_np[ST_SUCC] = occ, succ_total
        stats_np[ST_TARGET] = target_eff
        stats_dev = jnp.asarray(stats_np)

        from collections import deque
        inflight: deque = deque()  # (stats_dev, meta), oldest first

        def process(entry) -> None:
            """Materializes one dispatch's stats (the only blocking
            read) and applies them; absolute values make processing a
            no-op dispatch harmless."""
            nonlocal head, tail, occ, succ_total, cand_seen
            if self._faults.active:
                # Before any count/arena bookkeeping: the dispatch's
                # table/arena mutations are device-resident and real, so
                # a crash here tears the in-memory frontier — only a
                # checkpoint resume repairs it.
                self._faults.crash("wave_crash", self._tracer,
                                   wave=len(self.dispatch_log))
            stats_out, meta = entry
            stats_h = np.asarray(stats_out)
            succ_prev = succ_total
            head_prev = head
            head, tail, occ, succ_total = (
                int(stats_h[i]) for i in (ST_HEAD, ST_TAIL, ST_OCC,
                                          ST_SUCC))
            cand_total = int(stats_h[ST_CAND])
            cand_prev, cand_seen = cand_seen, cand_total
            if stats_h[ST_ERR]:
                lane = self._dm.error_lane
                raise RuntimeError(
                    f"device model error lane {lane} is set in a "
                    "generated state: an encoding capacity was exceeded "
                    "(for actor models: raise net_slots)")
            with self._lock:
                self._state_count = base_states + succ_total
                novel = tail - self._arena_tail
                self._unique_count += novel
                self._arena_tail = tail
                self._head = head
                self._resident = occ  # device-tier occupancy (absolute)
                now = time.monotonic()
                self.wave_log.append((now, self._state_count))
                # Unified wave event (obs schema): the device stats
                # vector is absolute, so per-dispatch deltas come from
                # the previous processed dispatch's totals.
                wave_evt = dict(
                    meta, t=now, states=self._state_count,
                    unique=self._unique_count,
                    waves=int(stats_h[ST_WAVES]),
                    compiled=self._take_compile(),
                    successors=succ_total - succ_prev,
                    candidates=cand_total - cand_prev, novel=novel,
                    # Frontier rows this dispatch consumed (the head
                    # advance) — the kernel-occupancy numerator.
                    rows=head - head_prev,
                    out_rows=None, capacity=self._capacity,
                    load_factor=round(occ / self._capacity, 4),
                    overflow=False,
                    # Bandwidth gauges (obs schema v2): the resident
                    # arena footprint (packed vec rows + fps + parent
                    # fps + ebits) and the table bytes.
                    bytes_per_state=4 * self._Wrow,
                    arena_bytes=ucap * (4 * self._Wrow + 8 + 8 + 4),
                    table_bytes=self._capacity * 8,
                    # v10: wave-loop host-I/O stall since the last
                    # wave event (safe-point joins + inline writes).
                    io_stall_s=self._take_io_stall())
                if self._store.active:
                    # Tier occupancy gauges (obs schema v6): device =
                    # live arena + table; spilled arena spans ride the
                    # store's host-tier gauges.
                    wave_evt.update(
                        self._store.gauges(),
                        tier_device_rows=occ,
                        tier_device_bytes=ucap * self._arena_row_bytes()
                        + self._capacity * 8)
                if self._prof.enabled:
                    # v13 cost stamping + (on sampled dispatches) the
                    # profile_snapshot roofline event; the internal
                    # riders never reach the dispatch log or trace.
                    self._prof.wave(
                        wave_evt, wave_evt.pop("_prof_key", None),
                        wave_evt.pop("_prof_s", None),
                        self._tracer, self._flight)
                self.dispatch_log.append(wave_evt)
                if self._flight.armed:
                    self._flight.record(wave_evt)
                if P:
                    disc_h = stats_h[ST_DISC:ST_DISC + P].view(np.uint64)
                    for i, prop in enumerate(properties):
                        fp = int(disc_h[i])
                        if (fp != int(SENTINEL)
                                and prop.name not in self._discoveries):
                            self._discoveries[prop.name] = fp
            if self._tracer.enabled:
                self._tracer.wave(wave_evt)
            if self._wave_obs.enabled:
                self._wave_obs.wave(wave_evt, self._tracer, self._flight)
            self._service_sync(tail)

        while True:
            if self._preempt_evt.is_set():
                # Preemption (job service): break to the normal exit —
                # the epilogue below retires every in-flight dispatch
                # and syncs the parent log, so the end-of-run
                # checkpoint is a valid resume image (same path a
                # target_state_count stop takes mid-frontier).
                self.preempted = True
                break
            with self._lock:
                # Vacuously true with zero properties — the run
                # retires immediately, like the host engines
                # (bfs.rs:117).
                done = (len(self._discoveries) == P
                        or (self._target_state_count is not None
                            and self._state_count
                            >= self._target_state_count))
            if done or (head >= tail and not inflight):
                break

            # Intended next bucket + its per-wave append bound.
            bucket = pick_bucket(self._buckets, tail - head)
            S_b = bucket * F
            growth = (occ + S_b > self._capacity // 2
                      or tail + S_b > ucap)
            ckpt_due = (self._ckpt_path is not None
                        and (self._unique_count - last_ckpt_states
                             >= self._ckpt_every * self._B))
            if (growth or ckpt_due or head >= tail) and inflight:
                # Host-side actions need processed stats at rest;
                # retire the oldest in-flight dispatch first (it may
                # already have resolved the condition).
                process(inflight.popleft())
                continue
            if growth:
                # Growth at rest, before the table/arena can fill.
                # The jitted programs chain on the device queue; the
                # old buffers are donated + released (_releasing). An
                # allocation failure (real or the injected grow_oom
                # fault) sheds the top batch bucket instead of killing
                # the run — the loop top re-derives the bucket and the
                # headroom requirement from the shrunken ladder, so a
                # narrower dispatch may no longer need the growth at
                # all (OOM graceful degradation).
                try:
                    self._grow_requested = (
                        self._capacity * 2 if occ + S_b
                        > self._capacity // 2 else self._capacity)
                    if self._faults.active:
                        self._faults.crash("grow_oom", self._tracer)
                    while occ + S_b > self._capacity // 2:
                        new_cap = self._capacity * 2
                        if self._tracer.enabled:
                            self._tracer.event(
                                "grow", kind="table",
                                old=self._capacity, new=new_cap)
                        visited = self._rehash_fn(self._capacity,
                                                  new_cap)(visited)
                        self._capacity = new_cap
                        self._visited = visited
                    while tail + S_b > ucap:
                        budget = self._store.device_budget \
                            if self._store.active else None
                        over = (budget is not None
                                and 2 * ucap * self._arena_row_bytes()
                                + self._capacity * 8 > budget)
                        if over and head > 0:
                            # Arena-span spill (tiered store): the
                            # expanded prefix [0, head) is only ever
                            # read by the parent-log sync, so sync it
                            # to the host and shift the live window
                            # down — headroom without growing past the
                            # device budget. Bit-identical: the wave
                            # reads the same [head, tail) rows in the
                            # same order, just at a new base.
                            self._fetch_parents(head)
                            shift = head
                            sh = jnp.int64(shift)
                            vecs_a = self._roll_fn(
                                ucap, jnp.uint32, W)(vecs_a, sh)
                            fps_a = self._roll_fn(
                                ucap, jnp.uint64)(fps_a, sh)
                            par_a = self._roll_fn(
                                ucap, jnp.uint64)(par_a, sh)
                            eb_a = self._roll_fn(
                                ucap, jnp.uint32)(eb_a, sh)
                            self._arena = (vecs_a, fps_a, par_a, eb_a)
                            head, tail = 0, tail - shift
                            with self._lock:
                                self._head, self._arena_tail = head, tail
                                self._synced_rows -= shift
                            self._store.note_arena_span(
                                shift, shift * self._arena_row_bytes())
                            # The chained stats carry the OLD window;
                            # rebuild them at rest (discovery slots are
                            # outputs only — the dispatch takes disc
                            # separately).
                            st = np.zeros(L, np.int64)
                            st[ST_HEAD], st[ST_TAIL] = head, tail
                            st[ST_OCC], st[ST_SUCC] = occ, succ_total
                            st[ST_CAND] = cand_seen
                            st[ST_TARGET] = target_eff
                            stats_dev = jnp.asarray(st)
                            continue
                        if over and self._store.active:
                            # Nothing left to shift: the device tier
                            # must exceed its budget — recorded, not
                            # fatal.
                            self._store.note_device_pressure(
                                2 * ucap * self._arena_row_bytes()
                                + self._capacity * 8, budget)
                        new_ucap = ucap * 2
                        if self._tracer.enabled:
                            self._tracer.event("grow", kind="arena",
                                               old=ucap, new=new_ucap)
                        vecs_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint32, W)(vecs_a)
                        fps_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint64)(fps_a)
                        par_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint64)(par_a)
                        eb_a = self._grow_fn(
                            ucap, new_ucap, jnp.uint32)(eb_a)
                        ucap = new_ucap
                        self._slice_cache.clear()
                        self._arena = (vecs_a, fps_a, par_a, eb_a)
                except Exception as e:  # noqa: BLE001 — non-OOM re-raised
                    self._handle_grow_failure(e)
                continue
            if ckpt_due:
                self._write_checkpoint(self._ckpt_path)
                last_ckpt_states = self._unique_count
                continue

            pkey = prof_s = t0 = None
            if self._prof.enabled:
                pkey = self._prof_key(
                    ("dispatch", bucket, self._capacity, ucap, self._K))
                if self._prof.should_sample(pkey):
                    t0 = time.monotonic()
            (vecs_a, fps_a, par_a, eb_a, visited, disc,
             stats_dev) = self._dispatch_fn(
                bucket, self._capacity, ucap)(
                vecs_a, fps_a, par_a, eb_a, visited, disc, stats_dev)
            if t0 is not None:
                # Rest-point timing (obs/prof.py): draining the
                # multi-dispatch pipeline for this one sample is the
                # 1/N price of a real device-time measurement.
                jax.block_until_ready(stats_dev)
                prof_s = time.monotonic() - t0
            self._arena = (vecs_a, fps_a, par_a, eb_a)
            self._visited = visited
            meta = {
                "bucket": bucket, "inflight": len(inflight) + 1,
                "kernel_path": self._kernel_path(self._capacity,
                                                 bucket),
                "expand_impl": self._expand_impl()}
            if pkey is not None:
                # Internal riders for process() — popped there before
                # the event reaches the schema'd streams.
                meta["_prof_key"] = pkey
                if prof_s is not None:
                    meta["_prof_s"] = prof_s
            inflight.append((stats_dev, meta))
            if len(inflight) >= self._depth:
                process(inflight.popleft())
        # Retire every launched dispatch (normal exit): their table
        # insertions are real, so dropping their outputs would tear the
        # frontier (states visited but their subtrees never queued). On
        # an error exit the frontier is torn by definition and
        # checkpoint() already refuses (see checkpoint()).
        while inflight:
            process(inflight.popleft())

        self._arena_tail = tail
        self._head = head
        self._fetch_parents(tail)

    # -- Parent log sync ---------------------------------------------------

    def _run(self) -> None:
        try:
            super()._run()
        finally:
            # Wake any _parent_map waiter even if the worker died before
            # its final parent fetch.
            with self._sync_cond:
                self._sync_cond.notify_all()

    def _fetch_parents(self, tail: int) -> None:
        """Appends arena rows [synced, tail) to the parent log (worker
        thread or post-join only). Always bumps the sync generation —
        a waiter must wake even when there was nothing new to fetch."""
        lo = self._synced_rows
        if tail > lo:
            _, fps_a, par_a, _ = self._arena
            child = self._fetch_rows(fps_a, lo, tail - lo)
            parent = self._fetch_rows(par_a, lo, tail - lo)
            with self._lock:
                self._parent_log.append((child, parent))
                self._synced_rows = tail
        with self._sync_cond:
            self._sync_generation += 1
            self._sync_cond.notify_all()

    def _service_sync(self, tail: int) -> None:
        with self._sync_cond:
            wanted = self._sync_requested
            self._sync_requested = False
        if wanted:
            self._fetch_parents(tail)

    def _parent_map(self):
        if (not self._done.is_set()
                and threading.current_thread() is not self._thread):
            # Ask the worker for a parent sync at its next safe point.
            with self._sync_cond:
                self._sync_requested = True
                gen = self._sync_generation
                # A single fused dispatch can exceed any fixed timeout on a
                # slow or tunneled accelerator; falling through early would
                # reconstruct paths from a stale parent log. Re-wait while
                # the worker is alive until the sync generation advances,
                # warning each minute so a wedged device is diagnosable.
                waited = 0.0
                while not self._sync_cond.wait_for(
                        lambda: (self._sync_generation != gen
                                 or self._done.is_set()), timeout=60.0):
                    if not self._thread.is_alive():
                        break
                    waited += 60.0
                    warnings.warn(
                        f"parent-log sync pending for {waited:.0f}s; the "
                        "fused dispatch is still running (slow or wedged "
                        "accelerator) — still waiting", RuntimeWarning)
        if self._error is not None:
            # The worker died mid-dispatch: rows since the last sync are
            # missing from the parent log, and reconstructing from it
            # would raise a misleading NondeterminismError. Surface the
            # real failure instead.
            raise self._error
        return super()._parent_map()

    def _reset_engine_state(self) -> None:
        """restart_from support: drop the failed run's device arena and
        sync bookkeeping (the restarted worker rebuilds both from the
        reloaded pending blocks)."""
        for attr in ("_arena", "_arena_tail", "_head"):
            self.__dict__.pop(attr, None)
        self._slice_cache.clear()
        self._synced_rows = 0
        with self._sync_cond:
            self._sync_requested = False

    # -- Checkpoint hooks --------------------------------------------------

    def _pending_blocks(self) -> list:
        head = getattr(self, "_head", 0)
        tail = getattr(self, "_arena_tail", 0)
        if not hasattr(self, "_arena") or tail <= head:
            return list(self._pending)
        vecs_a, fps_a, _, eb_a = self._arena
        return [(self._fetch_rows(vecs_a, head, tail - head, self._Wrow),
                 self._fetch_rows(fps_a, head, tail - head),
                 self._fetch_rows(eb_a, head, tail - head))]

    def _write_checkpoint(self, path: str) -> None:
        # Snapshot needs the parent log and the frontier; both live on
        # device between dispatches.
        tail = getattr(self, "_arena_tail", 0)
        if hasattr(self, "_arena"):
            self._fetch_parents(tail)
        super()._write_checkpoint(path)

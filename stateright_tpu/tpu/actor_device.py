"""Device compilation of actor models: slot-list networks + action wiring.

The host ``ActorModel`` (`actor/model.rs:205-513`) drives arbitrary Python
handlers over a hash-set network. The device form keeps the same
*semantics* with a fixed-width layout:

- **Network**: the reference's envelope set (`actor/model.rs:69`) becomes a
  bounded, *sorted* slot list of encoded ``uint32`` envelopes padded with
  ``EMPTY_ENV`` (all-ones). Sorted-unique slots are a canonical form of
  the set, so state identity is order-insensitive exactly like the
  reference's ``HashableHashSet`` hashing (`util.rs:123-144`) — for free.
  Inserts are branchless sorted-insert-with-dedup; a full network sets an
  overflow flag lane that the engine surfaces as a hard error (the host
  model has no such bound, so overflow means "raise ``net_slots``").
- **Actions** (`actor/model.rs:238-257`): one action per slot —
  optionally Drop (lossy), then Deliver — plus one Timeout per timer
  actor. Empty slots are invalid actions; the static fan-out is
  ``net_slots * (1 + lossy) + n_timers``.
- **No-op elision** (`actor.rs:232-234`, `actor/model.rs:278`): the
  per-model ``deliver`` hook returns an explicit ``handled`` flag
  mirroring each "return None" branch of the host handler — equality of
  encodings is NOT used, because a handler that returns an equal-but-new
  state still produces a checker action in the reference.

Subclasses implement the per-model ``deliver`` hook (actor dispatch +
history recording + sends) and the host codec; this base builds ``step``.

Dataflow note: ``deliver`` operates on the state's *body* (the lanes
below ``net_offset``) only — the network effect (removal + sends) is a
single sort-merge over ``[net, outs]`` applied here, and the successor
vector is assembled with ONE concatenate per slot. Earlier revisions
threaded the full state vector through the handler and rebuilt it with
chains of ``vec.at[lane].set`` — at batch x fanout that materialized the
full ``[B, E, W]`` tensor ~20 times per wave and dominated expand time
(8.6 us/state staged on the CPU backend, BENCH_r04 wave_breakdown);
component-wise dataflow cuts the full-width materializations to the
final assembly.
"""

from __future__ import annotations

from typing import List

import numpy as np

import jax.numpy as jnp

from .device_model import DeviceModel

__all__ = ["EMPTY_ENV", "ActorDeviceModel", "net_remove_at",
           "compact_envs"]

#: empty network slot — all-ones so real (smaller) envelopes sort first
EMPTY_ENV = np.uint32(0xFFFFFFFF)


def net_remove_at(net, slot):
    """Removes the envelope at ``slot``, shifting left: stays sorted."""
    e = net.shape[0]
    idx = jnp.arange(e)
    shifted = jnp.where(idx < slot, net,
                        net[jnp.minimum(idx + 1, e - 1)])
    return shifted.at[e - 1].set(jnp.uint32(EMPTY_ENV))


def compact_envs(envs, k: int):
    """First ``k`` non-EMPTY envelopes of ``envs`` in original order,
    EMPTY-padded: ``uint32[n] -> uint32[k]``.

    One cumsum + one scatter. The obvious
    ``argsort(envs == EMPTY, stable=True)`` is ~45x slower on the XLA CPU
    backend (per-row sort libcalls), and this sits inside the vmapped
    per-slot delivery — it was a third of ``server_deliver``'s staged
    time before the rewrite.
    """
    nonempty = envs != EMPTY_ENV
    rank = jnp.cumsum(nonempty) - 1
    slot = jnp.where(nonempty & (rank < k), rank, k)
    return (jnp.full((k,), EMPTY_ENV, jnp.uint32)
            .at[slot].set(envs, mode="drop"))


class ActorDeviceModel(DeviceModel):
    """Base class for device forms of ``ActorModel`` systems.

    Subclass contract (class attributes / methods):

    - ``net_slots``: network capacity E (bounds in-flight envelopes)
    - ``net_offset``: lane index where the E network lanes start; the lane
      at ``net_offset + net_slots`` is the overflow flag
    - ``max_out``: max sends per delivery
    - ``duplicating`` / ``lossy``: network semantics
      (`actor/model.rs:54-55`, `actor/model.rs:240-244`)
    - ``deliver(body, env) -> (new_body, handled, outs)``: apply one
      delivery — actor dispatch, history recording (`record_msg_in`
      before sends, matching `actor/model.rs:280-300`) — where ``body``
      is the state's non-network lanes ``vec[:net_offset]``; ``outs`` is
      ``uint32[max_out]`` of envelopes to send (EMPTY_ENV = none).
      ``handled`` False mirrors the host handler's no-op branches.
    - optionally ``n_timers`` + ``timeout(body, actor) -> (new_body,
      handled, outs)`` with the timer bitmask in lane ``timer_offset``
      (which must lie below ``net_offset``).
    """

    net_slots: int
    net_offset: int
    max_out: int
    duplicating: bool = True
    lossy: bool = False
    n_timers: int = 0
    timer_offset: int = -1

    # -- Derived ----------------------------------------------------------

    @property
    def max_fanout(self) -> int:  # type: ignore[override]
        return self.net_slots * (2 if self.lossy else 1) + self.n_timers

    def deliver(self, body, env):
        raise NotImplementedError

    def timeout(self, body, actor: int):
        raise NotImplementedError

    # -- The step program (actor/model.rs:238-327) ------------------------

    def _net_effect(self, net, outs, removed_slot=None):
        """A delivery's network effect: optional removal of the delivered
        slot (non-duplicating, `actor/model.rs:290-297`) plus set-dedup
        insertion of the sends, keeping the slot list sorted (the
        canonical set form state identity relies on). Returns
        ``(new_net, overflow)``.

        All shifts are rank-based selects between the lane vector and a
        one-lane-rotated copy — no sort: ``jnp.sort`` over the merged
        lanes costs ~2x this entire path on the XLA CPU backend (per-row
        libcalls for tiny rows), and the insert rank is just a
        less-than count since the list is sorted.
        """
        e = self.net_slots
        idx = jnp.arange(e)
        if removed_slot is not None:
            # Shift-left past the removed slot; stays sorted.
            nxt = jnp.concatenate(
                [net[1:], jnp.full((1,), EMPTY_ENV, jnp.uint32)])
            net = jnp.where(idx < removed_slot, net, nxt)
        overflow = jnp.zeros((), bool)
        for j in range(self.max_out):
            env = outs[j]
            skip = (env == EMPTY_ENV) | jnp.any(net == env)
            overflow = overflow | (~skip & (net[e - 1] != EMPTY_ENV))
            # Insert at the envelope's rank, shifting the tail right
            # (inserting into a full list drops the largest element).
            pos = jnp.sum((net < env).astype(jnp.int32))
            prev = jnp.concatenate([net[:1], net[:-1]])
            shifted = jnp.where(idx < pos, net,
                                jnp.where(idx == pos, env, prev))
            net = jnp.where(skip, net, shifted)
        return net, overflow

    def step(self, vec):
        import jax

        e = self.net_slots
        off = self.net_offset
        body = vec[:off]
        net = vec[off:off + e]
        err = vec[off + e]

        # One delivery per slot, vmapped: the handler graph is traced
        # ONCE instead of once per slot — compile time of the wave
        # program is proportional to the handler size, not to
        # handler * net_slots (which for the paxos bench config was a
        # ~50x HLO blowup and minutes of XLA time). The handler sees the
        # body component only; the successor vector is assembled with a
        # single concatenate (see the module docstring's dataflow note).
        def deliver_slot(slot):
            env = net[slot]
            new_body, handled, outs = self.deliver(body, env)
            new_net, overflow = self._net_effect(
                net, outs,
                removed_slot=None if self.duplicating else slot)
            new_err = jnp.where(overflow, jnp.uint32(1), err)
            succ = jnp.concatenate([new_body, new_net, new_err[None]])
            return succ, (env != EMPTY_ENV) & handled

        slots = jnp.arange(e)
        d_succ, d_valid = jax.vmap(deliver_slot)(slots)

        if self.lossy:
            # Drop: remove the envelope, nothing else changes
            # (actor/model.rs:262-266).
            def drop_slot(slot):
                return jnp.concatenate(
                    [body, net_remove_at(net, slot), err[None]])

            l_succ = jax.vmap(drop_slot)(slots)
            l_valid = net != EMPTY_ENV
            # Interleave [drop0, deliver0, drop1, deliver1, ...] to keep
            # the host model's per-envelope action order.
            succ = jnp.stack([l_succ, d_succ], axis=1).reshape(
                2 * e, vec.shape[0])
            valid = jnp.stack([l_valid, d_valid], axis=1).reshape(2 * e)
        else:
            succ, valid = d_succ, d_valid

        succs: List = [succ]
        valids: List = [valid]
        for actor in range(self.n_timers):
            timer_set = (body[self.timer_offset] >> actor) & 1
            new_body, handled, outs = self.timeout(body, actor)
            new_net, overflow = self._net_effect(net, outs)
            new_err = jnp.where(overflow, jnp.uint32(1), err)
            succs.append(jnp.concatenate(
                [new_body, new_net, new_err[None]])[None])
            valids.append(((timer_set == 1) & handled)[None])
        if len(succs) == 1:
            return succ, valid
        return jnp.concatenate(succs), jnp.concatenate(valids)

    # -- Host-side network codec ------------------------------------------

    def env_encode(self, envelope) -> int:
        raise NotImplementedError

    def env_decode(self, code: int):
        raise NotImplementedError

    def encode_network(self, network) -> np.ndarray:
        codes = sorted(self.env_encode(env) for env in network)
        if len(codes) > self.net_slots:
            raise ValueError(
                f"network has {len(codes)} in-flight envelopes; device "
                f"encoding bounds it at net_slots={self.net_slots}")
        out = np.full(self.net_slots + 1, EMPTY_ENV, np.uint32)
        out[:len(codes)] = codes
        out[self.net_slots] = 0  # overflow flag lane
        return out

    def decode_network(self, lanes: np.ndarray):
        if int(lanes[self.net_slots]) != 0:
            raise RuntimeError(
                "device network overflow: a state exceeded net_slots "
                f"({self.net_slots}) in-flight envelopes; re-run with a "
                "larger bound")
        return [self.env_decode(int(c)) for c in lanes[:self.net_slots]
                if c != EMPTY_ENV]

"""JAX version compatibility for the sharded engines.

``shard_map`` moved from ``jax.experimental.shard_map`` (kwarg
``check_rep``) to ``jax.shard_map`` (kwarg ``check_vma``) across JAX
releases; the engines call one entry point and let this module resolve
whichever the installed JAX provides. Import errors surface at engine
use, not module import, so a CPU-only install without the experimental
module can still import the package.
"""

from __future__ import annotations

__all__ = ["shard_map"]


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """Dispatches to the installed JAX's shard_map, mapping the
    replication-check kwarg to whichever name this version uses."""
    try:
        from jax import shard_map as _sm  # jax >= 0.6 naming
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_vma)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_vma=check_vma)
